"""Multi-PROCESS test harness: real OS processes, real TCP, barriers.

Reference parity: akka-multi-node-testkit — MultiNodeSpec assigns roles to
separate JVMs with named barriers (remote/testkit/MultiNodeSpec.scala:258,
373,388-401) coordinated by a TestConductor over a control channel
(remote/testconductor/Conductor.scala:56). Here:

- Conductor: a tiny line-protocol TCP server in the test process. Workers
  ENTER named barriers (released when all N arrive), POST json results,
  and the conductor collects exit codes.
- spawn_nodes(): launches N real python processes running a worker script
  with a sanitized environment (CPU jax, no device tunnel), giving each
  its node index and the conductor address.
- node_barrier()/node_result(): called from inside worker scripts.

Fault injection (throttle/blackhole, Conductor.scala:128,148) is applied
in-process by workers on their own TcpTransport.fault_injector — the same
seam the in-proc multi-node harness uses.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence


class Conductor:
    """Barrier + result collection server (one per test)."""

    def __init__(self, n_nodes: int):
        self.n_nodes = n_nodes
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(n_nodes * 4)
        self.port = self._srv.getsockname()[1]
        self._lock = threading.Lock()
        self._barriers: Dict[str, List[socket.socket]] = {}
        self.results: Dict[int, Any] = {}
        self._stop = threading.Event()
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="akka-tpu-conductor").start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            buf = b""
            while not self._stop.is_set():
                while b"\n" not in buf:
                    chunk = conn.recv(4096)
                    if not chunk:
                        return
                    buf += chunk
                line, _, buf = buf.partition(b"\n")
                parts = line.decode("utf-8").split(" ", 2)
                if parts[0] == "ENTER":
                    self._enter(parts[1], conn)
                elif parts[0] == "RESULT":
                    with self._lock:
                        self.results[int(parts[1])] = json.loads(parts[2])
                    conn.sendall(b"OK\n")
        except OSError:
            pass

    def _enter(self, name: str, conn: socket.socket) -> None:
        """Block the caller until n_nodes have entered barrier `name`
        (enterBarrier semantics: all-or-timeout)."""
        release: Optional[List[socket.socket]] = None
        with self._lock:
            waiting = self._barriers.setdefault(name, [])
            waiting.append(conn)
            if len(waiting) >= self.n_nodes:
                release = self._barriers.pop(name)
        if release is not None:
            for c in release:
                try:
                    c.sendall(b"GO\n")
                except OSError:
                    pass
        # non-releasing entrants just wait for GO on their socket (handled
        # client-side); the server keeps the connection open either way

    def shutdown(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass


def sanitized_env(extra: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Child env for worker processes: CPU jax, no device tunnel (a wedged
    TPU tunnel would hang every child at interpreter start), repo on path."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=2")
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    if extra:
        env.update(extra)
    return env


def spawn_nodes(worker_source: str, n_nodes: int,
                timeout: float = 120.0,
                extra_env: Optional[Dict[str, str]] = None):
    """Run `worker_source` in n real processes. The source sees
    AKKA_TPU_NODE_INDEX / AKKA_TPU_NODE_COUNT / AKKA_TPU_CONDUCTOR_PORT
    and uses node_barrier()/node_result(). Returns (results, stderrs).
    Raises on nonzero exit or timeout (with stderr attached). The overall
    timeout dilates with machine load (testkit.dilation) — n extra python
    processes on a busy box legitimately take longer to reach barriers."""
    from .dilation import dilated
    timeout = dilated(timeout)
    conductor = Conductor(n_nodes)
    procs: List[subprocess.Popen] = []
    drains: List[threading.Thread] = []
    outs: List[Dict[str, bytes]] = []
    try:
        for i in range(n_nodes):
            env = sanitized_env(extra_env)
            env["AKKA_TPU_NODE_INDEX"] = str(i)
            env["AKKA_TPU_NODE_COUNT"] = str(n_nodes)
            env["AKKA_TPU_CONDUCTOR_PORT"] = str(conductor.port)
            p = subprocess.Popen(
                [sys.executable, "-u", "-c", worker_source],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env)
            procs.append(p)
            # drain BOTH pipes concurrently for EVERY node: a verbose
            # worker blocked on a full pipe would otherwise never reach
            # its barrier and stall the whole group until timeout
            cap: Dict[str, bytes] = {"out": b"", "err": b""}
            outs.append(cap)

            def _drain(stream, key, cap=cap):
                cap[key] = stream.read()

            for stream, key in ((p.stdout, "out"), (p.stderr, "err")):
                t = threading.Thread(target=_drain, args=(stream, key),
                                     daemon=True)
                t.start()
                drains.append(t)
        deadline = time.monotonic() + timeout
        stderrs: List[str] = []
        for i, p in enumerate(procs):
            left = max(1.0, deadline - time.monotonic())
            try:
                p.wait(timeout=left)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                for t in drains:
                    t.join(2.0)
                raise AssertionError(
                    f"node {i} timed out after {timeout}s\n"
                    f"--- node {i} stderr ---\n"
                    f"{outs[i]['err'].decode()[-4000:]}")
        for t in drains:
            t.join(5.0)
        for i, p in enumerate(procs):
            stderrs.append(outs[i]["err"].decode())
            if p.returncode != 0:
                raise AssertionError(
                    f"node {i} exited {p.returncode}\n"
                    f"--- node {i} stderr ---\n{outs[i]['err'].decode()[-4000:]}\n"
                    f"--- node {i} stdout ---\n{outs[i]['out'].decode()[-2000:]}")
        return dict(conductor.results), stderrs
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        conductor.shutdown()


# ----------------------------------------------------------- worker helpers
_conductor_conn: Optional[socket.socket] = None
_conn_lock = threading.Lock()


def _conn() -> socket.socket:
    global _conductor_conn
    with _conn_lock:
        if _conductor_conn is None:
            port = int(os.environ["AKKA_TPU_CONDUCTOR_PORT"])
            _conductor_conn = socket.create_connection(("127.0.0.1", port),
                                                       timeout=30.0)
        return _conductor_conn


def node_index() -> int:
    return int(os.environ["AKKA_TPU_NODE_INDEX"])


def node_count() -> int:
    return int(os.environ["AKKA_TPU_NODE_COUNT"])


def node_barrier(name: str, timeout: float = 60.0) -> None:
    """enterBarrier(name) — blocks until every node has entered."""
    c = _conn()
    c.sendall(f"ENTER {name}\n".encode())
    c.settimeout(timeout)
    buf = b""
    while b"\n" not in buf:
        chunk = c.recv(64)
        if not chunk:
            raise RuntimeError(f"conductor died in barrier {name!r}")
        buf += chunk
    if not buf.startswith(b"GO"):
        raise RuntimeError(f"barrier {name!r}: unexpected {buf!r}")


def node_result(value: Any) -> None:
    """Report this node's result dict to the test process."""
    c = _conn()
    c.sendall(f"RESULT {node_index()} {json.dumps(value)}\n".encode())
    c.settimeout(30.0)
    buf = b""
    while b"\n" not in buf:
        chunk = c.recv(16)
        if not chunk:
            raise RuntimeError("conductor died in result post")
        buf += chunk
