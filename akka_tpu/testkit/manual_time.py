"""ManualTime: install the virtual-time scheduler into a live system.

Reference parity: akka-actor-testkit-typed ManualTime / akka-testkit
ExplicitlyTriggeredScheduler.scala — the scheduler itself lives in
akka_tpu.actor.scheduler.ExplicitlyTriggeredScheduler; this helper swaps it
into a freshly created ActorSystem (the reference does it via config).
"""

from __future__ import annotations

from ..actor.scheduler import ExplicitlyTriggeredScheduler

ManualTimeScheduler = ExplicitlyTriggeredScheduler


def install_manual_time(system) -> ExplicitlyTriggeredScheduler:
    """Replace a live system's scheduler with virtual time. Call right after
    ActorSystem.create, before any actor schedules a timer."""
    old = system.scheduler
    manual = ExplicitlyTriggeredScheduler()
    system.scheduler = manual
    old.shutdown()
    return manual
