"""Multi-node testkit: N role-named systems in one process, with barriers and
link fault injection.

Reference parity: akka-multi-node-testkit — MultiNodeSpec roles + runOn +
enterBarrier (remote/testkit/MultiNodeSpec.scala:258,373,388-401) and the
TestConductor's throttle/blackhole/passThrough/disconnect/shutdown
(remote/testconductor/Conductor.scala:128,148,177,188,230-239). The reference
runs one JVM per role on one machine; we run one ActorSystem per role in one
process over the fault-injectable InProcTransport — the same fidelity point
(real serialization + real transport hops, no real network). TPU-wise this is
the host-control-plane analogue of simulating a multi-chip mesh with
xla_force_host_platform_device_count (see tests/conftest.py).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..actor.system import ActorSystem
from ..remote.transport import InProcTransport


class BarrierTimeout(AssertionError):
    pass


class TestConductor:
    """Link-level fault injection between roles (reference:
    remote/testconductor/Conductor.scala)."""

    def __init__(self, kit: "MultiNodeKit"):
        self._kit = kit
        self._fi = InProcTransport.fault_injector

    def _addr(self, role: str) -> str:
        return self._kit.transport_address(role)

    def blackhole(self, from_role: str, to_role: str, both: bool = True) -> None:
        self._fi.blackhole(self._addr(from_role), self._addr(to_role))
        if both:
            self._fi.blackhole(self._addr(to_role), self._addr(from_role))

    def throttle(self, from_role: str, to_role: str,
                 rate_msgs_per_sec: float) -> None:
        self._fi.throttle(self._addr(from_role), self._addr(to_role),
                          rate_msgs_per_sec)

    def pass_through(self, from_role: str, to_role: str, both: bool = True) -> None:
        self._fi.pass_through(self._addr(from_role), self._addr(to_role))
        if both:
            self._fi.pass_through(self._addr(to_role), self._addr(from_role))

    disconnect = blackhole

    def shutdown(self, role: str) -> None:
        """Hard-kill a node: transport drops first (no graceful goodbye), then
        the system dies (reference: Conductor.shutdown :230-239)."""
        system = self._kit.systems.pop(role, None)
        if system is None:
            return
        system.provider.shutdown_transport()
        system.terminate()
        system.await_termination(10.0)

    def reset(self) -> None:
        self._fi.reset()


class MultiNodeKit:
    """Spin up one remote-enabled ActorSystem per role.

    kit = MultiNodeKit(["first", "second", "third"])
    kit.run({"first": fn_a, "second": fn_b})   # concurrent, with barriers
    kit.conductor.blackhole("first", "second")
    """

    def __init__(self, roles: Sequence[str],
                 config: Optional[dict] = None,
                 config_per_role: Optional[Dict[str, dict]] = None,
                 name_prefix: str = "multi"):
        self.roles = list(roles)
        self.systems: Dict[str, ActorSystem] = {}
        self.conductor = TestConductor(self)
        self._barriers: Dict[str, threading.Barrier] = {}
        self._barrier_lock = threading.Lock()
        self._parties = 0
        base = config or {}
        for role in self.roles:
            overrides = _deep_merge(
                {"akka": {"actor": {"provider": "remote"},
                          "stdout-loglevel": "ERROR", "log-dead-letters": 0,
                          "remote": {"transport": "inproc",
                                     "canonical": {"hostname": "local", "port": 0}}}},
                _deep_merge(base, (config_per_role or {}).get(role, {})))
            self.systems[role] = ActorSystem.create(f"{name_prefix}-{role}", overrides)

    # -- addressing -----------------------------------------------------------
    def system(self, role: str) -> ActorSystem:
        return self.systems[role]

    def address(self, role: str) -> str:
        """akka://name@host:port — for actor_selection across nodes."""
        s = self.systems[role]
        a = s.provider.local_address
        return f"akka://{s.name}@{a.host}:{a.port}"

    def transport_address(self, role: str) -> str:
        a = self.systems[role].provider.local_address
        return f"{a.host}:{a.port}"

    def node(self, role: str, path: str):
        """Resolve /user/... on another role from... any system (first role's)."""
        return self.address(role) + path

    # -- concurrent role code + barriers --------------------------------------
    def run(self, fns_by_role: Dict[str, Callable[["NodeHandle"], Any]],
            timeout: float = 30.0) -> Dict[str, Any]:
        """Run each role's fn concurrently (reference: runOn scoping). Each fn
        receives a NodeHandle exposing enter_barrier. Re-raises the first
        failure."""
        self._parties = len(fns_by_role)
        self._barriers.clear()
        results: Dict[str, Any] = {}
        errors: List[BaseException] = []

        def _runner(role: str, fn):
            try:
                results[role] = fn(NodeHandle(self, role))
            except BaseException as e:  # noqa: BLE001
                errors.append(e)
                self._abort_barriers()

        threads = [threading.Thread(target=_runner, args=(r, f),
                                    name=f"multi-node-{r}", daemon=True)
                   for r, f in fns_by_role.items()]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout)
            if t.is_alive():
                self._abort_barriers()
                raise BarrierTimeout(f"role thread {t.name} did not finish in {timeout}s")
        if errors:
            raise errors[0]
        return results

    def _barrier(self, name: str) -> threading.Barrier:
        with self._barrier_lock:
            if name not in self._barriers:
                self._barriers[name] = threading.Barrier(self._parties)
            return self._barriers[name]

    def _abort_barriers(self) -> None:
        with self._barrier_lock:
            for b in self._barriers.values():
                b.abort()

    def enter_barrier(self, name: str, timeout: float = 20.0) -> None:
        try:
            self._barrier(name).wait(timeout)
        except threading.BrokenBarrierError:
            raise BarrierTimeout(f"barrier [{name}] broken/timed out")

    # -- lifecycle ------------------------------------------------------------
    def shutdown(self) -> None:
        for system in self.systems.values():
            system.terminate()
        for system in self.systems.values():
            system.await_termination(10.0)
        self.systems.clear()
        self.conductor.reset()

    def __enter__(self) -> "MultiNodeKit":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()


class NodeHandle:
    """What a role's fn receives inside MultiNodeKit.run."""

    def __init__(self, kit: MultiNodeKit, role: str):
        self.kit = kit
        self.role = role
        self.system = kit.systems[role]

    def enter_barrier(self, name: str, timeout: float = 20.0) -> None:
        self.kit.enter_barrier(name, timeout)


def _deep_merge(base: dict, over: dict) -> dict:
    out = dict(base)
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out
