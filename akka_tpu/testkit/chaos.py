"""Deterministic fault injection for the batched device runtime.

Supervision code paths only fire when something breaks, and "something
breaks" must be REPRODUCIBLE to be testable: the chaos decisions here are
pure functions of (seed, step, lane) — no RNG state threads through the
scan carry, no host randomness, and the SAME seed produces the SAME fault
schedule on every delivery backend, every platform, and in a plain numpy
loop. That last property is what the parity suite leans on
(tests/test_supervision.py): an un-jitted oracle replays the exact fault
schedule the jitted chaos behavior saw, so the supervision counters can be
asserted EQUAL, not approximately equal.

The primitive is an integer hash (murmur3 finalizer over the packed
(seed, step, lane) words): `chaos_hash` is the jnp form used inside
jitted behaviors, `chaos_hit`/`chaos_hit_np` the bit-identical
jnp/numpy rate tests built on it (`chaos_uniform_np` maps the hash to
[0, 1) for oracles that want a float). Fault kinds are composable masks
over lanes:

  crash_mask        lane raises `_failed` this step (let-it-crash input)
  nan_mask          lane's state column is corrupted to NaN (pairs with
                    BatchedBehavior.nonfinite_guard)
  drop_mask         the lane's emissions this step are suppressed
  dup_mask          the lane's slot-0 emission is duplicated into the
                    last emit slot

`inject(behavior, ...)` wraps a BatchedBehavior with any subset of these,
returning a new behavior whose receive applies the faults AFTER the
wrapped receive runs — the wrapped behavior never observes the chaos,
exactly like a fault striking between two mailbox dequeues.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..batched.behavior import BatchedBehavior, Emit

# murmur3 fmix32 constants — chosen for avalanche, not secrecy; any
# fixed integer mixer with good bit diffusion works here
_C1 = 0x85EBCA6B
_C2 = 0xC2B2AE35
_MASK32 = 0xFFFFFFFF


def _fmix32_np(h) -> np.ndarray:
    # all arithmetic in uint64 with explicit 32-bit masking: no reliance
    # on numpy promotion rules (which changed across NEP 50) or on
    # wraparound-overflow behavior for the multiplies
    h = np.asarray(h, np.uint64) & np.uint64(_MASK32)
    h = h ^ (h >> np.uint64(16))
    h = (h * np.uint64(_C1)) & np.uint64(_MASK32)
    h = h ^ (h >> np.uint64(13))
    h = (h * np.uint64(_C2)) & np.uint64(_MASK32)
    return h ^ (h >> np.uint64(16))


def chaos_uniform_np(seed: int, step, lane, salt: int = 0) -> np.ndarray:
    """numpy twin of chaos_uniform — bit-identical u32 hash, mapped to
    [0, 1) as float64 (exact: 32-bit numerator, power-of-two divisor)."""
    step = np.asarray(step, np.uint32)
    lane = np.asarray(lane, np.uint32)
    h = np.uint32(seed & _MASK32) ^ np.uint32((salt * 0x9E3779B9) & _MASK32)
    h = _fmix32_np((h.astype(np.uint64) + step.astype(np.uint64)
                    * np.uint64(0x85EBCA77)) & _MASK32)
    h = _fmix32_np((h.astype(np.uint64) + lane.astype(np.uint64)
                    * np.uint64(0xC2B2AE3D)) & _MASK32)
    return h.astype(np.float64) / float(1 << 32)


def chaos_hash(seed: int, step, lane, salt: int = 0):
    """Deterministic per-(step, lane) u32 hash: pure integer arithmetic
    in uint32 (bit-stable across backends/platforms — no float-order
    sensitivity), finalized with the murmur3 mixer. `salt` decorrelates
    independent fault kinds sharing one seed. Compare against
    `_rate_threshold(rate)` rather than dividing: f32 rounding of h/2^32
    is not bit-stable enough for a parity contract."""
    def fmix(h):
        h = h ^ (h >> 16)
        h = h * jnp.uint32(_C1)
        h = h ^ (h >> 13)
        h = h * jnp.uint32(_C2)
        return h ^ (h >> 16)

    h = jnp.uint32(seed & _MASK32) ^ jnp.uint32((salt * 0x9E3779B9) & _MASK32)
    h = fmix(h + jnp.asarray(step).astype(jnp.uint32) * jnp.uint32(0x85EBCA77))
    h = fmix(h + jnp.asarray(lane).astype(jnp.uint32) * jnp.uint32(0xC2B2AE3D))
    return h


def _rate_threshold(rate: float) -> int:
    """rate in [0, 1] -> u32 threshold. Shared quantization for the jnp
    and numpy sides: hash < threshold  <=>  uniform < rate."""
    return min(int(round(rate * float(1 << 32))), 1 << 32)


def chaos_hit(seed: int, step, lane, rate: float, salt: int = 0):
    """jnp bool: does the (step, lane) cell fire at `rate`?"""
    thr = _rate_threshold(rate)
    if thr <= 0:
        return jnp.zeros(jnp.shape(jnp.asarray(lane)), jnp.bool_) \
            if jnp.ndim(jnp.asarray(lane)) else jnp.asarray(False)
    if thr >= (1 << 32):
        return jnp.ones(jnp.shape(jnp.asarray(lane)), jnp.bool_) \
            if jnp.ndim(jnp.asarray(lane)) else jnp.asarray(True)
    return chaos_hash(seed, step, lane, salt) < jnp.uint32(thr)


def chaos_hit_np(seed: int, step, lane, rate: float, salt: int = 0):
    """numpy twin of chaos_hit — the oracle's fault schedule."""
    thr = _rate_threshold(rate)
    if thr <= 0:
        return np.zeros(np.shape(lane), np.bool_)
    if thr >= (1 << 32):
        return np.ones(np.shape(lane), np.bool_)
    step = np.asarray(step, np.uint32)
    lane = np.asarray(lane, np.uint32)
    h = np.uint32(seed & _MASK32) ^ np.uint32((salt * 0x9E3779B9) & _MASK32)
    h = _fmix32_np((h.astype(np.uint64) + step.astype(np.uint64)
                    * np.uint64(0x85EBCA77)) & _MASK32)
    h = _fmix32_np((h.astype(np.uint64) + lane.astype(np.uint64)
                    * np.uint64(0xC2B2AE3D)) & _MASK32)
    return h.astype(np.uint64) < np.uint64(thr)


# salts decorrelating the fault kinds (shared with oracles). LOSS/STALL
# key on (step, shard) instead of (step, lane): they model DEVICE faults
# (preemption, hung dispatch), not actor faults
CRASH_SALT, NAN_SALT, DROP_SALT, DUP_SALT = 1, 2, 3, 4
LOSS_SALT, STALL_SALT = 5, 6


def loss_schedule(seed: int, steps: int, n_shards: int, rate: float,
                  salt: int = LOSS_SALT):
    """jnp [steps, n_shards] bool: does shard s suffer a device fault at
    step t? Same murmur3 schedule primitive as the actor-fault masks, so
    the SAME seed yields the SAME loss schedule on every backend — the
    failover parity suite replays it against a numpy twin."""
    step = jnp.repeat(jnp.arange(steps, dtype=jnp.uint32), n_shards)
    shard = jnp.tile(jnp.arange(n_shards, dtype=jnp.uint32), steps)
    return chaos_hit(seed, step, shard, rate, salt).reshape(steps, n_shards)


def loss_schedule_np(seed: int, steps: int, n_shards: int, rate: float,
                     salt: int = LOSS_SALT) -> np.ndarray:
    """numpy twin of loss_schedule — bit-identical by the chaos_hit
    contract."""
    step = np.repeat(np.arange(steps, dtype=np.uint32), n_shards)
    shard = np.tile(np.arange(n_shards, dtype=np.uint32), steps)
    return chaos_hit_np(seed, step, shard, rate, salt).reshape(
        steps, n_shards)


class DeviceLossInjector:
    """Deterministic device-loss/stall injection for the MeshSentinel
    (batched/sentinel.py).

    A real shard loss is invisible to the host except through SILENCE: the
    device stops completing programs, so the shard's attention row — its
    heartbeat — stops advancing. This injector reproduces exactly that
    signature on a healthy simulation mesh: it rewrites the HOST-OBSERVED
    copy of the per-shard attention words ([n_shards, ATT_WORDS]), freezing
    a chaos-chosen shard's row at its last pre-fault observation. Device
    state is never touched, which gives the quiet-path guarantee for free:
    with `enabled=False` (or zero rates) the filter is the identity and the
    run is bit-identical to an uninjected one — asserted, not assumed, by
    tests/test_failover.py on both delivery backends.

    Two fault kinds, both keyed on the murmur3 (step, shard) schedule:

      loss_rate   permanent — the shard dies at its first scheduled step
                  and its row freezes forever (preemption)
      stall_rate  transient — the row freezes for `stall_steps` observed
                  steps, then thaws (GC pause / slow collective): long
                  enough stalls trip the detector exactly like a loss,
                  short ones only dent phi
    """

    def __init__(self, seed: int, n_shards: int, loss_rate: float = 0.0,
                 stall_rate: float = 0.0, stall_steps: int = 4,
                 enabled: bool = True):
        self.seed = int(seed)
        self.n_shards = int(n_shards)
        self.loss_rate = float(loss_rate)
        self.stall_rate = float(stall_rate)
        self.stall_steps = int(stall_steps)
        self.enabled = bool(enabled)
        self._loss_at = {}        # shard -> first scheduled loss step
        self._loss_scanned = 0    # steps [0, _loss_scanned) already hashed
        self._frozen = {}         # shard -> frozen attention row (np copy)
        self._prev = {}           # shard -> last observed row (np copy)

    def lost_at(self, shard: int, upto_step: int):
        """First scheduled loss step for `shard` that is <= upto_step, or
        None. Pure function of (seed, schedule) — the parity tests use it
        to predict WHEN the sentinel must fail over."""
        if self.loss_rate <= 0.0:
            return None
        if upto_step >= self._loss_scanned:
            steps = np.arange(self._loss_scanned, upto_step + 1,
                              dtype=np.uint32)
            for s in range(self.n_shards):
                if s in self._loss_at:
                    continue
                hits = chaos_hit_np(self.seed, steps,
                                    np.full_like(steps, s),
                                    self.loss_rate, LOSS_SALT)
                idx = np.nonzero(hits)[0]
                if idx.size:
                    self._loss_at[s] = int(steps[idx[0]])
            self._loss_scanned = upto_step + 1
        at = self._loss_at.get(shard)
        return at if at is not None and at <= upto_step else None

    def _stalled(self, shard: int, step: int) -> bool:
        if self.stall_rate <= 0.0:
            return False
        lo = max(0, step - self.stall_steps + 1)
        steps = np.arange(lo, step + 1, dtype=np.uint32)
        return bool(chaos_hit_np(self.seed, steps, np.full_like(steps, shard),
                                 self.stall_rate, STALL_SALT).any())

    def filter_attention(self, att: np.ndarray) -> np.ndarray:
        """Apply the fault schedule to one host-observed attention fetch.
        Rows of lost/stalled shards are replaced by their last healthy
        observation (frozen heartbeat); everything else passes through
        untouched. Identity when disabled."""
        if not self.enabled or (self.loss_rate <= 0.0
                                and self.stall_rate <= 0.0):
            return att
        att = np.array(att, copy=True).reshape(-1, att.shape[-1])
        from ..batched.supervision import ATT_STEP
        for s in range(min(self.n_shards, att.shape[0])):
            step = int(att[s, ATT_STEP])
            dead = self.lost_at(s, step) is not None
            if dead or self._stalled(s, step):
                if s not in self._frozen:
                    # freeze at the last observation BEFORE the fault (the
                    # dying step's completion never reaches the host); a
                    # shard lost before its first drain reports zeros
                    self._frozen[s] = self._prev.get(
                        s, np.zeros_like(att[s]))
                att[s] = self._frozen[s]
            else:
                self._frozen.pop(s, None)  # stall window over: thaw
                self._prev[s] = att[s].copy()
        return att


def inject(target: BatchedBehavior, seed: int, crash_rate: float = 0.0,
           nan_rate: float = 0.0, drop_rate: float = 0.0,
           dup_rate: float = 0.0,
           nan_col: Optional[str] = None) -> BatchedBehavior:
    """Wrap a BatchedBehavior with deterministic fault injection.

    Faults apply AFTER the wrapped receive, keyed on (seed, ctx.step,
    ctx.actor_id) — reproducible, backend-independent, oracle-replayable:

      crash_rate  raise `_failed` — the runtime treats it exactly like a
                  poisoned receive (step.py per_actor): the lane's state
                  update this step is DISCARDED, its emissions are
                  suppressed, and the supervisor resolves the failure in
                  the same jitted pass
      nan_rate    overwrite `nan_col` (default: first inexact state
                  column) with NaN — use with nonfinite_guard=True to
                  exercise the guard, or without to watch NaN propagate
      drop_rate   suppress ALL of the lane's emissions this step
      dup_rate    copy the slot-0 emission into the LAST emit slot
                  (duplicate delivery; needs out_degree >= 2 to differ)

    The returned behavior shares the target's state spec (plus `_failed`
    when crashes are injected) so it can replace the target 1:1.
    """
    if nan_rate > 0:
        col = nan_col
        if col is None:
            for c, (_, dt) in target.state_spec.items():
                if jnp.issubdtype(jnp.dtype(dt), jnp.inexact):
                    col = c
                    break
        if col is None:
            raise ValueError("nan_rate > 0 needs an inexact state column")
        if col not in target.state_spec:
            raise KeyError(f"unknown nan_col {col!r}")
        nan_col = col

    spec = dict(target.state_spec)
    if crash_rate > 0:
        spec.setdefault("_failed", ((), jnp.bool_))
    inner = target.receive

    def receive(state_row, delivered, ctx):
        new_state, emit = inner(state_row, delivered, ctx)
        lane = ctx.actor_id
        if crash_rate > 0:
            hit = chaos_hit(seed, ctx.step, lane, crash_rate, CRASH_SALT)
            new_state = dict(new_state)
            new_state["_failed"] = new_state.get(
                "_failed", jnp.asarray(False)) | hit
        if nan_rate > 0:
            hit = chaos_hit(seed, ctx.step, lane, nan_rate, NAN_SALT)
            new_state = dict(new_state)
            v = jnp.asarray(new_state[nan_col])
            new_state[nan_col] = jnp.where(hit, jnp.full_like(v, jnp.nan), v)
        if drop_rate > 0 or dup_rate > 0:
            emit = emit.with_type()
            if dup_rate > 0:
                hit = chaos_hit(seed, ctx.step, lane, dup_rate, DUP_SALT)
                dup = hit & emit.valid[0]
                emit = Emit(
                    dst=emit.dst.at[-1].set(
                        jnp.where(dup, emit.dst[0], emit.dst[-1])),
                    payload=emit.payload.at[-1].set(
                        jnp.where(dup, emit.payload[0], emit.payload[-1])),
                    valid=emit.valid.at[-1].set(
                        jnp.where(dup, True, emit.valid[-1])),
                    type=emit.type.at[-1].set(
                        jnp.where(dup, emit.type[0], emit.type[-1])))
            if drop_rate > 0:
                hit = chaos_hit(seed, ctx.step, lane, drop_rate, DROP_SALT)
                emit = Emit(dst=jnp.where(hit, -1, emit.dst),
                            payload=emit.payload,
                            valid=emit.valid & ~hit,
                            type=emit.type)
        return new_state, emit

    return dataclasses.replace(target, state_spec=spec, receive=receive)
