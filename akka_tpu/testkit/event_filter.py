"""LoggingTestKit: assert on log events published to the event stream.

Reference parity: akka-actor-testkit-typed LoggingTestKit / classic
EventFilter (akka-testkit/.../TestEventListener.scala) — intercept LogEvents,
count matches, optionally mute them from stdout while the block runs.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, List, Optional, Type

from ..event.logging import Debug, Error, Info, LogEvent, Warning


class LoggingTestKit:
    """Context manager that records matching LogEvents.

    with LoggingTestKit.error("boom", occurrences=1).expect(system):
        ref.tell("explode")
    """

    def __init__(self, level: Optional[Type[LogEvent]] = None,
                 message_contains: str = "", occurrences: int = 1,
                 custom: Optional[Callable[[LogEvent], bool]] = None):
        self._level = level
        self._contains = message_contains
        self._occurrences = occurrences
        self._custom = custom
        self._matched: List[LogEvent] = []
        self._event = threading.Event()
        self._system = None

    # -- factories (reference: LoggingTestKit.error/warn/info/debug) ---------
    @staticmethod
    def error(message_contains: str = "", occurrences: int = 1) -> "LoggingTestKit":
        return LoggingTestKit(Error, message_contains, occurrences)

    @staticmethod
    def warn(message_contains: str = "", occurrences: int = 1) -> "LoggingTestKit":
        return LoggingTestKit(Warning, message_contains, occurrences)

    @staticmethod
    def info(message_contains: str = "", occurrences: int = 1) -> "LoggingTestKit":
        return LoggingTestKit(Info, message_contains, occurrences)

    @staticmethod
    def debug(message_contains: str = "", occurrences: int = 1) -> "LoggingTestKit":
        return LoggingTestKit(Debug, message_contains, occurrences)

    @staticmethod
    def custom(fn: Callable[[LogEvent], bool], occurrences: int = 1) -> "LoggingTestKit":
        return LoggingTestKit(custom=fn, occurrences=occurrences)

    # -- matching -------------------------------------------------------------
    def _matches(self, event: LogEvent) -> bool:
        if self._custom is not None:
            return self._custom(event)
        if self._level is not None and not isinstance(event, self._level):
            return False
        return self._contains in str(event.message)

    def _on_event(self, event: Any) -> None:
        if isinstance(event, LogEvent) and self._matches(event):
            self._matched.append(event)
            if len(self._matched) >= self._occurrences:
                self._event.set()

    # -- use ------------------------------------------------------------------
    def expect(self, system) -> "LoggingTestKit":
        self._system = system
        return self

    def __enter__(self) -> "LoggingTestKit":
        if self._system is None:
            raise RuntimeError("call .expect(system) before entering")
        self._system.event_stream.subscribe(self._on_event, LogEvent)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        try:
            if exc_type is None and not self._event.wait(3.0):
                raise AssertionError(
                    f"expected {self._occurrences} matching log event(s), "
                    f"saw {len(self._matched)}")
        finally:
            self._system.event_stream.unsubscribe(self._on_event)

    @property
    def matched(self) -> List[LogEvent]:
        return list(self._matched)
