"""TestProbe + TestKit assertions for async single-process tests.

Reference parity: akka-testkit/src/main/scala/akka/testkit/TestKit.scala —
`expectMsg`/`expectMsgClass`/`expectNoMessage`/`fishForMessage`/`awaitAssert`
(:244-319 area), time dilation via `akka.test.timefactor`, `watch` +
`expectTerminated`; TestProbe (TestKit.scala TestProbe factory).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterable, Optional, Sequence, Type

from ..actor.actor import Actor
from ..actor.messages import Terminated
from ..actor.props import Props
from ..actor.ref import ActorRef


class _ProbeActor(Actor):
    def __init__(self, q: "queue.Queue[tuple[Any, Any]]"):
        super().__init__()
        self._q = q

    def receive(self, message):
        self._q.put((message, self.sender))


class AssertionFailure(AssertionError):
    pass


class TestProbe:
    """A queue-backed actor you can make assertions against.

    All timeouts are dilated by `akka.test.timefactor` from the system config
    (reference: TestKit.scala `dilated`).
    """

    _count = 0
    _count_lock = threading.Lock()

    def __init__(self, system, name: Optional[str] = None):
        self.system = system
        self._queue: "queue.Queue[tuple[Any, Any]]" = queue.Queue()
        if name is None:
            with TestProbe._count_lock:
                TestProbe._count += 1
                name = f"testProbe-{TestProbe._count}"
        self.ref: ActorRef = system.actor_of(
            Props.create(_ProbeActor, self._queue), name)
        self._last_sender: Optional[ActorRef] = None
        self._timefactor = float(
            system.settings.config.get("akka.test.timefactor", 1.0) or 1.0)
        self._default_timeout = system.settings.config.get_duration(
            "akka.test.single-expect-default", "3s")

    # -- timing ---------------------------------------------------------------
    def dilated(self, timeout: Optional[float]) -> float:
        if timeout is None:
            timeout = self._default_timeout
        return timeout * self._timefactor

    # -- sending --------------------------------------------------------------
    def send(self, target: ActorRef, message: Any) -> None:
        target.tell(message, self.ref)

    def reply(self, message: Any) -> None:
        if self._last_sender is None:
            raise AssertionFailure("no last sender to reply to")
        self._last_sender.tell(message, self.ref)

    def forward(self, target: ActorRef, message: Any) -> None:
        target.tell(message, self._last_sender)

    @property
    def last_sender(self) -> Optional[ActorRef]:
        return self._last_sender

    # -- watching -------------------------------------------------------------
    def watch(self, ref: ActorRef) -> ActorRef:
        self.ref.cell.watch(ref)
        return ref

    def unwatch(self, ref: ActorRef) -> ActorRef:
        self.ref.cell.unwatch(ref)
        return ref

    # -- receiving ------------------------------------------------------------
    def _next(self, timeout: Optional[float]) -> tuple[Any, Any]:
        try:
            msg, sender = self._queue.get(timeout=self.dilated(timeout))
        except queue.Empty:
            raise AssertionFailure(
                f"timeout ({self.dilated(timeout):.1f}s) while waiting for a message")
        self._last_sender = sender
        return msg, sender

    def receive_one(self, timeout: Optional[float] = None) -> Any:
        return self._next(timeout)[0]

    def receive_n(self, n: int, timeout: Optional[float] = None) -> list:
        deadline = time.monotonic() + self.dilated(timeout)
        out = []
        for _ in range(n):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise AssertionFailure(
                    f"timeout receiving {n} messages; got {len(out)}")
            try:
                msg, sender = self._queue.get(timeout=remaining)
            except queue.Empty:
                raise AssertionFailure(
                    f"timeout receiving {n} messages; got {len(out)}")
            self._last_sender = sender
            out.append(msg)
        return out

    def expect_msg(self, expected: Any, timeout: Optional[float] = None) -> Any:
        msg, _ = self._next(timeout)
        if msg != expected:
            raise AssertionFailure(f"expected {expected!r}, got {msg!r}")
        return msg

    def expect_msg_class(self, cls: Type, timeout: Optional[float] = None) -> Any:
        msg, _ = self._next(timeout)
        if not isinstance(msg, cls):
            raise AssertionFailure(f"expected {cls.__name__}, got {msg!r}")
        return msg

    def expect_msg_any_of(self, *candidates: Any, timeout: Optional[float] = None) -> Any:
        msg, _ = self._next(timeout)
        if msg not in candidates:
            raise AssertionFailure(f"expected one of {candidates!r}, got {msg!r}")
        return msg

    def expect_msg_all_of(self, *expected: Any, timeout: Optional[float] = None) -> list:
        remaining = list(expected)
        got = []
        deadline = time.monotonic() + self.dilated(timeout)
        while remaining:
            t = deadline - time.monotonic()
            if t <= 0:
                raise AssertionFailure(f"missing {remaining!r}; got {got!r}")
            try:
                msg, sender = self._queue.get(timeout=t)
            except queue.Empty:
                raise AssertionFailure(f"missing {remaining!r}; got {got!r}")
            self._last_sender = sender
            got.append(msg)
            if msg in remaining:
                remaining.remove(msg)
        return got

    def expect_no_message(self, timeout: float = 0.1) -> None:
        try:
            msg, _ = self._queue.get(timeout=self.dilated(timeout))
            raise AssertionFailure(f"expected no message, got {msg!r}")
        except queue.Empty:
            pass

    def expect_terminated(self, ref: ActorRef, timeout: Optional[float] = None) -> Terminated:
        msg = self.expect_msg_class(Terminated, timeout=timeout)
        if msg.actor != ref:
            raise AssertionFailure(f"expected Terminated({ref}), got {msg!r}")
        return msg

    def fish_for_message(self, predicate: Callable[[Any], bool],
                         timeout: Optional[float] = None) -> Any:
        """Skip messages until predicate matches (reference: fishForMessage)."""
        deadline = time.monotonic() + self.dilated(timeout)
        while True:
            t = deadline - time.monotonic()
            if t <= 0:
                raise AssertionFailure("fish_for_message timed out")
            try:
                msg, sender = self._queue.get(timeout=t)
            except queue.Empty:
                raise AssertionFailure("fish_for_message timed out")
            self._last_sender = sender
            if predicate(msg):
                return msg

    def receive_while(self, predicate: Callable[[Any], bool],
                      idle: float = 0.3, max_time: float = 3.0) -> list:
        out = []
        deadline = time.monotonic() + self.dilated(max_time)
        while time.monotonic() < deadline:
            try:
                msg, sender = self._queue.get(timeout=self.dilated(idle))
            except queue.Empty:
                break
            if not predicate(msg):
                # put it back conceptually: reference stops and keeps it for next expect
                self._queue.put((msg, sender))
                break
            self._last_sender = sender
            out.append(msg)
        return out


def await_assert(assertion: Callable[[], Any], max_time: float = 3.0,
                 interval: float = 0.05) -> Any:
    """Poll an assertion until it passes (reference: TestKit.awaitAssert)."""
    deadline = time.monotonic() + max_time
    last: Optional[BaseException] = None
    while time.monotonic() < deadline:
        try:
            return assertion()
        except BaseException as e:  # noqa: BLE001
            last = e
            time.sleep(interval)
    try:
        return assertion()
    except BaseException as e:  # noqa: BLE001
        raise AssertionFailure(f"await_assert never passed within {max_time}s: {e!r}") from (last or e)


def await_condition(condition: Callable[[], bool], max_time: float = 3.0,
                    interval: float = 0.05, message: str = "") -> None:
    deadline = time.monotonic() + max_time
    while time.monotonic() < deadline:
        if condition():
            return
        time.sleep(interval)
    if condition():
        return
    raise AssertionFailure(message or f"condition not met within {max_time}s")
