"""Sharding test helpers shared by in-proc and multi-process suites."""

from __future__ import annotations

from typing import Optional, Set


def region_entity_ids(region, probe, timeout: float = 4.0
                      ) -> Optional[Set[str]]:
    """Poll-safe GetShardRegionState read: drain stale replies first (a
    previous poll's late answer must not desync this one), wait past the
    region's internal per-shard aggregation timeout, and return None on a
    miss so await_condition-style loops retry instead of erroring.

    The reply may legitimately be PARTIAL (the region sends what it has at
    its own timeout) — callers comparing against a full id set must treat
    a short set as 'retry', which the None-or-set contract supports."""
    from .probe import AssertionFailure
    while True:
        try:
            probe.receive_one(0.01)
        except (AssertionError, AssertionFailure):
            break
    from ..sharding import GetShardRegionState
    region.tell(GetShardRegionState(), probe.ref)
    try:
        state = probe.receive_one(timeout)
    except (AssertionError, AssertionFailure):
        return None
    ids: Set[str] = set()
    for shard in state.shards:
        ids |= set(shard.entity_ids)
    return ids
