"""Test infrastructure (reference: akka-testkit, akka-actor-testkit-typed,
akka-multi-node-testkit — SURVEY.md §4)."""

from .probe import (TestProbe, AssertionFailure, await_assert,  # noqa: F401
                    await_condition)
from .behavior_testkit import (BehaviorTestKit, TestInbox, Effect,  # noqa: F401
                               Spawned, SpawnedAnonymous, Stopped, Watched,
                               WatchedWith, Unwatched, Scheduled,
                               ReceiveTimeoutSet, ReceiveTimeoutCancelled,
                               MessageAdapter)
from .manual_time import ManualTimeScheduler, install_manual_time  # noqa: F401
from .event_filter import LoggingTestKit  # noqa: F401
from .sharding import region_entity_ids  # noqa: F401
from .multi_node import (MultiNodeKit, NodeHandle, TestConductor,  # noqa: F401
                         BarrierTimeout)
from .chaos import (chaos_hash, chaos_hit, chaos_hit_np,  # noqa: F401
                    chaos_uniform_np, inject)
