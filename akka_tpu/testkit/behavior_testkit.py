"""Synchronous, deterministic behavior testing: BehaviorTestKit + TestInbox.

Reference parity: akka-actor-testkit-typed/.../internal/BehaviorTestKitImpl.scala
(:26 runs the behavior on the caller thread; :79-107 records Effects), effect
vocabulary from .../scaladsl/Effects.scala (Spawned, Stopped, Watched,
Scheduled, MessageAdapter, ReceiveTimeoutSet, ...), TestInbox from
.../scaladsl/TestInbox.scala. No threads, no dispatchers: receive runs inline
and effects/messages are recorded for assertion — the TPU analogue of testing
a behavior as a pure function.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional

from ..typed.behavior import (Behavior, PostStop, Signal, canonicalize,
                              interpret_message, interpret_signal, is_alive,
                              start)


# -- effects (reference: akka-actor-testkit-typed scaladsl/Effects.scala) ----

@dataclass(frozen=True)
class Effect:
    pass


@dataclass(frozen=True)
class Spawned(Effect):
    behavior: Any
    child_name: str
    ref: Any = None


@dataclass(frozen=True)
class SpawnedAnonymous(Effect):
    behavior: Any
    ref: Any = None


@dataclass(frozen=True)
class Stopped(Effect):
    child_name: str


@dataclass(frozen=True)
class Watched(Effect):
    ref: Any


@dataclass(frozen=True)
class WatchedWith(Effect):
    ref: Any
    message: Any


@dataclass(frozen=True)
class Unwatched(Effect):
    ref: Any


@dataclass(frozen=True)
class Scheduled(Effect):
    delay: float
    target: Any
    message: Any


@dataclass(frozen=True)
class ReceiveTimeoutSet(Effect):
    timeout: float
    message: Any


@dataclass(frozen=True)
class ReceiveTimeoutCancelled(Effect):
    pass


@dataclass(frozen=True)
class MessageAdapter(Effect):
    fn: Callable[[Any], Any]
    ref: Any


class TestInbox:
    """Captures messages sent to a synthetic ref (reference: TestInbox.scala)."""

    _counter = itertools.count(1)

    def __init__(self, name: Optional[str] = None):
        self.name = name or f"inbox-{next(TestInbox._counter)}"
        self._messages: List[Any] = []
        self.ref = _InboxRef(self)

    def receive_message(self) -> Any:
        if not self._messages:
            raise AssertionError(f"TestInbox {self.name} is empty")
        return self._messages.pop(0)

    def expect_message(self, expected: Any) -> Any:
        msg = self.receive_message()
        if msg != expected:
            raise AssertionError(f"expected {expected!r}, got {msg!r}")
        return msg

    @property
    def has_messages(self) -> bool:
        return bool(self._messages)

    def all_messages(self) -> List[Any]:
        return list(self._messages)

    def clear(self) -> List[Any]:
        out, self._messages = self._messages, []
        return out


class _InboxRef:
    def __init__(self, inbox: TestInbox):
        self._inbox = inbox
        self.path = f"test://{inbox.name}"

    def tell(self, message: Any, sender: Any = None) -> None:
        self._inbox._messages.append(message)

    __call__ = tell

    def __repr__(self):
        return f"TestInboxRef({self._inbox.name})"


class _RecordedCancellable:
    __slots__ = ("is_cancelled",)

    def __init__(self):
        self.is_cancelled = False

    def cancel(self) -> bool:
        if self.is_cancelled:
            return False
        self.is_cancelled = True
        return True


class _StubScheduler:
    """Recording scheduler so Behaviors.with_timers works synchronously."""

    def __init__(self, kit: "BehaviorTestKit"):
        self._kit = kit

    def schedule_once(self, delay: float, fn=None) -> _RecordedCancellable:
        return _RecordedCancellable()

    def schedule_tell_with_fixed_delay(self, initial: float, delay: float,
                                       target: Any, msg: Any) -> _RecordedCancellable:
        self._kit._effects.append(Scheduled(delay, target, msg))
        return _RecordedCancellable()

    schedule_tell_at_fixed_rate = schedule_tell_with_fixed_delay


class _StubSystem:
    def __init__(self, kit: "BehaviorTestKit"):
        self.scheduler = _StubScheduler(kit)
        self.name = "BehaviorTestKit"


class _SyncContext:
    """Duck-typed TypedActorContext recording effects instead of doing them
    (reference: akka-actor-testkit-typed EffectfulActorContext)."""

    def __init__(self, kit: "BehaviorTestKit", name: str):
        self._kit = kit
        self._self_inbox = TestInbox(name)
        self._children: dict = {}
        self._system = _StubSystem(kit)
        self.log = _ListLogger(kit.logs)

    @property
    def self(self) -> Any:  # noqa: A003
        return self._self_inbox.ref

    @property
    def system(self):
        return self._system

    @property
    def children(self):
        return list(self._children.values())

    def child(self, name: str):
        return self._children.get(name)

    def child_inbox(self, name: str) -> Optional[TestInbox]:
        ref = self._children.get(name)
        return ref._inbox if ref is not None else None

    def spawn(self, behavior: Behavior, name: Optional[str] = None, **_kw):
        if name is None:
            return self.spawn_anonymous(behavior)
        inbox = TestInbox(name)
        self._children[name] = inbox.ref
        self._kit._effects.append(Spawned(behavior, name, inbox.ref))
        return inbox.ref

    def spawn_anonymous(self, behavior: Behavior):
        inbox = TestInbox()
        self._children[inbox.name] = inbox.ref
        self._kit._effects.append(SpawnedAnonymous(behavior, inbox.ref))
        return inbox.ref

    def stop(self, child) -> None:
        for name, ref in list(self._children.items()):
            if ref is child:
                del self._children[name]
                self._kit._effects.append(Stopped(name))
                return
        self._kit._effects.append(Stopped(getattr(child, "path", str(child))))

    def watch(self, ref) -> None:
        self._kit._effects.append(Watched(ref))

    def watch_with(self, ref, msg) -> None:
        self._kit._effects.append(WatchedWith(ref, msg))

    def unwatch(self, ref) -> None:
        self._kit._effects.append(Unwatched(ref))

    def set_receive_timeout(self, timeout: float, msg: Any) -> None:
        self._kit._effects.append(ReceiveTimeoutSet(timeout, msg))

    def cancel_receive_timeout(self) -> None:
        self._kit._effects.append(ReceiveTimeoutCancelled())

    def schedule_once(self, delay: float, target, msg):
        self._kit._effects.append(Scheduled(delay, target, msg))
        return _RecordedCancellable()

    def message_adapter(self, fn: Callable[[Any], Any], for_type: type = object):
        class _AdapterRef:
            path = "test://adapter"

            def tell(_s, message, sender=None):
                self._self_inbox._messages.append(fn(message))
        ref = _AdapterRef()
        self._kit._effects.append(MessageAdapter(fn, ref))
        return ref

    def pipe_to_self(self, future, map_result):
        # synchronous kit: resolve immediately if done, else record nothing
        if future.done():
            try:
                self._self_inbox._messages.append(map_result(future.result(), None))
            except BaseException as e:  # noqa: BLE001
                self._self_inbox._messages.append(map_result(None, e))


class _ListLogger:
    def __init__(self, sink: List[tuple]):
        self._sink = sink

    def debug(self, msg, *a):
        self._sink.append(("DEBUG", msg % a if a else msg))

    def info(self, msg, *a):
        self._sink.append(("INFO", msg % a if a else msg))

    def warning(self, msg, *a):
        self._sink.append(("WARNING", msg % a if a else msg))

    warn = warning

    def error(self, msg, *a):
        self._sink.append(("ERROR", msg % a if a else msg))


class BehaviorTestKit:
    """Run a Behavior synchronously, asserting on effects and child inboxes."""

    def __init__(self, behavior: Behavior, name: str = "testkit"):
        self._effects: List[Effect] = []
        self.logs: List[tuple] = []
        self.context = _SyncContext(self, name)
        self.current = start(behavior, self.context)

    # -- driving --------------------------------------------------------------
    def run(self, message: Any) -> None:
        nxt = interpret_message(self.current, self.context, message)
        self.current = canonicalize(nxt, self.current, self.context)

    def run_one(self) -> None:
        """Deliver the next message from the self inbox."""
        self.run(self.self_inbox.receive_message())

    def signal(self, sig: Signal) -> None:
        nxt = interpret_signal(self.current, self.context, sig)
        self.current = canonicalize(nxt, self.current, self.context)

    @property
    def is_alive(self) -> bool:
        return is_alive(self.current)

    # -- inspection -----------------------------------------------------------
    @property
    def self_inbox(self) -> TestInbox:
        return self.context._self_inbox

    def retrieve_all_effects(self) -> List[Effect]:
        out, self._effects = self._effects, []
        return out

    def retrieve_effect(self) -> Effect:
        if not self._effects:
            raise AssertionError("no effects recorded")
        return self._effects.pop(0)

    def expect_effect(self, expected: Effect) -> Effect:
        eff = self.retrieve_effect()
        if eff != expected:
            raise AssertionError(f"expected {expected!r}, got {eff!r}")
        return eff

    def expect_effect_class(self, cls: type) -> Effect:
        eff = self.retrieve_effect()
        if not isinstance(eff, cls):
            raise AssertionError(f"expected {cls.__name__}, got {eff!r}")
        return eff

    def has_effects(self) -> bool:
        return bool(self._effects)

    def child_inbox(self, name: str) -> Optional[TestInbox]:
        return self.context.child_inbox(name)
