"""Adaptive test-time dilation (VERDICT r3 #9).

Reference parity: akka-testkit TestKit.scala:244-319 — every timeout is
`dilated` by `akka.test.timefactor` so timing-coupled assertions survive
slow machines. TestProbe already honors the per-system config factor;
this module adds the PROCESS-level factor used by the multi-process and
lease suites, whose deadlines (lease TTLs, heartbeat pauses, SBR
stable-after) race the wall clock of the whole machine:

- `AKKA_TPU_TEST_TIMEFACTOR` env var: explicit override (CI knob),
  inherited by spawned worker nodes.
- Otherwise AUTO: the 1-minute load average beyond half the cores widens
  the factor proportionally (capped) — a quiet machine runs at 1.0, a
  machine also compiling XLA in 8 other processes gets its heartbeat
  pauses and TTLs stretched instead of flaking.
"""

from __future__ import annotations

import os
import time

_slip_cache = {"at": 0.0, "value": 1.0}


def _sleep_slip() -> float:
    """How late short sleeps wake up RIGHT NOW (scheduler pressure).

    The 1-minute load average lags a just-started load burst by tens of
    seconds — exactly the window in which a timing test sets up its
    deadlines. A 20ms sleep's overshoot responds within one call. Cached
    for 2s so hot await-loops don't pay 20ms per check."""
    now = time.monotonic()
    if now - _slip_cache["at"] < 2.0:
        return _slip_cache["value"]
    t0 = time.perf_counter()
    time.sleep(0.02)
    slip = (time.perf_counter() - t0) / 0.02
    _slip_cache["at"] = now
    _slip_cache["value"] = slip
    return slip


def time_factor() -> float:
    env = os.environ.get("AKKA_TPU_TEST_TIMEFACTOR")
    if env:
        try:
            return max(float(env), 0.1)
        except ValueError:
            pass
    try:
        load = os.getloadavg()[0]
        ncpu = os.cpu_count() or 1
    except (OSError, AttributeError):
        return 1.0
    excess = max(0.0, load - 0.5 * ncpu) / ncpu
    from_load = 1.0 + 3.0 * excess
    from_slip = _sleep_slip()
    return min(max(1.0, from_load, from_slip), 8.0)


def dilated(seconds: float) -> float:
    """Widen a deadline by the current machine-load factor."""
    return seconds * time_factor()


def dilated_s(seconds: float) -> str:
    """Config-string form ("1.5s") for HOCON-style duration keys."""
    return f"{dilated(seconds):.2f}s"
