"""IO: TCP/UDP/DNS as actors (reference: akka-actor io/ — SURVEY.md §2.1,
"IO (TCP/UDP/DNS): NIO selector-based networking as actors", io/Tcp.scala:40).
One selector thread per system multiplexes sockets; readiness enters the
actor world as messages, so handlers speak the reference protocol
(Connect/Bind/Register/Received/Write/Close...)."""

from .tcp import (Abort, Aborted, Bind, Bound, Close, Closed,  # noqa: F401
                  CommandFailed, ConfirmedClose, ConfirmedClosed, Connect,
                  Connected, ConnectionClosed, ErrorClosed, PeerClosed,
                  Received, Register, Tcp, Unbind, Unbound, Write,
                  WritingResumed)
from .udp import (SimpleSender, SimpleSenderReady, Udp, UdpBind,  # noqa: F401
                  UdpBound, UdpReceived, UdpSend, UdpUnbind, UdpUnbound)
from .dns import Dns, Resolve, Resolved, ResolveFailed  # noqa: F401

__all__ = [
    "Tcp", "Connect", "Connected", "Bind", "Bound", "Unbind", "Unbound",
    "Register", "Received", "Write", "CommandFailed", "Close",
    "ConfirmedClose", "Abort", "ConnectionClosed", "Closed", "Aborted",
    "ConfirmedClosed", "PeerClosed", "ErrorClosed", "WritingResumed",
    "Udp", "UdpBind", "UdpBound", "UdpReceived", "UdpSend", "SimpleSender",
    "SimpleSenderReady", "UdpUnbind", "UdpUnbound",
    "Dns", "Resolve", "Resolved", "ResolveFailed",
]
