"""UDP as actors (reference: akka-actor/src/main/scala/akka/io/Udp.scala,
UdpListener.scala, UdpSender.scala): Bind a handler for datagrams, or
SimpleSender for fire-and-forget sends."""

from __future__ import annotations

import socket
import threading
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

from ..actor.actor import Actor
from ..actor.props import Props
from ..actor.ref import ActorRef
from ..actor.system import ActorSystem
from .tcp import CommandFailed, _SelectorLoop
import selectors


@dataclass(frozen=True)
class UdpBind:
    handler: ActorRef
    local_address: Tuple[str, int]


@dataclass(frozen=True)
class UdpBound:
    local_address: Tuple[str, int]


@dataclass(frozen=True)
class UdpReceived:
    data: bytes
    sender_address: Tuple[str, int]


@dataclass(frozen=True)
class UdpSend:
    data: bytes
    target: Tuple[str, int]


@dataclass(frozen=True)
class SimpleSender:
    pass


@dataclass(frozen=True)
class SimpleSenderReady:
    sender_ref: ActorRef


@dataclass(frozen=True)
class UdpUnbind:
    pass


@dataclass(frozen=True)
class UdpUnbound:
    pass


@dataclass(frozen=True)
class _UdpReadable:
    pass


class UdpListenerActor(Actor):
    def __init__(self, loop: _SelectorLoop, bind: UdpBind, commander: ActorRef):
        super().__init__()
        self.loop = loop
        self.bind = bind
        self.commander = commander
        self.sock: Optional[socket.socket] = None

    def pre_start(self) -> None:
        try:
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(self.bind.local_address)
            s.setblocking(False)
            self.sock = s
        except OSError as e:
            self.commander.tell(CommandFailed(self.bind, str(e)),
                                self.self_ref)
            self.context.stop(self.self_ref)
            return
        self.commander.tell(UdpBound(self.sock.getsockname()), self.self_ref)
        ref, sock = self.self_ref, self.sock

        def cb(key, events):
            ref.tell(_UdpReadable(), None)

        def do():
            self.loop.sel.register(sock, selectors.EVENT_READ, ("udp", cb))
        self.loop.execute(do)

    def post_stop(self) -> None:
        sock = self.sock
        if sock is not None:
            def do():
                try:
                    self.loop.sel.unregister(sock)
                except (KeyError, ValueError, OSError):
                    pass
                try:
                    sock.close()
                except OSError:
                    pass
            self.loop.execute(do)

    def receive(self, message: Any) -> Any:
        if isinstance(message, _UdpReadable):
            while True:
                try:
                    data, addr = self.sock.recvfrom(65536)
                except (BlockingIOError, InterruptedError):
                    break
                except OSError:
                    break
                self.bind.handler.tell(UdpReceived(data, addr), self.self_ref)
        elif isinstance(message, UdpSend):
            try:
                self.sock.sendto(message.data, message.target)
            except OSError as e:
                self.sender.tell(CommandFailed(message, str(e)), self.self_ref)
        elif isinstance(message, UdpUnbind):
            self.sender.tell(UdpUnbound(), self.self_ref)
            self.context.stop(self.self_ref)
        else:
            return NotImplemented


class UdpSenderActor(Actor):
    def __init__(self):
        super().__init__()
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)

    def post_stop(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    def receive(self, message: Any) -> Any:
        if isinstance(message, UdpSend):
            try:
                self.sock.sendto(message.data, message.target)
            except OSError as e:
                self.sender.tell(CommandFailed(message, str(e)), self.self_ref)
        else:
            return NotImplemented


class UdpManagerActor(Actor):
    def __init__(self, loop: _SelectorLoop):
        super().__init__()
        self.loop = loop

    def receive(self, message: Any) -> Any:
        if isinstance(message, UdpBind):
            self.context.actor_of(Props.create(
                UdpListenerActor, self.loop, message, self.sender))
        elif isinstance(message, SimpleSender):
            ref = self.context.actor_of(Props.create(UdpSenderActor))
            self.sender.tell(SimpleSenderReady(ref), self.self_ref)
        else:
            return NotImplemented


class Udp:
    """Udp.get(system).manager (reference: Udp.scala extension)."""

    _instances: Dict[ActorSystem, "Udp"] = {}
    _lock = threading.Lock()

    @staticmethod
    def get(system: ActorSystem) -> "Udp":
        with Udp._lock:
            inst = Udp._instances.get(system)
            if inst is None:
                inst = Udp._instances[system] = Udp(system)
                system.register_on_termination(inst._shutdown)
            return inst

    def __init__(self, system: ActorSystem):
        self.system = system
        from .tcp import Tcp
        self.loop = Tcp.get(system).loop  # share the IO thread
        self.manager = system.system_actor_of(
            Props.create(UdpManagerActor, self.loop), "IO-UDP")

    def _shutdown(self) -> None:
        Udp._instances.pop(self.system, None)
