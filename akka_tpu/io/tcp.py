"""TCP as actors over a selector loop.

Reference parity: akka-actor/src/main/scala/akka/io/Tcp.scala (:40 extension,
:596 message surface — Connect/Bind/Register/Received/Write/Close and the
close variants), io/TcpManager.scala, io/TcpListener.scala,
io/TcpOutgoingConnection.scala, io/TcpConnection.scala, driven by a
SelectionHandler (io/SelectionHandler.scala) — here one `selectors`-based IO
thread per Tcp extension instead of the reference's selector-dispatcher
actors; readiness events enter the actor world as plain tells (thread-safe),
so connection actors keep the reference's protocol exactly.
"""

from __future__ import annotations

import collections
import selectors
import socket
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

from ..actor.actor import Actor
from ..actor.props import Props
from ..actor.ref import ActorRef
from ..actor.system import ActorSystem


# -- user API messages (reference: Tcp.scala message surface) ----------------

@dataclass(frozen=True)
class Connect:
    remote_address: Tuple[str, int]
    local_address: Optional[Tuple[str, int]] = None
    timeout: float = 10.0


@dataclass(frozen=True)
class Connected:
    remote_address: Tuple[str, int]
    local_address: Tuple[str, int]


@dataclass(frozen=True)
class Bind:
    handler: ActorRef
    local_address: Tuple[str, int]
    backlog: int = 100


@dataclass(frozen=True)
class Bound:
    local_address: Tuple[str, int]


@dataclass(frozen=True)
class Unbind:
    pass


@dataclass(frozen=True)
class Unbound:
    pass


@dataclass(frozen=True)
class Register:
    handler: ActorRef
    keep_open_on_peer_closed: bool = False


@dataclass(frozen=True)
class Received:
    data: bytes


@dataclass(frozen=True)
class Write:
    data: bytes
    ack: Any = None  # if set, sender gets this message once written


@dataclass(frozen=True)
class WritingResumed:
    pass


@dataclass(frozen=True)
class CommandFailed:
    cmd: Any
    cause: str = ""


@dataclass(frozen=True)
class Close:
    pass


@dataclass(frozen=True)
class ConfirmedClose:
    pass


@dataclass(frozen=True)
class Abort:
    pass


class ConnectionClosed:
    pass


@dataclass(frozen=True)
class Closed(ConnectionClosed):
    pass


@dataclass(frozen=True)
class Aborted(ConnectionClosed):
    pass


@dataclass(frozen=True)
class ConfirmedClosed(ConnectionClosed):
    pass


@dataclass(frozen=True)
class PeerClosed(ConnectionClosed):
    pass


@dataclass(frozen=True)
class ErrorClosed(ConnectionClosed):
    cause: str = ""


# -- internal selector events ------------------------------------------------

@dataclass(frozen=True)
class _Readable:
    pass


@dataclass(frozen=True)
class _Writable:
    pass


@dataclass(frozen=True)
class _Acceptable:
    pass


@dataclass(frozen=True)
class _ConnectFinished:
    ok: bool
    error: str = ""


class _SelectorLoop:
    """One IO thread multiplexing all sockets of a Tcp/Udp extension;
    readiness is delivered to owner actors as tells."""

    def __init__(self, name: str):
        self.sel = selectors.DefaultSelector()
        self._lock = threading.Lock()
        self._pending: list = []
        self._stopped = threading.Event()
        self._waker_r, self._waker_w = socket.socketpair()
        self._waker_r.setblocking(False)
        # the write end MUST be nonblocking too: when the pipe is full the
        # loop is already awake, and a blocking send here can deadlock the
        # selector thread against itself (cb -> _set_mask -> execute)
        self._waker_w.setblocking(False)
        self.sel.register(self._waker_r, selectors.EVENT_READ, ("waker", None))
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)
        self._thread.start()

    def execute(self, fn) -> None:
        """Run fn on the selector thread (register/modify must happen there)."""
        with self._lock:
            self._pending.append(fn)
        try:
            self._waker_w.send(b"x")
        except OSError:
            pass

    def _run(self) -> None:
        while not self._stopped.is_set():
            events = self.sel.select(timeout=0.2)
            with self._lock:
                pending, self._pending = self._pending, []
            for fn in pending:
                try:
                    fn()
                except Exception:  # noqa: BLE001
                    pass
            for key, mask in events:
                kind, cb = key.data
                if kind == "waker":
                    try:
                        while self._waker_r.recv(4096):
                            pass
                    except (BlockingIOError, OSError):
                        pass
                    continue
                try:
                    cb(key, mask)
                except Exception:  # noqa: BLE001
                    pass

    def shutdown(self) -> None:
        self._stopped.set()
        try:
            self._waker_w.send(b"x")
        except OSError:
            pass
        self._thread.join(timeout=2.0)
        try:
            self.sel.close()
            self._waker_r.close()
            self._waker_w.close()
        except OSError:
            pass


class TcpConnectionActor(Actor):
    """One per connection (reference: io/TcpConnection.scala). Speaks
    Register/Received/Write/Close with its handler."""

    def __init__(self, loop: _SelectorLoop, sock: socket.socket,
                 remote: Tuple[str, int], commander: ActorRef,
                 is_outgoing: bool):
        super().__init__()
        self.loop = loop
        self.sock = sock
        self.remote = remote
        self.commander = commander
        self.is_outgoing = is_outgoing
        self.handler: Optional[ActorRef] = None
        self.keep_open = False
        self._peer_closed = False  # peer EOF seen while keep_open
        self.out_buf: collections.deque = collections.deque()  # (bytes, ack, sender)
        self.closing: Optional[Any] = None
        self._registered = False

    def pre_start(self) -> None:
        self.sock.setblocking(False)
        if self.is_outgoing:
            local = self.sock.getsockname()
            self.commander.tell(Connected(self.remote, local), self.self_ref)
        # reads start only after Register (reference: suspended until then)

    def post_stop(self) -> None:
        self._unregister_and_close()

    def _unregister_and_close(self) -> None:
        sock = self.sock

        def do():
            try:
                self.loop.sel.unregister(sock)
            except (KeyError, ValueError, OSError):
                pass
            try:
                sock.close()
            except OSError:
                pass
        self.loop.execute(do)

    def _interest(self, read: bool, write: bool) -> None:
        mask = (selectors.EVENT_READ if read else 0) | \
               (selectors.EVENT_WRITE if write else 0)
        ref = self.self_ref

        def cb(key, events):
            if events & selectors.EVENT_READ:
                ref.tell(_Readable(), None)
                # pause reads until the actor processed this one (one event
                # per readiness cycle keeps delivery ordered)
                self._set_mask(key.fileobj, selectors.EVENT_WRITE
                               if self.out_buf else 0)
            if events & selectors.EVENT_WRITE:
                ref.tell(_Writable(), None)
                self._set_mask(key.fileobj, selectors.EVENT_READ
                               if self._registered else 0)

        def do():
            try:
                if mask == 0:
                    try:
                        self.loop.sel.unregister(self.sock)
                    except (KeyError, ValueError):
                        pass
                    return
                try:
                    self.loop.sel.modify(self.sock, mask, ("conn", cb))
                except (KeyError, ValueError):
                    self.loop.sel.register(self.sock, mask, ("conn", cb))
            except OSError:
                pass
        self.loop.execute(do)

    def _set_mask(self, sock, mask) -> None:
        def do():
            try:
                if mask == 0:
                    self.loop.sel.unregister(sock)
                else:
                    key = self.loop.sel.get_key(sock)
                    self.loop.sel.modify(sock, mask, key.data)
            except (KeyError, ValueError, OSError):
                pass
        self.loop.execute(do)

    # -- receive -------------------------------------------------------------
    def receive(self, message: Any) -> Any:  # noqa: C901
        if isinstance(message, Register):
            self.handler = message.handler
            self.keep_open = message.keep_open_on_peer_closed
            self._registered = True
            self._interest(read=True, write=bool(self.out_buf))
        elif isinstance(message, Write):
            if self.closing is not None:
                self.sender.tell(CommandFailed(message, "closing"),
                                 self.self_ref)
                return
            self.out_buf.append((message.data, message.ack, self.sender))
            self._try_write()
        elif isinstance(message, _Readable):
            self._do_read()
        elif isinstance(message, _Writable):
            self._try_write()
        elif isinstance(message, Close):
            self.closing = Closed()
            if not self.out_buf:
                self._finish_close()
        elif isinstance(message, ConfirmedClose):
            self.closing = ConfirmedClosed()
            if not self.out_buf:
                try:
                    self.sock.shutdown(socket.SHUT_WR)
                except OSError:
                    pass
        elif isinstance(message, Abort):
            try:
                self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                                     b"\x01\x00\x00\x00\x00\x00\x00\x00")
            except OSError:
                pass
            self._notify_closed(Aborted())
            self.context.stop(self.self_ref)
        else:
            return NotImplemented

    def _do_read(self) -> None:
        try:
            while True:
                data = self.sock.recv(65536)
                if data == b"":
                    # peer closed
                    if isinstance(self.closing, ConfirmedClosed):
                        self._notify_closed(ConfirmedClosed())
                    elif self.keep_open:
                        # half-open: writes continue; read side is done —
                        # drop READ interest (an EOF socket stays
                        # read-ready, so leaving it armed busy-loops the
                        # selector and spams PeerClosed) and remember the
                        # EOF for the eventual ConfirmedClose handshake
                        if not self._peer_closed:
                            self._peer_closed = True
                            if self.handler:
                                self.handler.tell(PeerClosed(),
                                                  self.self_ref)
                        self._interest(read=False,
                                       write=bool(self.out_buf))
                        return
                    else:
                        self._notify_closed(PeerClosed())
                    self.context.stop(self.self_ref)
                    return
                if self.handler is not None:
                    self.handler.tell(Received(data), self.self_ref)
                if len(data) < 65536:
                    break
        except (BlockingIOError, InterruptedError):
            pass
        except OSError as e:
            self._notify_closed(ErrorClosed(str(e)))
            self.context.stop(self.self_ref)
            return
        self._interest(read=True, write=bool(self.out_buf))

    def _try_write(self) -> None:
        while self.out_buf:
            data, ack, sender = self.out_buf[0]
            try:
                n = self.sock.send(data)
            except (BlockingIOError, InterruptedError):
                self._interest(read=self._registered, write=True)
                return
            except OSError as e:
                self._notify_closed(ErrorClosed(str(e)))
                self.context.stop(self.self_ref)
                return
            if n < len(data):
                self.out_buf[0] = (data[n:], ack, sender)
                self._interest(read=self._registered, write=True)
                return
            self.out_buf.popleft()
            if ack is not None and sender is not None:
                sender.tell(ack, self.self_ref)
        if self.closing is not None:
            self._finish_close()

    def _finish_close(self) -> None:
        if isinstance(self.closing, ConfirmedClosed):
            try:
                self.sock.shutdown(socket.SHUT_WR)
            except OSError:
                pass
            if self._peer_closed:
                # the peer's EOF already arrived (keep_open half-open):
                # both directions are now shut — finish immediately, the
                # selector will never re-report the consumed EOF
                self._notify_closed(ConfirmedClosed())
                self.context.stop(self.self_ref)
            return  # wait for peer EOF
        self._notify_closed(self.closing)
        self.context.stop(self.self_ref)

    def _notify_closed(self, event) -> None:
        target = self.handler or self.commander
        if target is not None:
            target.tell(event, self.self_ref)


class TcpListenerActor(Actor):
    """(reference: io/TcpListener.scala)"""

    def __init__(self, loop: _SelectorLoop, bind: Bind, commander: ActorRef):
        super().__init__()
        self.loop = loop
        self.bind = bind
        self.commander = commander
        self.sock: Optional[socket.socket] = None

    def pre_start(self) -> None:
        try:
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(self.bind.local_address)
            s.listen(self.bind.backlog)
            s.setblocking(False)
            self.sock = s
        except OSError as e:
            self.commander.tell(CommandFailed(self.bind, str(e)),
                                self.self_ref)
            self.context.stop(self.self_ref)
            return
        self.commander.tell(Bound(self.sock.getsockname()), self.self_ref)
        ref = self.self_ref

        def cb(key, events):
            ref.tell(_Acceptable(), None)

        sock = self.sock

        def do():
            self.loop.sel.register(sock, selectors.EVENT_READ,
                                   ("listener", cb))
        self.loop.execute(do)

    def post_stop(self) -> None:
        sock = self.sock
        if sock is not None:
            def do():
                try:
                    self.loop.sel.unregister(sock)
                except (KeyError, ValueError, OSError):
                    pass
                try:
                    sock.close()
                except OSError:
                    pass
            self.loop.execute(do)

    def receive(self, message: Any) -> Any:
        if isinstance(message, _Acceptable):
            while True:
                try:
                    conn, addr = self.sock.accept()
                except (BlockingIOError, InterruptedError):
                    break
                except OSError:
                    break
                child = self.context.actor_of(Props.create(
                    TcpConnectionActor, self.loop, conn, addr,
                    self.bind.handler, False))
                self.bind.handler.tell(
                    Connected(addr, conn.getsockname()), child)
        elif isinstance(message, Unbind):
            self.sender.tell(Unbound(), self.self_ref)
            self.context.stop(self.self_ref)
        else:
            return NotImplemented


class TcpManagerActor(Actor):
    """(reference: io/TcpManager.scala; obtained via Tcp.get(system).manager)"""

    def __init__(self, loop: _SelectorLoop):
        super().__init__()
        self.loop = loop

    def receive(self, message: Any) -> Any:
        if isinstance(message, Connect):
            commander = self.sender
            try:
                s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                if message.local_address:
                    s.bind(message.local_address)
                s.settimeout(message.timeout)
                s.connect(message.remote_address)  # blocking on manager: the
                # reference connects async; acceptable for the host control
                # plane (connect is rare), data path is fully non-blocking
                s.settimeout(0)
            except OSError as e:
                commander.tell(CommandFailed(message, str(e)), self.self_ref)
                return
            self.context.actor_of(Props.create(
                TcpConnectionActor, self.loop, s, message.remote_address,
                commander, True))
        elif isinstance(message, Bind):
            self.context.actor_of(Props.create(
                TcpListenerActor, self.loop, message, self.sender))
        else:
            return NotImplemented


class Tcp:
    """Tcp.get(system).manager (reference: Tcp.scala:40 extension)."""

    _instances: Dict[ActorSystem, "Tcp"] = {}
    _lock = threading.Lock()

    @staticmethod
    def get(system: ActorSystem) -> "Tcp":
        with Tcp._lock:
            inst = Tcp._instances.get(system)
            if inst is None:
                inst = Tcp._instances[system] = Tcp(system)
                system.register_on_termination(inst._shutdown)
            return inst

    def __init__(self, system: ActorSystem):
        self.system = system
        self.loop = _SelectorLoop(f"akka-tpu-io-{system.name}")
        self.manager = system.system_actor_of(
            Props.create(TcpManagerActor, self.loop), "IO-TCP")

    def _shutdown(self) -> None:
        self.loop.shutdown()
        Tcp._instances.pop(self.system, None)
