"""Async DNS (reference: akka-actor/src/main/scala/akka/io/Dns.scala and
io/dns/ — async resolver with positive/negative caching). Resolution runs
on a small thread pool via socket.getaddrinfo; results are cached with a
TTL and delivered as Resolved messages."""

from __future__ import annotations

import socket
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from ..actor.actor import Actor
from ..actor.props import Props
from ..actor.ref import ActorRef
from ..actor.system import ActorSystem


@dataclass(frozen=True)
class Resolve:
    name: str


@dataclass(frozen=True)
class Resolved:
    name: str
    addresses: Tuple[str, ...]


@dataclass(frozen=True)
class ResolveFailed:
    name: str
    cause: str


@dataclass(frozen=True)
class _ResolutionDone:
    name: str
    addresses: Optional[Tuple[str, ...]]
    error: str
    requesters: Tuple[ActorRef, ...]


class DnsManagerActor(Actor):
    def __init__(self, positive_ttl: float = 30.0, negative_ttl: float = 5.0):
        super().__init__()
        self.positive_ttl = positive_ttl
        self.negative_ttl = negative_ttl
        self.cache: Dict[str, Tuple[float, Any]] = {}  # name -> (expiry, msg)
        self.in_flight: Dict[str, List[ActorRef]] = {}
        self.pool = ThreadPoolExecutor(4, thread_name_prefix="akka-tpu-dns")

    def post_stop(self) -> None:
        self.pool.shutdown(wait=False)

    def receive(self, message: Any) -> Any:
        if isinstance(message, Resolve):
            name, requester = message.name, self.sender
            cached = self.cache.get(name)
            if cached is not None and cached[0] > time.monotonic():
                requester.tell(cached[1], self.self_ref)
                return
            if name in self.in_flight:
                self.in_flight[name].append(requester)
                return
            self.in_flight[name] = [requester]
            self_ref = self.self_ref

            def resolve():
                try:
                    infos = socket.getaddrinfo(name, None)
                    addrs = tuple(dict.fromkeys(i[4][0] for i in infos))
                    self_ref.tell(_ResolutionDone(
                        name, addrs, "", ()), None)
                except OSError as e:
                    self_ref.tell(_ResolutionDone(name, None, str(e), ()),
                                  None)
            self.pool.submit(resolve)
        elif isinstance(message, _ResolutionDone):
            requesters = self.in_flight.pop(message.name, [])
            if message.addresses is not None:
                reply: Any = Resolved(message.name, message.addresses)
                ttl = self.positive_ttl
            else:
                reply = ResolveFailed(message.name, message.error)
                ttl = self.negative_ttl
            self.cache[message.name] = (time.monotonic() + ttl, reply)
            for r in requesters:
                r.tell(reply, self.self_ref)
        else:
            return NotImplemented


class Dns:
    """Dns.get(system).manager; tell it Resolve(name)."""

    _instances: Dict[ActorSystem, "Dns"] = {}
    _lock = threading.Lock()

    @staticmethod
    def get(system: ActorSystem) -> "Dns":
        with Dns._lock:
            inst = Dns._instances.get(system)
            if inst is None:
                inst = Dns._instances[system] = Dns(system)
                system.register_on_termination(
                    lambda: Dns._instances.pop(system, None))
            return inst

    def __init__(self, system: ActorSystem):
        self.system = system
        self.manager = system.system_actor_of(
            Props.create(DnsManagerActor), "IO-DNS")
