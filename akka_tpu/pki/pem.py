"""PEM / DER handling (akka-pki parity).

Reference: akka-pki/src/main/scala/akka/pki/pem/PEMDecoder.scala:16 (RFC 7468
lax decoding of PEM into labeled DER blocks) and DERPrivateKeyLoader.scala:26
(turning DER into a usable private key, dispatching on the PEM label /
PKCS#1 vs PKCS#8 vs SEC.1 structure).

The decoder is a real RFC 7468 parser (no external deps); the key loader
parses just enough ASN.1 to classify the key (version / algorithm OID) and
hands the bytes to `ssl`/`cryptography` for actual use.
"""

from __future__ import annotations

import base64
import re
from dataclasses import dataclass
from typing import List, Optional, Tuple


class PEMLoadingException(ValueError):
    """(reference: akka.pki.pem.PEMLoadingException)"""


@dataclass(frozen=True)
class PEMData:
    """One decoded PEM block (PEMDecoder.DERData analogue)."""

    label: str
    bytes: bytes


_PEM_RE = re.compile(
    r"-----BEGIN ([A-Z0-9 ]+)-----\s*(.*?)\s*-----END ([A-Z0-9 ]+)-----",
    re.DOTALL)


def decode(pem: str) -> PEMData:
    """Decode the FIRST PEM block (PEMDecoder.decode semantics: lax RFC
    7468 — whitespace anywhere in the base64 body is tolerated)."""
    blocks = decode_all(pem)
    if not blocks:
        raise PEMLoadingException("no PEM block found")
    return blocks[0]


def decode_all(pem: str) -> List[PEMData]:
    """Every PEM block in the input, in order (cert chains)."""
    out: List[PEMData] = []
    for m in _PEM_RE.finditer(pem):
        begin, body, end = m.group(1), m.group(2), m.group(3)
        if begin != end:
            raise PEMLoadingException(
                f"mismatched PEM labels: BEGIN {begin} / END {end}")
        b64 = re.sub(r"\s+", "", body)
        try:
            der = base64.b64decode(b64, validate=True)
        except Exception as e:  # noqa: BLE001
            raise PEMLoadingException(f"invalid base64 in PEM body: {e}") from e
        out.append(PEMData(label=begin, bytes=der))
    return out


# ---------------------------------------------------------------- minimal DER
def _read_tlv(data: bytes, off: int) -> Tuple[int, bytes, int]:
    """One ASN.1 TLV: returns (tag, value, next_offset)."""
    if off >= len(data):
        raise PEMLoadingException("truncated DER")
    tag = data[off]
    off += 1
    if off >= len(data):
        raise PEMLoadingException("truncated DER length")
    length = data[off]
    off += 1
    if length & 0x80:
        n = length & 0x7F
        if n == 0 or off + n > len(data):
            raise PEMLoadingException("bad DER length")
        length = int.from_bytes(data[off:off + n], "big")
        off += n
    if off + length > len(data):
        raise PEMLoadingException("DER value exceeds input")
    return tag, data[off:off + length], off + length


def _decode_oid(value: bytes) -> str:
    if not value:
        raise PEMLoadingException("empty OID")
    # every subidentifier — INCLUDING the first — is base-128 with
    # continuation bits; the first packs (arc1, arc2) as 40*arc1+arc2
    # with arc1 capped at 2 (X.690: arc1 = 2 whenever the value >= 80,
    # e.g. OID 2.999 encodes as 88 37)
    subids = []
    acc = 0
    pending = False
    for b in value:
        acc = (acc << 7) | (b & 0x7F)
        pending = bool(b & 0x80)
        if not pending:
            subids.append(acc)
            acc = 0
    if pending or not subids:
        # a trailing continuation byte with a zero payload leaves acc == 0,
        # so the flag — not acc's truthiness — is the truncation signal
        raise PEMLoadingException("truncated OID subidentifier")
    first = subids[0]
    arc1 = 2 if first >= 80 else first // 40
    arc2 = first - 40 * arc1
    return ".".join([str(arc1), str(arc2)] + [str(s) for s in subids[1:]])


_OID_NAMES = {
    "1.2.840.113549.1.1.1": "RSA",
    "1.2.840.10045.2.1": "EC",
    "1.3.101.112": "Ed25519",
    "1.3.101.110": "X25519",
    "1.2.840.10040.4.1": "DSA",
}


@dataclass(frozen=True)
class PrivateKeyInfo:
    """What DERPrivateKeyLoader derives before constructing the key."""

    format: str      # "PKCS#1" | "PKCS#8" | "SEC.1"
    algorithm: str   # RSA | EC | Ed25519 | ...
    der: bytes


class DERPrivateKeyLoader:
    """(reference: akka.pki.pem.DERPrivateKeyLoader.load:26 — dispatch on
    the PEM label, parse the DER enough to know what key it is)."""

    @staticmethod
    def load(data: PEMData) -> PrivateKeyInfo:
        label = data.label
        if label == "RSA PRIVATE KEY":  # PKCS#1
            DERPrivateKeyLoader._check_pkcs1(data.bytes)
            return PrivateKeyInfo("PKCS#1", "RSA", data.bytes)
        if label == "EC PRIVATE KEY":   # SEC.1
            DERPrivateKeyLoader._check_sequence(data.bytes)
            return PrivateKeyInfo("SEC.1", "EC", data.bytes)
        if label == "PRIVATE KEY":      # PKCS#8
            alg = DERPrivateKeyLoader._pkcs8_algorithm(data.bytes)
            return PrivateKeyInfo("PKCS#8", alg, data.bytes)
        raise PEMLoadingException(
            f"unsupported PEM label for a private key: {label!r}")

    @staticmethod
    def _check_sequence(der: bytes) -> bytes:
        tag, value, _ = _read_tlv(der, 0)
        if tag != 0x30:
            raise PEMLoadingException("private key DER is not a SEQUENCE")
        return value

    @staticmethod
    def _check_pkcs1(der: bytes) -> None:
        body = DERPrivateKeyLoader._check_sequence(der)
        tag, version, _ = _read_tlv(body, 0)
        if tag != 0x02:
            raise PEMLoadingException("PKCS#1 key missing version INTEGER")

    @staticmethod
    def _pkcs8_algorithm(der: bytes) -> str:
        body = DERPrivateKeyLoader._check_sequence(der)
        off = 0
        tag, _version, off = _read_tlv(body, off)       # version INTEGER
        if tag != 0x02:
            raise PEMLoadingException("PKCS#8 missing version")
        tag, alg_seq, off = _read_tlv(body, off)        # AlgorithmIdentifier
        if tag != 0x30:
            raise PEMLoadingException("PKCS#8 missing AlgorithmIdentifier")
        tag, oid, _ = _read_tlv(alg_seq, 0)
        if tag != 0x06:
            raise PEMLoadingException("PKCS#8 AlgorithmIdentifier missing OID")
        dotted = _decode_oid(oid)
        return _OID_NAMES.get(dotted, dotted)


def load_certificates(path: str) -> List[PEMData]:
    """All CERTIFICATE blocks from a PEM file (chain order preserved)."""
    with open(path, "r", encoding="utf-8") as f:
        blocks = decode_all(f.read())
    certs = [b for b in blocks if b.label == "CERTIFICATE"]
    if not certs:
        raise PEMLoadingException(f"no CERTIFICATE block in {path}")
    return certs


def load_private_key(path: str) -> PrivateKeyInfo:
    """The first private-key block from a PEM file, classified."""
    with open(path, "r", encoding="utf-8") as f:
        blocks = decode_all(f.read())
    for b in blocks:
        if b.label.endswith("PRIVATE KEY"):
            return DERPrivateKeyLoader.load(b)
    raise PEMLoadingException(f"no private key block in {path}")
