"""PKI: PEM decoding + DER private-key classification (akka-pki parity,
akka-pki/src/main/scala/akka/pki/pem/)."""

from .pem import (DERPrivateKeyLoader, PEMData, PEMLoadingException,
                  PrivateKeyInfo, decode, decode_all, load_certificates,
                  load_private_key)

__all__ = [
    "DERPrivateKeyLoader", "PEMData", "PEMLoadingException",
    "PrivateKeyInfo", "decode", "decode_all", "load_certificates",
    "load_private_key",
]
