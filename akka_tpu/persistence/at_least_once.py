"""AtLeastOnceDelivery: resend-until-confirm with persisted delivery state.

Reference parity: akka-persistence/src/main/scala/akka/persistence/
AtLeastOnceDelivery.scala — deliver() allocates a delivery id and tracks the
unconfirmed message, a redeliver tick resends overdue ones (redeliver-interval,
redelivery-burst-limit), confirmDelivery() clears, UnconfirmedWarning after
warn-after-number-of-unconfirmed-attempts, getDeliverySnapshot/
setDeliverySnapshot persist the delivery state across restarts.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Tuple

from .eventsourced import PersistentActor


@dataclass(frozen=True)
class UnconfirmedDelivery:
    delivery_id: int
    destination: Any  # ActorRef
    message: Any


@dataclass(frozen=True)
class UnconfirmedWarning:
    unconfirmed_deliveries: Tuple[UnconfirmedDelivery, ...]


@dataclass(frozen=True)
class AtLeastOnceDeliverySnapshot:
    current_delivery_id: int
    unconfirmed_deliveries: Tuple[UnconfirmedDelivery, ...]


@dataclass(frozen=True)
class _RedeliveryTick:
    pass


class _Delivery:
    __slots__ = ("destination", "message", "timestamp", "attempt")

    def __init__(self, destination, message, timestamp, attempt):
        self.destination = destination
        self.message = message
        self.timestamp = timestamp
        self.attempt = attempt


class AtLeastOnceDelivery(PersistentActor):
    """Mix-in flavor of PersistentActor (reference trait AtLeastOnceDelivery)."""

    redeliver_interval = 5.0
    redelivery_burst_limit = 10_000
    warn_after_number_of_unconfirmed_attempts = 5
    max_unconfirmed_messages = 100_000

    def __init__(self) -> None:
        super().__init__()
        self._delivery_sequence_nr = 0
        self._unconfirmed: Dict[int, _Delivery] = {}
        self._redeliver_task = None

    # -- lifecycle ------------------------------------------------------------
    def pre_start(self) -> None:
        self._redeliver_task = \
            self.context.system.scheduler.schedule_tell_with_fixed_delay(
                self.redeliver_interval / 2, self.redeliver_interval / 2,
                self.self_ref, _RedeliveryTick())
        super().pre_start()

    def post_stop(self) -> None:
        if self._redeliver_task:
            self._redeliver_task.cancel()
        super().post_stop()

    # -- user API -------------------------------------------------------------
    def deliver(self, destination, delivery_id_to_message: Callable[[int], Any]
                ) -> None:
        """(reference: AtLeastOnceDelivery.deliver)"""
        if len(self._unconfirmed) >= self.max_unconfirmed_messages:
            raise MaxUnconfirmedMessagesExceededException(
                f"too many unconfirmed messages "
                f"({self.max_unconfirmed_messages})")
        self._delivery_sequence_nr += 1
        did = self._delivery_sequence_nr
        msg = delivery_id_to_message(did)
        now = time.time()
        if self.recovery_running:
            # replayed deliver: don't send now, the redeliver tick will —
            # unless it gets confirmed later in the replay
            self._unconfirmed[did] = _Delivery(destination, msg, now, 0)
        else:
            self._unconfirmed[did] = _Delivery(destination, msg, now, 1)
            destination.tell(msg, self.self_ref)

    def confirm_delivery(self, delivery_id: int) -> bool:
        return self._unconfirmed.pop(delivery_id, None) is not None

    @property
    def number_of_unconfirmed(self) -> int:
        return len(self._unconfirmed)

    def get_delivery_snapshot(self) -> AtLeastOnceDeliverySnapshot:
        return AtLeastOnceDeliverySnapshot(
            self._delivery_sequence_nr,
            tuple(UnconfirmedDelivery(did, d.destination, d.message)
                  for did, d in sorted(self._unconfirmed.items())))

    def set_delivery_snapshot(self, snap: AtLeastOnceDeliverySnapshot) -> None:
        self._delivery_sequence_nr = snap.current_delivery_id
        now = time.time()
        self._unconfirmed = {
            u.delivery_id: _Delivery(u.destination, u.message, now, 0)
            for u in snap.unconfirmed_deliveries}

    # -- redelivery -----------------------------------------------------------
    def around_receive(self, receive: Callable[[Any], Any], msg: Any) -> None:
        if isinstance(msg, _RedeliveryTick):
            self._redeliver_overdue()
            return
        super().around_receive(receive, msg)

    def _redeliver_overdue(self) -> None:
        if self.recovery_running:
            return
        now = time.time()
        deadline = now - self.redeliver_interval
        warnings: List[UnconfirmedDelivery] = []
        sent = 0
        for did, d in sorted(self._unconfirmed.items()):
            if sent >= self.redelivery_burst_limit:
                break
            if d.timestamp <= deadline or d.attempt == 0:
                d.timestamp = now
                d.attempt += 1
                d.destination.tell(d.message, self.self_ref)
                sent += 1
                if d.attempt == self.warn_after_number_of_unconfirmed_attempts:
                    warnings.append(UnconfirmedDelivery(did, d.destination,
                                                        d.message))
        if warnings:
            self.self_ref.tell(UnconfirmedWarning(tuple(warnings)),
                               self.self_ref)


class MaxUnconfirmedMessagesExceededException(RuntimeError):
    pass
