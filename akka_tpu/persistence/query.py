"""Persistence query: streams of persisted events.

Reference parity: akka-persistence-query/src/main/scala/akka/persistence/
query/scaladsl/ — CurrentEventsByPersistenceIdQuery.scala:14,
EventsByPersistenceIdQuery, EventsByTagQuery.scala:14, PersistenceIdsQuery;
query/EventEnvelope.scala; Offset (Sequence). The leveldb ReadJournal impl
(persistence-query/.../journal/leveldb/) reads through the journal store and
subscribes for live updates — here the ReadJournal reads through the
JournalPlugin and registers a listener for the live variants.

`current_*` queries return plain lists (the finite snapshot); `events_by_*`
live queries return an EventStream handle: iterate, poll, or attach a
callback; close() detaches. When akka_tpu.stream lands, EventStream.to_source
adapts these into a backpressured Source.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable, List, Optional

from ..actor.system import ActorSystem
from .journal import JournalPlugin
from .messages import PersistentRepr, Tagged
from .persistence import Persistence


@dataclass(frozen=True)
class Sequence:
    """Offset (reference: query/Offset.scala)."""
    value: int


NoOffset = Sequence(0)


@dataclass(frozen=True)
class EventEnvelope:
    """(reference: query/EventEnvelope.scala)"""
    offset: Sequence
    persistence_id: str
    sequence_nr: int
    event: Any
    timestamp: float = 0.0


class EventStream:
    """Live query handle: buffered push stream with callback or poll access."""

    def __init__(self, detach: Callable[[], None]):
        self._detach = detach
        self._lock = threading.Lock()
        self._buf: List[EventEnvelope] = []
        self._cv = threading.Condition(self._lock)
        self._callback: Optional[Callable[[EventEnvelope], None]] = None
        self._closed = False

    def _push(self, env: EventEnvelope) -> None:
        cb = None
        with self._cv:
            if self._closed:
                return
            if self._callback is not None:
                cb = self._callback
            else:
                self._buf.append(env)
                self._cv.notify_all()
        if cb is not None:
            cb(env)

    def on_event(self, cb: Callable[[EventEnvelope], None]) -> "EventStream":
        with self._cv:
            self._callback = cb
            pending, self._buf = self._buf, []
        for env in pending:
            cb(env)
        return self

    def poll(self, timeout: Optional[float] = None) -> Optional[EventEnvelope]:
        with self._cv:
            if not self._buf:
                self._cv.wait(timeout)
            if self._buf:
                return self._buf.pop(0)
            return None

    def drain(self) -> List[EventEnvelope]:
        with self._cv:
            out, self._buf = self._buf, []
            return out

    def close(self) -> None:
        with self._cv:
            self._closed = True
        self._detach()


class ReadJournal:
    """Obtain via PersistenceQuery.get(system).read_journal_for(plugin_id)."""

    def __init__(self, system: ActorSystem, plugin: JournalPlugin):
        self.system = system
        self.plugin = plugin

    # -- current (finite) queries --------------------------------------------
    def current_persistence_ids(self) -> List[str]:
        return self.plugin.persistence_ids()

    def current_events_by_persistence_id(
            self, persistence_id: str, from_sequence_nr: int = 0,
            to_sequence_nr: int = 2**63 - 1) -> List[EventEnvelope]:
        out: List[EventEnvelope] = []

        def cb(r: PersistentRepr) -> None:
            out.append(self._envelope(r))

        self.plugin.replay(persistence_id, max(1, from_sequence_nr),
                           to_sequence_nr, 2**63 - 1, cb)
        return out

    def current_events_by_tag(self, tag: str,
                              offset: Sequence = NoOffset
                              ) -> List[EventEnvelope]:
        return [EventEnvelope(Sequence(off), r.persistence_id, r.sequence_nr,
                              r.payload, r.timestamp)
                for off, r in self.plugin.events_by_tag(tag, offset.value)]

    # -- live queries ---------------------------------------------------------
    def events_by_persistence_id(self, persistence_id: str,
                                 from_sequence_nr: int = 0) -> EventStream:
        """Current events then live updates, gap-free: the listener is
        registered BEFORE the current read, events arriving in between are
        buffered and flushed after it, deduped by sequence nr."""
        lock = threading.Lock()
        emitted: set = set()
        buffered: List[PersistentRepr] = []
        live = [False]
        min_nr = max(1, from_sequence_nr)

        def listener(r: PersistentRepr) -> None:
            if r.persistence_id != persistence_id or r.sequence_nr < min_nr:
                return
            with lock:
                if r.sequence_nr in emitted:
                    return
                if not live[0]:
                    buffered.append(r)
                    return
                emitted.add(r.sequence_nr)
            stream._push(self._envelope(r))

        stream = EventStream(lambda: self.plugin.remove_listener(listener))
        self.plugin.add_listener(listener)
        current = self.current_events_by_persistence_id(persistence_id,
                                                        from_sequence_nr)
        with lock:
            for env in current:
                emitted.add(env.sequence_nr)
        for env in current:
            stream._push(env)
        # flush whatever arrived during the current read, in order, until a
        # pass finds nothing new — ONLY then go live, so a concurrent write
        # can never be pushed ahead of earlier events
        while True:
            with lock:
                pending = sorted((r for r in buffered
                                  if r.sequence_nr not in emitted),
                                 key=lambda r: r.sequence_nr)
                for r in pending:
                    emitted.add(r.sequence_nr)
                if not pending:
                    live[0] = True
                    buffered.clear()
                    break
            for r in pending:
                stream._push(self._envelope(r))
        return stream

    def events_by_tag(self, tag: str, offset: Sequence = NoOffset
                      ) -> EventStream:
        """Gap-free live tag query; tracks the highest emitted offset so each
        notification only reads NEW tag-index entries (not the whole index)."""
        lock = threading.Lock()
        last = [offset.value]
        live = [False]

        def new_envelopes() -> List[EventEnvelope]:
            # call under lock; tag index rows hold untagged payloads
            out = []
            for off, r in self.plugin.events_by_tag(tag, last[0]):
                last[0] = max(last[0], off)
                out.append(EventEnvelope(Sequence(off), r.persistence_id,
                                         r.sequence_nr, r.payload,
                                         r.timestamp))
            return out

        def listener(_r: PersistentRepr) -> None:
            with lock:
                if not live[0]:
                    return  # the initial read covers it (offset-tracked)
                out = new_envelopes()
            for env in out:
                stream._push(env)

        stream = EventStream(lambda: self.plugin.remove_listener(listener))
        self.plugin.add_listener(listener)
        # loop until a read finds nothing new, then flip live under the same
        # lock the listener takes — no window for out-of-order emission
        while True:
            with lock:
                batch = new_envelopes()
                if not batch:
                    live[0] = True
                    break
            for env in batch:
                stream._push(env)
        return stream

    @staticmethod
    def _envelope(r: PersistentRepr) -> EventEnvelope:
        payload = r.payload.payload if isinstance(r.payload, Tagged) else r.payload
        return EventEnvelope(Sequence(r.sequence_nr), r.persistence_id,
                             r.sequence_nr, payload, r.timestamp)


class PersistenceQuery:
    """(reference: PersistenceQuery.scala extension)"""

    _instances = {}
    _lock = threading.Lock()

    @staticmethod
    def get(system: ActorSystem) -> "PersistenceQuery":
        with PersistenceQuery._lock:
            inst = PersistenceQuery._instances.get(system)
            if inst is None:
                inst = PersistenceQuery._instances[system] = \
                    PersistenceQuery(system)
                system.register_on_termination(
                    lambda: PersistenceQuery._instances.pop(system, None))
            return inst

    def __init__(self, system: ActorSystem):
        self.system = system

    def read_journal_for(self, plugin_id: str = "") -> ReadJournal:
        plugin = Persistence.get(self.system).journal_plugin_for(plugin_id)
        return ReadJournal(self.system, plugin)
