"""Journal plugins + the journal actor.

Reference parity: akka-persistence/src/main/scala/akka/persistence/journal/
AsyncWriteJournal.scala (the WriteMessages/ReplayMessages actor protocol,
per-message Success/Rejected/Failure fan-out), journal/inmem/InmemJournal.scala,
journal/leveldb/LeveldbStore.scala (replaced by an append-only pickle record
log — the image has no LevelDB; the access pattern, per-id replay cursors +
tag index, is preserved), journal/leveldb/SharedLeveldbStore.scala (shared
store for multi-node tests → SharedInMemStore).

TPU note (SURVEY.md §2.10 item 8): the journal is the host-side append log;
batched-runtime slab snapshots live in akka_tpu/persistence/slab_snapshot.py.
"""

from __future__ import annotations

import os
import pickle
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..actor.actor import Actor
from .adapter import EventAdapters
from .adapter import _IDENTITY as _IDENTITY_ADAPTER
from .messages import (AtomicWrite, DeleteMessagesFailure,
                       DeleteMessagesSuccess, DeleteMessagesTo,
                       PersistentRepr, RecoverySuccess, ReplayedMessage,
                       ReplayMessages, ReplayMessagesFailure, Tagged,
                       WriteMessageFailure, WriteMessageRejected,
                       WriteMessages, WriteMessagesFailed,
                       WriteMessagesSuccessful, WriteMessageSuccess)


class JournalPlugin:
    """Synchronous storage SPI; the JournalActor provides the async actor
    protocol on top (reference: AsyncWriteJournal + AsyncRecovery SPI).

    write_atomic returns None on success or an error string to REJECT the
    write (event not stored, actor keeps running); raising an exception is a
    write FAILURE (actor stops) — mirroring the reference's Try[Unit] vs
    failed future distinction (AsyncWriteJournal.scala asyncWriteMessages doc).
    """

    def write_atomic(self, write: AtomicWrite) -> Optional[str]:
        raise NotImplementedError

    def replay(self, persistence_id: str, from_nr: int, to_nr: int, max_n: int,
               callback: Callable[[PersistentRepr], None]) -> None:
        raise NotImplementedError

    def highest_sequence_nr(self, persistence_id: str, from_nr: int) -> int:
        raise NotImplementedError

    def delete_to(self, persistence_id: str, to_nr: int) -> None:
        raise NotImplementedError

    # -- query-side hooks (persistence-query reads through the plugin) -------
    def persistence_ids(self) -> List[str]:
        return []

    def events_by_tag(self, tag: str, from_offset: int
                      ) -> List[Tuple[int, PersistentRepr]]:
        """[(offset, repr)] for tagged events; offset is a global counter."""
        return []

    def add_listener(self, listener: Callable[[PersistentRepr], None]) -> None:
        """Live-query hook: called for every stored repr."""

    def remove_listener(self, listener: Callable[[PersistentRepr], None]) -> None:
        pass


class _MemStore:
    """Shared guts of the in-memory journal (separable so multiple systems
    can point at ONE store, the SharedLeveldbStore pattern for multi-node
    persistence tests)."""

    def __init__(self):
        self.lock = threading.RLock()
        self.messages: Dict[str, List[PersistentRepr]] = {}
        self.deleted_to: Dict[str, int] = {}
        self.highest: Dict[str, int] = {}
        self.by_tag: Dict[str, List[Tuple[int, PersistentRepr]]] = {}
        self.offset = 0
        self.listeners: List[Callable[[PersistentRepr], None]] = []


class InMemJournal(JournalPlugin):
    """(reference: journal/inmem/InmemJournal.scala)"""

    def __init__(self, store: Optional[_MemStore] = None):
        self.store = store or _MemStore()

    def write_atomic(self, write: AtomicWrite) -> Optional[str]:
        st = self.store
        with st.lock:
            pid = write.persistence_id
            lst = st.messages.setdefault(pid, [])
            for repr_ in write.payload:
                repr_, tags = _untag(repr_)
                lst.append(repr_)
                st.highest[pid] = max(st.highest.get(pid, 0), repr_.sequence_nr)
                st.offset += 1
                for t in tags:
                    st.by_tag.setdefault(t, []).append((st.offset, repr_))
            listeners = list(st.listeners)
            stored = [_untag(r)[0] for r in write.payload]
        for cb in listeners:
            for r in stored:
                cb(r)
        return None

    def replay(self, persistence_id, from_nr, to_nr, max_n, callback):
        with self.store.lock:
            deleted_to = self.store.deleted_to.get(persistence_id, 0)
            msgs = [r for r in self.store.messages.get(persistence_id, [])
                    if from_nr <= r.sequence_nr <= to_nr
                    and r.sequence_nr > deleted_to][:max_n]
        for r in msgs:
            callback(r)

    def highest_sequence_nr(self, persistence_id, from_nr):
        with self.store.lock:
            return self.store.highest.get(persistence_id, 0)

    def delete_to(self, persistence_id, to_nr):
        with self.store.lock:
            cur = self.store.deleted_to.get(persistence_id, 0)
            self.store.deleted_to[persistence_id] = max(cur, to_nr)

    def persistence_ids(self):
        with self.store.lock:
            return sorted(self.store.messages.keys())

    def events_by_tag(self, tag, from_offset):
        with self.store.lock:
            return [(o, r) for o, r in self.store.by_tag.get(tag, [])
                    if o > from_offset]

    def add_listener(self, listener):
        with self.store.lock:
            self.store.listeners.append(listener)

    def remove_listener(self, listener):
        with self.store.lock:
            if listener in self.store.listeners:
                self.store.listeners.remove(listener)


class SharedInMemStore:
    """Process-global named stores for multi-node tests (reference:
    SharedLeveldbStore)."""

    _stores: Dict[str, _MemStore] = {}
    _lock = threading.Lock()

    @staticmethod
    def get(name: str = "default") -> _MemStore:
        with SharedInMemStore._lock:
            st = SharedInMemStore._stores.get(name)
            if st is None:
                st = SharedInMemStore._stores[name] = _MemStore()
            return st

    @staticmethod
    def reset(name: Optional[str] = None) -> None:
        with SharedInMemStore._lock:
            if name is None:
                SharedInMemStore._stores.clear()
            else:
                SharedInMemStore._stores.pop(name, None)


def _untag(repr_: PersistentRepr) -> Tuple[PersistentRepr, frozenset]:
    if isinstance(repr_.payload, Tagged):
        return repr_.with_payload(repr_.payload.payload), repr_.payload.tags
    return repr_, frozenset()


class _SerializedPayload:
    """Envelope stored in place of the raw event payload when the journal
    serializes through the Serialization registry: (serializer id,
    manifest, bytes) — the manifest carries the schema VERSION, so
    replays after a rolling upgrade run the registered migrations
    (akka-serialization-jackson JacksonMigration parity)."""

    __slots__ = ("serializer_id", "manifest", "data")

    def __init__(self, serializer_id: int, manifest: str, data: bytes):
        self.serializer_id = serializer_id
        self.manifest = manifest
        self.data = data

    def __getstate__(self):
        return (self.serializer_id, self.manifest, self.data)

    def __setstate__(self, s):
        self.serializer_id, self.manifest, self.data = s


def scan_record_log(path: str):
    """Yield (end_offset, record) for every INTACT record in a
    length-prefixed record log, stopping at the first torn or corrupt tail
    (short header, short blob, or a blob pickle.loads rejects). The
    end_offset of the last yielded record is the byte length of the valid
    prefix — what repair_record_log truncates to."""
    if not os.path.exists(path):
        return
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        offset = 0
        while True:
            hdr = f.read(8)
            if len(hdr) < 8:
                return
            n = int.from_bytes(hdr, "little")
            if offset + 8 + n > size:
                # truncated tail, OR garbage bytes read as an absurd length
                # prefix — bound by the file size BEFORE allocating, so a
                # torn tail can never MemoryError the repair that exists
                # to clean it up
                return
            blob = f.read(n)
            if len(blob) < n:
                return  # truncated tail (crash mid-append)
            try:
                obj = pickle.loads(blob)
            except Exception:  # noqa: BLE001 — torn/garbled tail record
                return
            offset += 8 + n
            yield offset, obj


def repair_record_log(path: str, flight_recorder=None) -> int:
    """Crash-safe open: truncate a torn tail record (a host killed
    mid-append leaves a partial length-prefix+blob) back to the last intact
    record, warning via the flight recorder instead of letting readers hit
    UnpicklingError. Returns the number of bytes dropped (0 = intact)."""
    if not os.path.exists(path):
        return 0
    good = 0
    for end, _obj in scan_record_log(path):
        good = end
    size = os.path.getsize(path)
    if size <= good:
        return 0
    with open(path, "r+b") as f:
        f.truncate(good)
        f.flush()
        os.fsync(f.fileno())
    dropped = size - good
    if flight_recorder is not None and getattr(
            flight_recorder, "enabled", False):
        flight_recorder.journal_truncated(path, dropped)
    return dropped


class FileJournal(JournalPlugin):
    """Append-only record log: one file per persistence id, length-prefixed
    pickled PersistentReprs, plus a tag-index file. Replaces the reference's
    LevelDB store (journal/leveldb/LeveldbStore.scala) with the same
    capabilities: per-id replay, highest-seq-nr, logical delete-to, tags.

    Appends are atomic-at-the-record (length-prefix + fsync); on open every
    log in the directory is repaired via repair_record_log, so a kill -9
    mid-append costs at most the record being written, never the log.

    With `serialization` set (a serialization.Serialization), event
    PAYLOADS are stored as (serializer id, manifest, bytes) envelopes via
    the registry instead of raw pickle — the versioned-manifest seam that
    makes journals survive schema evolution (VersionedJsonSerializer +
    SchemaMigration, the Jackson-journal analogue)."""

    def __init__(self, directory: str, serialization=None,
                 flight_recorder=None):
        self.serialization = serialization
        self.flight_recorder = flight_recorder
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self.lock = threading.RLock()
        self.listeners: List[Callable[[PersistentRepr], None]] = []
        self._meta_path = os.path.join(directory, "_meta.pickle")
        self._tags_path = os.path.join(directory, "_tags.log")
        # {pid: {"deleted_to": n, "highest": n}}, global tag offset counter
        self._meta: Dict[str, Dict[str, int]] = {}
        self._offset = 0
        for name in sorted(os.listdir(directory)):
            if name.endswith(".log"):
                repair_record_log(os.path.join(directory, name),
                                  flight_recorder)
        self._load_meta()

    # -- file helpers ---------------------------------------------------------
    def _pid_path(self, pid: str) -> str:
        import hashlib
        safe = hashlib.sha1(pid.encode()).hexdigest()[:16]
        return os.path.join(self.dir, f"j-{safe}.log")

    @staticmethod
    def _append_record(path: str, obj: Any) -> None:
        blob = pickle.dumps(obj, protocol=4)
        with open(path, "ab") as f:
            f.write(len(blob).to_bytes(8, "little"))
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())

    @staticmethod
    def _read_records(path: str):
        # torn/corrupt tails stop the scan rather than raising; the repair
        # pass in __init__ already truncated them with a warning
        for _end, obj in scan_record_log(path):
            yield obj

    def _load_meta(self) -> None:
        if os.path.exists(self._meta_path):
            try:
                with open(self._meta_path, "rb") as f:
                    saved = pickle.load(f)
                self._meta = saved.get("meta", {})
                self._offset = saved.get("offset", 0)
            except (OSError, pickle.PickleError, EOFError):
                self._meta = {}
        # recover pid registry from directory on cold start
        for rec in self._read_records(os.path.join(self.dir, "_pids.log")):
            self._meta.setdefault(rec, {})

    def _save_meta(self) -> None:
        tmp = self._meta_path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump({"meta": self._meta, "offset": self._offset}, f, 4)
        os.replace(tmp, self._meta_path)

    # -- SPI -------------------------------------------------------------------
    def write_atomic(self, write: AtomicWrite) -> Optional[str]:
        with self.lock:
            pid = write.persistence_id
            path = self._pid_path(pid)
            # serialize EVERYTHING first so an unpicklable event rejects the
            # whole batch with zero bytes written (AtomicWrite is
            # all-or-nothing; events reported rejected must not replay later)
            untagged = []
            try:
                from ..serialization.serialization import SerializationError
                blobs = []
                for repr_ in write.payload:
                    r, tags = _untag(repr_)
                    if self.serialization is not None:
                        sid, man, blob = self.serialization.serialize(
                            r.payload)
                        r = r.with_payload(
                            _SerializedPayload(sid, man, blob))
                    untagged.append((r, tags))
                    blobs.append(pickle.dumps(r, protocol=4))
                    for t in tags:
                        pickle.dumps((t, 0, r), protocol=4)
            except (pickle.PickleError, TypeError, AttributeError,
                    SerializationError) as e:
                return f"unserializable event: {e}"  # reject, not fail
            known = pid in self._meta
            m = self._meta.setdefault(pid, {})
            stored = []
            for r, tags in untagged:
                self._append_record(path, r)
                m["highest"] = max(m.get("highest", 0), r.sequence_nr)
                stored.append(r)
                for t in tags:
                    self._offset += 1
                    self._append_record(self._tags_path,
                                        (t, self._offset, r))
            if not known:
                self._append_record(os.path.join(self.dir, "_pids.log"), pid)
            self._save_meta()
            listeners = list(self.listeners)
        if listeners:
            unwrapped = [self._unwrap(r) for r in stored]  # once, not per cb
            for cb in listeners:
                for r in unwrapped:
                    cb(r)
        return None

    def _unwrap(self, r):
        """Deserialize a _SerializedPayload envelope back into the event
        object — where versioned manifests run their migrations."""
        if self.serialization is not None and \
                isinstance(r.payload, _SerializedPayload):
            p = r.payload
            return r.with_payload(self.serialization.deserialize(
                p.serializer_id, p.manifest, p.data))
        return r

    def replay(self, persistence_id, from_nr, to_nr, max_n, callback):
        if max_n <= 0:
            return
        with self.lock:
            deleted_to = self._meta.get(persistence_id, {}).get("deleted_to", 0)
            out = []
            for r in self._read_records(self._pid_path(persistence_id)):
                if (from_nr <= r.sequence_nr <= to_nr
                        and r.sequence_nr > deleted_to):
                    out.append(r)
                    if len(out) >= max_n:
                        break
        for r in out:
            callback(self._unwrap(r))

    def highest_sequence_nr(self, persistence_id, from_nr):
        with self.lock:
            return self._meta.get(persistence_id, {}).get("highest", 0)

    def delete_to(self, persistence_id, to_nr):
        with self.lock:
            m = self._meta.setdefault(persistence_id, {})
            m["deleted_to"] = max(m.get("deleted_to", 0), to_nr)
            self._save_meta()

    def persistence_ids(self):
        with self.lock:
            return sorted(self._meta.keys())

    def events_by_tag(self, tag, from_offset):
        with self.lock:
            out = []
            for t, off, r in self._read_records(self._tags_path):
                if t == tag and off > from_offset:
                    out.append((off, r))
        # deserialization (and user migration code) runs OUTSIDE the lock,
        # like replay(): a slow migration must not stall concurrent writes
        return [(off, self._unwrap(r)) for off, r in out]

    def add_listener(self, listener):
        with self.lock:
            self.listeners.append(listener)

    def remove_listener(self, listener):
        with self.lock:
            if listener in self.listeners:
                self.listeners.remove(listener)


class JournalActor(Actor):
    """Async actor protocol over a sync plugin (reference:
    AsyncWriteJournal.scala receiveWriteMessages / ReplayMessages handling).
    Runs on its own dispatcher in the reference; here the actor's mailbox
    already serializes plugin access per journal.

    `adapters` (EventAdapters) is the per-journal domain<->journal-model
    seam (reference: WriteJournalBase.preparePersistentBatch applying
    toJournal on the write side, AsyncWriteJournal.adaptFromJournal fanning
    each stored record out to 0..N ReplayedMessages on the read side)."""

    def __init__(self, plugin: JournalPlugin, adapters=None):
        super().__init__()
        self.plugin = plugin
        self.adapters = adapters if adapters is not None else EventAdapters()

    def _adapt_to_journal(self, repr_: PersistentRepr) -> PersistentRepr:
        """Apply the write-side adapter to the DOMAIN payload; a typed
        tagger's Tagged wrapper is transparent (adapt inside, keep tags) —
        and an adapter may itself RETURN Tagged to attach tags."""
        payload, tags = repr_.payload, None
        if isinstance(payload, Tagged):
            payload, tags = payload.payload, payload.tags
        adapter = self.adapters.get(type(payload))
        if adapter is _IDENTITY_ADAPTER and tags is None:
            return repr_
        adapted = adapter.to_journal(payload)
        manifest = adapter.manifest(payload) or repr_.manifest
        if tags is not None:
            # tagger tags and adapter-attached tags UNION (dropping either
            # silently breaks events_by_tag for that source)
            if isinstance(adapted, Tagged):
                adapted = Tagged(adapted.payload, adapted.tags | tags)
            else:
                adapted = Tagged(adapted, tags)
        out = repr_.with_payload(adapted)
        return PersistentRepr(out.payload, out.sequence_nr,
                              out.persistence_id, manifest, out.writer_uuid,
                              out.deleted, out.timestamp)

    def _adapt_from_journal(self, repr_: PersistentRepr) -> List[PersistentRepr]:
        """Read-side: one stored record -> 0..N domain events, all sharing
        the stored sequence_nr (reference: adaptFromJournal)."""
        adapter = self.adapters.get(type(repr_.payload))
        seq = adapter.from_journal(repr_.payload, repr_.manifest)
        return [repr_.with_payload(ev) for ev in seq.events]

    def receive(self, message: Any) -> Any:
        if isinstance(message, WriteMessages):
            self._write(message)
        elif isinstance(message, ReplayMessages):
            self._replay(message)
        elif isinstance(message, DeleteMessagesTo):
            try:
                self.plugin.delete_to(message.persistence_id,
                                      message.to_sequence_nr)
                message.persistent_actor.tell(
                    DeleteMessagesSuccess(message.to_sequence_nr), self.self_ref)
            except Exception as e:  # noqa: BLE001
                message.persistent_actor.tell(
                    DeleteMessagesFailure(str(e), message.to_sequence_nr),
                    self.self_ref)
        else:
            return NotImplemented

    def _write(self, msg: WriteMessages) -> None:
        actor, iid = msg.persistent_actor, msg.actor_instance_id
        results: List[Tuple[AtomicWrite, Optional[str]]] = []
        failure: Optional[str] = None
        n_written = 0
        for aw in msg.messages:
            if failure is not None:
                break
            try:
                to_store = aw if self.adapters.is_empty else AtomicWrite(
                    tuple(self._adapt_to_journal(r) for r in aw.payload))
                rejection = self.plugin.write_atomic(to_store)
                results.append((aw, rejection))
                if rejection is None:
                    n_written += 1
            except Exception as e:  # noqa: BLE001 — store failure
                failure = str(e)
        if failure is not None:
            actor.tell(WriteMessagesFailed(failure, len(msg.messages), iid),
                       self.self_ref)
            for aw in msg.messages:
                for repr_ in aw.payload:
                    actor.tell(WriteMessageFailure(repr_, failure, iid),
                               self.self_ref)
            return
        actor.tell(WriteMessagesSuccessful(iid), self.self_ref)
        for aw, rejection in results:
            for repr_ in aw.payload:
                r, _ = _untag(repr_)
                if rejection is None:
                    actor.tell(WriteMessageSuccess(r, iid), self.self_ref)
                else:
                    actor.tell(WriteMessageRejected(r, rejection, iid),
                               self.self_ref)

    def _replay(self, msg: ReplayMessages) -> None:
        actor = msg.persistent_actor

        def emit(r: PersistentRepr) -> None:
            if self.adapters.is_empty:
                actor.tell(ReplayedMessage(r), self.self_ref)
                return
            for adapted in self._adapt_from_journal(r):
                actor.tell(ReplayedMessage(adapted), self.self_ref)
        try:
            self.plugin.replay(
                msg.persistence_id, msg.from_sequence_nr, msg.to_sequence_nr,
                msg.max, emit)
            highest = self.plugin.highest_sequence_nr(
                msg.persistence_id, msg.from_sequence_nr)
            actor.tell(RecoverySuccess(highest), self.self_ref)
        except Exception as e:  # noqa: BLE001
            actor.tell(ReplayMessagesFailure(str(e)), self.self_ref)
