"""Snapshot store plugins + actor.

Reference parity: akka-persistence/src/main/scala/akka/persistence/snapshot/
SnapshotStore.scala (LoadSnapshot/SaveSnapshot actor protocol),
snapshot/local/LocalSnapshotStore.scala:31 (one file per snapshot named
snapshot-<pid>-<seqNr>-<ts>, newest-first selection, keep a few fallbacks).
"""

from __future__ import annotations

import hashlib
import os
import pickle
import threading
from typing import Any, Dict, List, Optional, Tuple

from ..actor.actor import Actor
from .messages import (DeleteSnapshot, DeleteSnapshotFailure, DeleteSnapshots,
                       DeleteSnapshotsFailure, DeleteSnapshotsSuccess,
                       DeleteSnapshotSuccess, LoadSnapshot, LoadSnapshotFailed,
                       LoadSnapshotResult, SaveSnapshot, SaveSnapshotFailure,
                       SaveSnapshotSuccess, SelectedSnapshot, SnapshotMetadata,
                       SnapshotSelectionCriteria)


class SnapshotPlugin:
    def load(self, persistence_id: str, criteria: SnapshotSelectionCriteria
             ) -> Optional[SelectedSnapshot]:
        raise NotImplementedError

    def save(self, metadata: SnapshotMetadata, snapshot: Any) -> None:
        raise NotImplementedError

    def delete(self, metadata: SnapshotMetadata) -> None:
        raise NotImplementedError

    def delete_matching(self, persistence_id: str,
                        criteria: SnapshotSelectionCriteria) -> None:
        raise NotImplementedError


class InMemSnapshotStore(SnapshotPlugin):
    def __init__(self):
        self.lock = threading.RLock()
        self.snapshots: Dict[str, List[Tuple[SnapshotMetadata, Any]]] = {}

    def load(self, persistence_id, criteria):
        with self.lock:
            candidates = [(md, s) for md, s in
                          self.snapshots.get(persistence_id, [])
                          if criteria.matches(md)]
        if not candidates:
            return None
        md, snap = max(candidates, key=lambda p: (p[0].sequence_nr,
                                                  p[0].timestamp))
        return SelectedSnapshot(md, snap)

    def save(self, metadata, snapshot):
        with self.lock:
            lst = self.snapshots.setdefault(metadata.persistence_id, [])
            lst[:] = [(md, s) for md, s in lst
                      if not (md.sequence_nr == metadata.sequence_nr
                              and md.timestamp == metadata.timestamp)]
            lst.append((metadata, snapshot))

    def delete(self, metadata):
        with self.lock:
            lst = self.snapshots.get(metadata.persistence_id, [])
            lst[:] = [(md, s) for md, s in lst
                      if md.sequence_nr != metadata.sequence_nr]

    def delete_matching(self, persistence_id, criteria):
        with self.lock:
            lst = self.snapshots.get(persistence_id, [])
            lst[:] = [(md, s) for md, s in lst if not criteria.matches(md)]


class LocalSnapshotStore(SnapshotPlugin):
    """One pickle file per snapshot: snapshot-<pidhash>-<seqnr>-<ts_us>
    (reference: snapshot/local/LocalSnapshotStore.scala:31)."""

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self.lock = threading.RLock()

    @staticmethod
    def _safe(pid: str) -> str:
        return hashlib.sha1(pid.encode()).hexdigest()[:16]

    def _files_for(self, pid: str) -> List[Tuple[SnapshotMetadata, str]]:
        prefix = f"snapshot-{self._safe(pid)}-"
        out = []
        for name in os.listdir(self.dir):
            if not name.startswith(prefix):
                continue
            try:
                _, _, seq, ts = name.rsplit("-", 3)
                out.append((SnapshotMetadata(pid, int(seq), int(ts) / 1e6),
                            os.path.join(self.dir, name)))
            except ValueError:
                continue
        return out

    def load(self, persistence_id, criteria):
        with self.lock:
            candidates = [(md, p) for md, p in self._files_for(persistence_id)
                          if criteria.matches(md)]
            # newest first; fall back on unreadable files (reference keeps 3)
            for md, path in sorted(candidates,
                                   key=lambda x: (x[0].sequence_nr,
                                                  x[0].timestamp),
                                   reverse=True):
                try:
                    with open(path, "rb") as f:
                        return SelectedSnapshot(md, pickle.load(f))
                except (OSError, pickle.PickleError, EOFError):
                    continue
        return None

    def save(self, metadata, snapshot):
        with self.lock:
            name = (f"snapshot-{self._safe(metadata.persistence_id)}-"
                    f"{metadata.sequence_nr}-{int(metadata.timestamp * 1e6)}")
            tmp = os.path.join(self.dir, name + ".tmp")
            with open(tmp, "wb") as f:
                pickle.dump(snapshot, f, protocol=4)
            os.replace(tmp, os.path.join(self.dir, name))

    def delete(self, metadata):
        with self.lock:
            for md, path in self._files_for(metadata.persistence_id):
                if md.sequence_nr == metadata.sequence_nr:
                    try:
                        os.unlink(path)
                    except OSError:
                        pass

    def delete_matching(self, persistence_id, criteria):
        with self.lock:
            for md, path in self._files_for(persistence_id):
                if criteria.matches(md):
                    try:
                        os.unlink(path)
                    except OSError:
                        pass


class SnapshotStoreActor(Actor):
    """(reference: snapshot/SnapshotStore.scala receive)"""

    def __init__(self, plugin: SnapshotPlugin):
        super().__init__()
        self.plugin = plugin

    def receive(self, message: Any) -> Any:
        if isinstance(message, LoadSnapshot):
            try:
                crit = message.criteria
                if message.to_sequence_nr < crit.max_sequence_nr:
                    crit = SnapshotSelectionCriteria(
                        max_sequence_nr=message.to_sequence_nr,
                        max_timestamp=crit.max_timestamp,
                        min_sequence_nr=crit.min_sequence_nr,
                        min_timestamp=crit.min_timestamp)
                selected = self.plugin.load(message.persistence_id, crit)
                self.sender.tell(
                    LoadSnapshotResult(selected, message.to_sequence_nr),
                    self.self_ref)
            except Exception as e:  # noqa: BLE001
                self.sender.tell(LoadSnapshotFailed(str(e)), self.self_ref)
        elif isinstance(message, SaveSnapshot):
            try:
                self.plugin.save(message.metadata, message.snapshot)
                self.sender.tell(SaveSnapshotSuccess(message.metadata),
                                 self.self_ref)
            except Exception as e:  # noqa: BLE001
                self.sender.tell(SaveSnapshotFailure(message.metadata, str(e)),
                                 self.self_ref)
        elif isinstance(message, DeleteSnapshot):
            try:
                self.plugin.delete(message.metadata)
                self.sender.tell(DeleteSnapshotSuccess(message.metadata),
                                 self.self_ref)
            except Exception as e:  # noqa: BLE001
                self.sender.tell(DeleteSnapshotFailure(message.metadata,
                                                       str(e)), self.self_ref)
        elif isinstance(message, DeleteSnapshots):
            try:
                self.plugin.delete_matching(message.persistence_id,
                                            message.criteria)
                self.sender.tell(DeleteSnapshotsSuccess(message.criteria),
                                 self.self_ref)
            except Exception as e:  # noqa: BLE001
                self.sender.tell(DeleteSnapshotsFailure(message.criteria,
                                                        str(e)), self.self_ref)
        else:
            return NotImplemented
