"""Event/snapshot adapter seams: domain model <-> journal model.

Reference parity: akka-persistence/src/main/scala/akka/persistence/journal/
EventAdapter.scala:21 (manifest/toJournal/fromJournal with EventSeq —
0..N domain events per stored record, the read-side upcasting hook),
EventAdapters.scala:25 (the per-journal registry binding event classes to
adapters, most-specific class wins), and akka-persistence-typed/src/main/
scala/akka/persistence/typed/SnapshotAdapter.scala:14 (state <-> stored
snapshot mapping, wired into EventSourcedBehavior).

The adapter layer COMPOSES with the versioned serializer
(serialization/versioned.py): adapters map between in-memory models before
anything is serialized; schema migrations rewrite serialized payloads. A
tagging adapter returns `Tagged(journal_event, tags)` and the journal's
untag path (journal.py _untag) handles it like the typed tagger's output.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Type


class EventSeq:
    """What fromJournal returns: zero, one or many domain events for one
    stored record (reference: EventAdapter.scala EventSeq)."""

    __slots__ = ("events",)

    def __init__(self, events: Iterable[Any]):
        self.events: List[Any] = list(events)

    @staticmethod
    def empty() -> "EventSeq":
        return EventSeq(())

    @staticmethod
    def single(event: Any) -> "EventSeq":
        return EventSeq((event,))

    @staticmethod
    def many(events: Iterable[Any]) -> "EventSeq":
        return EventSeq(events)


class EventAdapter:
    """domain event <-> journal model (reference: EventAdapter.scala:21).

    Override any subset: `to_journal` for the write side (wrap, detach the
    domain model, attach tags), `from_journal` for the read side (unwrap,
    upcast 1->N), `manifest` to stamp a type hint stored alongside."""

    def manifest(self, event: Any) -> str:
        return ""

    def to_journal(self, event: Any) -> Any:
        return event

    def from_journal(self, event: Any, manifest: str) -> EventSeq:
        return EventSeq.single(event)


class IdentityEventAdapter(EventAdapter):
    """(reference: IdentityEventAdapter)"""


_IDENTITY = IdentityEventAdapter()


class EventAdapters:
    """Per-journal adapter registry (reference: EventAdapters.scala:25).

    bindings: {event_class: adapter}. Lookup walks the class MRO so the
    most specific binding wins; unbound classes get the identity adapter.
    Write-side lookup uses the DOMAIN event's class; read-side lookup uses
    the stored JOURNAL model's class."""

    def __init__(self, bindings: Optional[Dict[Type, EventAdapter]] = None):
        self._bindings: Dict[Type, EventAdapter] = dict(bindings or {})
        self._cache: Dict[Type, EventAdapter] = {}

    def register(self, event_class: Type, adapter: EventAdapter) -> None:
        self._bindings[event_class] = adapter
        self._cache.clear()

    def get(self, event_class: Type) -> EventAdapter:
        hit = self._cache.get(event_class)
        if hit is not None:
            return hit
        for cls in event_class.__mro__:
            adapter = self._bindings.get(cls)
            if adapter is not None:
                self._cache[event_class] = adapter
                return adapter
        self._cache[event_class] = _IDENTITY
        return _IDENTITY

    @property
    def is_empty(self) -> bool:
        return not self._bindings


class SnapshotAdapter:
    """state <-> stored snapshot (reference: typed/SnapshotAdapter.scala:14).
    Override `to_journal` to detach/compress the stored form and
    `from_journal` to upcast old snapshots into the current state type."""

    def to_journal(self, state: Any) -> Any:
        return state

    def from_journal(self, from_journal: Any) -> Any:
        return from_journal
