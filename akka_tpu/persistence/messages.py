"""Persistence protocol messages.

Reference parity: akka-persistence/src/main/scala/akka/persistence/
JournalProtocol.scala (WriteMessages / ReplayMessages and their replies),
SnapshotProtocol.scala (LoadSnapshot / SaveSnapshot), Persistent.scala
(PersistentRepr), Persistence.scala (Recovery), Snapshot.scala
(SnapshotMetadata / SnapshotOffer / SelectedSnapshot).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, FrozenSet, List, Optional, Tuple


@dataclass(frozen=True)
class PersistentRepr:
    """One persisted event record (reference: Persistent.scala PersistentRepr)."""
    payload: Any
    sequence_nr: int
    persistence_id: str
    manifest: str = ""
    writer_uuid: str = ""
    deleted: bool = False
    timestamp: float = field(default_factory=time.time)

    def with_payload(self, payload: Any) -> "PersistentRepr":
        return PersistentRepr(payload, self.sequence_nr, self.persistence_id,
                              self.manifest, self.writer_uuid, self.deleted,
                              self.timestamp)


@dataclass(frozen=True)
class Tagged:
    """Wrap an event to attach query tags (reference: journal/Tagged.scala)."""
    payload: Any
    tags: FrozenSet[str]

    @staticmethod
    def of(payload: Any, *tags: str) -> "Tagged":
        return Tagged(payload, frozenset(tags))


# -- journal protocol (reference: JournalProtocol.scala) ---------------------

@dataclass(frozen=True)
class AtomicWrite:
    """All-or-nothing batch of events from one persistAll call."""
    payload: Tuple[PersistentRepr, ...]

    @property
    def persistence_id(self) -> str:
        return self.payload[0].persistence_id

    @property
    def lowest_sequence_nr(self) -> int:
        return self.payload[0].sequence_nr

    @property
    def highest_sequence_nr(self) -> int:
        return self.payload[-1].sequence_nr


@dataclass(frozen=True)
class WriteMessages:
    messages: Tuple[AtomicWrite, ...]
    persistent_actor: Any  # ActorRef
    actor_instance_id: int


@dataclass(frozen=True)
class WriteMessagesSuccessful:
    actor_instance_id: int


@dataclass(frozen=True)
class WriteMessagesFailed:
    cause: str
    write_count: int
    actor_instance_id: int


@dataclass(frozen=True)
class WriteMessageSuccess:
    persistent: PersistentRepr
    actor_instance_id: int


@dataclass(frozen=True)
class WriteMessageRejected:
    """Serialization-style rejection: the event was NOT stored but the actor
    keeps running (reference: JournalProtocol.WriteMessageRejected)."""
    persistent: PersistentRepr
    cause: str
    actor_instance_id: int


@dataclass(frozen=True)
class WriteMessageFailure:
    """Store failure: the actor is stopped (reference semantics)."""
    persistent: PersistentRepr
    cause: str
    actor_instance_id: int


@dataclass(frozen=True)
class ReplayMessages:
    from_sequence_nr: int
    to_sequence_nr: int
    max: int
    persistence_id: str
    persistent_actor: Any


@dataclass(frozen=True)
class ReplayedMessage:
    persistent: PersistentRepr


@dataclass(frozen=True)
class RecoverySuccess:
    highest_sequence_nr: int


@dataclass(frozen=True)
class ReplayMessagesFailure:
    cause: str


@dataclass(frozen=True)
class DeleteMessagesTo:
    persistence_id: str
    to_sequence_nr: int
    persistent_actor: Any


@dataclass(frozen=True)
class DeleteMessagesSuccess:
    to_sequence_nr: int


@dataclass(frozen=True)
class DeleteMessagesFailure:
    cause: str
    to_sequence_nr: int


# -- snapshot protocol (reference: SnapshotProtocol.scala, Snapshot.scala) ---

@dataclass(frozen=True)
class SnapshotMetadata:
    persistence_id: str
    sequence_nr: int
    timestamp: float = 0.0


@dataclass(frozen=True)
class SnapshotOffer:
    """Delivered to receive_recover before any replayed events."""
    metadata: SnapshotMetadata
    snapshot: Any


@dataclass(frozen=True)
class SelectedSnapshot:
    metadata: SnapshotMetadata
    snapshot: Any


@dataclass(frozen=True)
class SnapshotSelectionCriteria:
    max_sequence_nr: int = 2**63 - 1
    max_timestamp: float = float("inf")
    min_sequence_nr: int = 0
    min_timestamp: float = 0.0

    @staticmethod
    def latest() -> "SnapshotSelectionCriteria":
        return SnapshotSelectionCriteria()

    @staticmethod
    def none() -> "SnapshotSelectionCriteria":
        return SnapshotSelectionCriteria(max_sequence_nr=0, max_timestamp=0.0)

    def matches(self, md: SnapshotMetadata) -> bool:
        return (self.min_sequence_nr <= md.sequence_nr <= self.max_sequence_nr
                and self.min_timestamp <= md.timestamp <= self.max_timestamp)


@dataclass(frozen=True)
class LoadSnapshot:
    persistence_id: str
    criteria: SnapshotSelectionCriteria
    to_sequence_nr: int


@dataclass(frozen=True)
class LoadSnapshotResult:
    snapshot: Optional[SelectedSnapshot]
    to_sequence_nr: int


@dataclass(frozen=True)
class LoadSnapshotFailed:
    cause: str


@dataclass(frozen=True)
class SaveSnapshot:
    metadata: SnapshotMetadata
    snapshot: Any


@dataclass(frozen=True)
class SaveSnapshotSuccess:
    metadata: SnapshotMetadata


@dataclass(frozen=True)
class SaveSnapshotFailure:
    metadata: SnapshotMetadata
    cause: str


@dataclass(frozen=True)
class DeleteSnapshot:
    metadata: SnapshotMetadata


@dataclass(frozen=True)
class DeleteSnapshotSuccess:
    metadata: SnapshotMetadata


@dataclass(frozen=True)
class DeleteSnapshotFailure:
    metadata: SnapshotMetadata
    cause: str


@dataclass(frozen=True)
class DeleteSnapshots:
    persistence_id: str
    criteria: SnapshotSelectionCriteria


@dataclass(frozen=True)
class DeleteSnapshotsSuccess:
    criteria: SnapshotSelectionCriteria


@dataclass(frozen=True)
class DeleteSnapshotsFailure:
    criteria: SnapshotSelectionCriteria
    cause: str


# -- recovery config (reference: Persistence.scala Recovery) -----------------

@dataclass(frozen=True)
class Recovery:
    from_snapshot: SnapshotSelectionCriteria = SnapshotSelectionCriteria()
    to_sequence_nr: int = 2**63 - 1
    replay_max: int = 2**63 - 1

    @staticmethod
    def default() -> "Recovery":
        return Recovery()

    @staticmethod
    def none() -> "Recovery":
        return Recovery(from_snapshot=SnapshotSelectionCriteria.none(),
                        to_sequence_nr=0, replay_max=0)


@dataclass(frozen=True)
class RecoveryCompleted:
    pass


RECOVERY_COMPLETED = RecoveryCompleted()
