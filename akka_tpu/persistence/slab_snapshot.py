"""Checkpoint/resume of the TPU-batched runtime: snapshot the SoA slabs.

SURVEY.md §2.10 item 8 / §5 checkpoint-resume: "snapshot = dump of SoA state
tensors (orbax), journal = append-only host log of message batches; replay =
re-running jitted steps". This module is that snapshot half for
akka_tpu.batched.BatchedSystem: every device-resident slab (per-column actor
state, behavior ids, alive mask, inbox tensors, step counter) is serialized
as one pytree.

Uses orbax-checkpoint when importable (async-friendly, TPU-native sharding
aware) and falls back to a .npz file — the pytree layout is identical, so
the two formats are feature-equivalent for single-host slabs.

Journal-side replay integration: JournalPlugin stores inbox batches via
`record_step_batch`, and `replay_steps` re-applies them to a restored system
— the reference's event replay (persistence/Eventsourced.scala recovery)
with "event" = one step's message batch.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np


_SLAB_KEYS = ("behavior_id", "alive", "step_count", "inbox_dst",
              "inbox_type", "inbox_payload", "inbox_valid")


def slab_pytree(system) -> Dict[str, Any]:
    """Extract the full device state of a BatchedSystem (or
    ShardedBatchedSystem) as a pytree of HOST copies. Copies are mandatory:
    the step functions donate their input buffers, so a snapshot of live
    device arrays would be deleted by the very next `run()`."""
    tree: Dict[str, Any] = {
        "state": {k: np.asarray(jax.device_get(v))
                  for k, v in system.state.items()}}
    for k in _SLAB_KEYS:
        v = getattr(system, k, None)
        if v is not None:
            tree[k] = np.asarray(jax.device_get(v))
    return tree


def _put_like(system, arr, current) -> Any:
    """Re-place a restored array with the sharding its predecessor had
    (a sharded system's slabs must go back onto the mesh, not onto the
    default device). Sharding metadata survives donation, so `current`
    may be a deleted array and still answer .sharding."""
    a = jnp.asarray(arr)
    try:
        sharding = current.sharding
    except Exception:  # noqa: BLE001 — plain single-device system
        return a
    return jax.device_put(a, sharding)


def restore_slab_pytree(system, tree: Dict[str, Any]) -> None:
    """Load a pytree produced by slab_pytree back into `system` (shapes must
    match: same capacity/out_degree/payload schema)."""
    for col, arr in tree["state"].items():
        cur = system.state.get(col)
        if cur is not None and tuple(cur.shape) != tuple(arr.shape):
            raise ValueError(
                f"slab shape mismatch for state[{col!r}]: "
                f"{tuple(arr.shape)} vs {tuple(cur.shape)}")
        system.state[col] = _put_like(system, arr, cur)
    for k in _SLAB_KEYS:
        if k not in tree:
            continue  # older snapshot without this column
        cur = getattr(system, k, None)
        arr = tree[k]
        if cur is None:
            continue
        if hasattr(cur, "shape") and tuple(cur.shape) != tuple(
                np.asarray(arr).shape):
            raise ValueError(f"slab shape mismatch for {k}: "
                             f"{np.asarray(arr).shape} vs {tuple(cur.shape)}")
        setattr(system, k, _put_like(system, arr, cur))


def _try_orbax():
    try:
        import orbax.checkpoint as ocp
        return ocp
    except Exception:  # noqa: BLE001 — orbax optional at runtime
        return None


def save_slabs(system, directory: str, step: Optional[int] = None) -> str:
    """Snapshot `system` under `directory`; returns the checkpoint path."""
    tree = jax.tree_util.tree_map(np.asarray, slab_pytree(system))
    ocp = _try_orbax()
    name = f"slab-{step if step is not None else int(tree['step_count'])}"
    path = os.path.join(os.path.abspath(directory), name)
    if ocp is not None:
        ckpt = ocp.PyTreeCheckpointer()
        ckpt.save(path, tree, force=True)
        return path
    os.makedirs(directory, exist_ok=True)
    flat = {}
    for col, arr in tree["state"].items():
        flat[f"state.{col}"] = arr
    for k in _SLAB_KEYS:
        flat[k] = tree[k]
    np.savez(path + ".npz", **flat)
    return path + ".npz"


def restore_slabs(system, path: str) -> None:
    """Restore a snapshot written by save_slabs into `system`."""
    if path.endswith(".npz"):
        with np.load(path) as data:
            tree: Dict[str, Any] = {"state": {}}
            for k in data.files:
                if k.startswith("state."):
                    tree["state"][k[len("state."):]] = data[k]
                else:
                    tree[k] = data[k]
        restore_slab_pytree(system, tree)
        return
    ocp = _try_orbax()
    if ocp is None:
        raise RuntimeError("orbax not available and path is not .npz")
    tree = ocp.PyTreeCheckpointer().restore(path)
    restore_slab_pytree(system, tree)


def latest_slab_path(directory: str) -> Optional[str]:
    if not os.path.isdir(directory):
        return None
    best, best_step = None, -1
    for name in os.listdir(directory):
        if not name.startswith("slab-"):
            continue
        stem = name[len("slab-"):]
        stem = stem[:-4] if stem.endswith(".npz") else stem
        try:
            step = int(stem)
        except ValueError:
            continue
        if step > best_step:
            best, best_step = os.path.join(directory, name), step
    return best
