"""Checkpoint/resume of the TPU-batched runtime: snapshot the SoA slabs.

SURVEY.md §2.10 item 8 / §5 checkpoint-resume: "snapshot = dump of SoA state
tensors (orbax), journal = append-only host log of message batches; replay =
re-running jitted steps". This module is that snapshot half for
akka_tpu.batched.BatchedSystem: every device-resident slab (per-column actor
state, behavior ids, alive mask, inbox tensors, step counter, supervision
counters, attention word) is serialized as one pytree.

Schema v3 (docs/CHECKPOINT_RECOVERY.md has the full layout): v1 carried only
the seven core slabs and silently dropped the supervision aggregates added
since — a restore of a v1 snapshot into a supervised system would resume
with whatever stale counters the target happened to hold. v2 adds
`mail_dropped`, `sup_counts`, `attention` and the sharded `dropped` block
plus an explicit `schema_version` field. v3 adds the telemetry plane:
the `metrics` histogram slab and the `inbox_enq` enqueue-step column
(docs/OBSERVABILITY.md) — both are derived telemetry whose shapes depend
on whether metrics are compiled in, so on shape mismatch they zero-fill
instead of failing the restore (like `attention`). The loader still
accepts v1/v2 snapshots and ZERO-FILLS (with `reserved_fill`) every live
slab the snapshot does not carry, so the restored state is a pure function
of the snapshot file, never of the pre-restore target.

Uses orbax-checkpoint when importable (async-friendly, TPU-native sharding
aware) and falls back to a .npz file — the pytree layout is identical, so
the two formats are feature-equivalent for single-host slabs. The .npz
fallback writes tmp + fsync + os.replace, so a crash mid-save leaves the
previous snapshot intact instead of a torn file.

Journal-side replay integration: JournalPlugin stores inbox batches via
`record_step_batch`, and `replay_steps` re-applies them to a restored system
— the reference's event replay (persistence/Eventsourced.scala recovery)
with "event" = one step's message batch. The write-ahead tell journal
(persistence/tell_journal.py) is the crash-recovery counterpart: staged
batches are logged BEFORE enqueue and replayed past the snapshot's step.
"""

from __future__ import annotations

import os
import shutil
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

SCHEMA_VERSION = 3

# v1 slabs: core actor/inbox tensors (pre-supervision snapshots carry only
# these).
_SLAB_KEYS_V1 = ("behavior_id", "alive", "step_count", "inbox_dst",
                 "inbox_type", "inbox_payload", "inbox_valid")
# v2 additions: supervision aggregates + the attention word. `dropped`
# exists only on ShardedBatchedSystem; getattr-None skips it elsewhere.
_SLAB_KEYS_V2 = ("mail_dropped", "sup_counts", "attention", "dropped")
# v3 additions: the telemetry plane — the device metric slab and the
# per-row enqueue-step column feeding the sojourn histogram. Shapes vary
# with metrics_on / shard count, so mismatches zero-fill (see below).
_SLAB_KEYS_V3 = ("metrics", "inbox_enq")
_SLAB_KEYS = _SLAB_KEYS_V1 + _SLAB_KEYS_V2 + _SLAB_KEYS_V3

# Derived telemetry, not source state: a layout change across runtimes
# zero-fills instead of raising, and the next step/drain repopulates it.
_ZERO_FILL_ON_MISMATCH = ("attention", "metrics", "inbox_enq")


def _reserved_fill(col: str) -> int:
    from ..batched.supervision import reserved_fill
    return reserved_fill(col)


def slab_pytree(system) -> Dict[str, Any]:
    """Extract the full device state of a BatchedSystem (or
    ShardedBatchedSystem) as a pytree of HOST copies. Copies are mandatory:
    the step functions donate their input buffers, so a snapshot of live
    device arrays would be deleted by the very next `run()`. Callers must
    quiesce first (`block_until_ready()`); the system-level `checkpoint()`
    entry points do."""
    tree: Dict[str, Any] = {
        "schema_version": np.int64(SCHEMA_VERSION),
        "state": {k: np.asarray(jax.device_get(v))
                  for k, v in system.state.items()}}
    for k in _SLAB_KEYS:
        v = getattr(system, k, None)
        # zero-size slabs (inbox_enq with metrics compiled out) are
        # omitted: tensorstore refuses empty params, and the restore path
        # zero-fills absent v3 keys anyway
        if v is not None and getattr(v, "size", 1) != 0:
            tree[k] = np.asarray(jax.device_get(v))
    return tree


def _put_like(system, arr, current) -> Any:
    """Re-place a restored array with the sharding its predecessor had
    (a sharded system's slabs must go back onto the mesh, not onto the
    default device). Sharding metadata survives donation, so `current`
    may be a deleted array and still answer .sharding."""
    a = jnp.asarray(arr)
    try:
        sharding = current.sharding
    except Exception:  # noqa: BLE001 — plain single-device system
        return a
    return jax.device_put(a, sharding)


def restore_slab_pytree(system, tree: Dict[str, Any]) -> None:
    """Load a pytree produced by slab_pytree back into `system` (shapes must
    match: same capacity/out_degree/payload schema).

    Version handling: snapshots without `schema_version` are v1. Any live
    state column or v2 slab the snapshot lacks is reset to its
    `reserved_fill` value — a v1 snapshot restored into a supervised system
    yields zeroed retry counters / re-armed backoff deadlines, not the
    target's stale pre-restore values. Snapshot columns the target does not
    declare are skipped (a behavior-schema change is the caller's problem,
    not a KeyError)."""
    version = int(np.asarray(tree.get("schema_version", 1)))
    if version > SCHEMA_VERSION:
        raise ValueError(
            f"snapshot schema v{version} is newer than this runtime's "
            f"v{SCHEMA_VERSION}; upgrade the runtime to restore it")
    for col, arr in tree["state"].items():
        cur = system.state.get(col)
        if cur is None:
            continue  # column no longer in the target's schema
        if tuple(cur.shape) != tuple(arr.shape):
            raise ValueError(
                f"slab shape mismatch for state[{col!r}]: "
                f"{tuple(arr.shape)} vs {tuple(cur.shape)}")
        system.state[col] = _put_like(system, arr, cur)
    for col, cur in list(system.state.items()):
        if col not in tree["state"]:
            # v1 upgrade path: supervision columns absent from the
            # snapshot reset to their re-arm fill, for determinism
            fill = jnp.full(cur.shape, _reserved_fill(col), cur.dtype)
            system.state[col] = _put_like(system, fill, cur)
    for k in _SLAB_KEYS:
        cur = getattr(system, k, None)
        if cur is None:
            continue  # slab the target does not have (e.g. `dropped`)
        if k in tree:
            arr = tree[k]
            if hasattr(cur, "shape") and tuple(cur.shape) != tuple(
                    np.asarray(arr).shape):
                if k in _ZERO_FILL_ON_MISMATCH:
                    # derived telemetry, not source state: a layout change
                    # (the 4-word pre-progress-lane attention format,
                    # per-shard rows from another mesh, or a metrics-on/off
                    # flip) zero-fills and the first restored step repacks
                    setattr(system, k, _put_like(
                        system, jnp.zeros(cur.shape, cur.dtype), cur))
                    continue
                raise ValueError(
                    f"slab shape mismatch for {k}: "
                    f"{np.asarray(arr).shape} vs {tuple(cur.shape)}")
            setattr(system, k, _put_like(system, arr, cur))
        elif k in _SLAB_KEYS_V2 or k in _SLAB_KEYS_V3:
            # older snapshot: the aggregate never existed — zero it
            fill = jnp.zeros(cur.shape, cur.dtype)
            setattr(system, k, _put_like(system, fill, cur))


def _try_orbax():
    try:
        import orbax.checkpoint as ocp
        return ocp
    except Exception:  # noqa: BLE001 — orbax optional at runtime
        return None


def save_slabs(system, directory: str, step: Optional[int] = None) -> str:
    """Snapshot `system` under `directory`; returns the checkpoint path."""
    return save_slab_tree(slab_pytree(system), directory, step)


def save_slab_tree(tree: Dict[str, Any], directory: str,
                   step: Optional[int] = None) -> str:
    """Serialize an already host-gathered slab pytree (`slab_pytree`
    output) under `directory`. Split from save_slabs so the hot re-shard
    path (sentinel.scale_to) can take the host copies at the drain barrier
    and overlap THIS — the fsync'd disk write — with the rebuild on the
    new mesh, restoring directly from the in-memory tree."""
    tree = jax.tree_util.tree_map(np.asarray, tree)
    ocp = _try_orbax()
    name = f"slab-{step if step is not None else int(tree['step_count'])}"
    path = os.path.join(os.path.abspath(directory), name)
    if ocp is not None:
        ckpt = ocp.PyTreeCheckpointer()
        ckpt.save(path, tree, force=True)
        return path
    os.makedirs(directory, exist_ok=True)
    flat = {"schema_version": tree["schema_version"]}
    for col, arr in tree["state"].items():
        flat[f"state.{col}"] = arr
    for k in _SLAB_KEYS:
        if k in tree:
            flat[k] = tree[k]
    # tmp + fsync + rename: a crash mid-save must not tear the snapshot a
    # recovery is about to depend on
    final = path + ".npz"
    tmp = final + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **flat)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, final)
    return final


def load_slab_tree(path: str) -> Dict[str, Any]:
    """Read a snapshot back as the host-side pytree (no system needed) —
    the re-sharding restore path inspects shapes before placement."""
    if path.endswith(".npz"):
        with np.load(path) as data:
            tree: Dict[str, Any] = {"state": {}}
            for k in data.files:
                if k.startswith("state."):
                    tree["state"][k[len("state."):]] = data[k]
                else:
                    tree[k] = data[k]
        return tree
    ocp = _try_orbax()
    if ocp is None:
        raise RuntimeError("orbax not available and path is not .npz")
    return ocp.PyTreeCheckpointer().restore(path)


def restore_slabs(system, path: str) -> None:
    """Restore a snapshot written by save_slabs into `system`."""
    restore_slab_pytree(system, load_slab_tree(path))


def _slab_step(name: str) -> Optional[int]:
    if not name.startswith("slab-"):
        return None
    stem = name[len("slab-"):]
    stem = stem[:-4] if stem.endswith(".npz") else stem
    try:
        return int(stem)
    except ValueError:
        return None


def latest_slab_path(directory: str) -> Optional[str]:
    if not os.path.isdir(directory):
        return None
    best, best_step = None, -1
    for name in os.listdir(directory):
        step = _slab_step(name)
        if step is not None and step > best_step:
            best, best_step = os.path.join(directory, name), step
    return best


def gc_slabs(directory: str, keep: int) -> int:
    """Retained-snapshot GC: delete all but the `keep` newest snapshots in
    `directory`. Returns how many were removed. Both the .npz fallback
    (files) and orbax (directories) layouts are handled."""
    if keep <= 0 or not os.path.isdir(directory):
        return 0
    entries = []
    for name in os.listdir(directory):
        step = _slab_step(name)
        if step is not None:
            entries.append((step, name))
    entries.sort(reverse=True)
    removed = 0
    for _step, name in entries[keep:]:
        full = os.path.join(directory, name)
        try:
            if os.path.isdir(full):
                shutil.rmtree(full)
            else:
                os.remove(full)
            removed += 1
        except OSError:
            pass  # concurrent GC / permissions: stale snapshot stays
    return removed
