"""PersistentActor: event sourcing with persist/persistAsync + recovery.

Reference parity: akka-persistence/src/main/scala/akka/persistence/
Eventsourced.scala — persist appends to a batch and registers a stashing
handler invocation (:399), the batch is flushed to the journal AFTER the
current receive returns (:354-362), commands arriving while a `persist` is
in flight go to an internal stash (:218-233, single-writer per
persistenceId), recovery = permit → snapshot load → event replay →
RecoveryCompleted (RecoveryPermitter.scala, journal/AsyncRecovery.scala),
persistAsync skips the stashing, deferAsync sequences a handler after
in-flight persists. Journal write failure stops the actor; rejection keeps
it running (onPersistFailure/onPersistRejected semantics).
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Callable, List, Optional, Sequence

from ..actor.actor import Actor
from ..dispatch.mailbox import DequeBasedMessageQueue, Envelope
from .messages import (AtomicWrite, DeleteMessagesFailure,
                       DeleteMessagesSuccess, DeleteMessagesTo, LoadSnapshot,
                       LoadSnapshotFailed, LoadSnapshotResult, PersistentRepr,
                       Recovery, RecoveryCompleted, RecoverySuccess,
                       ReplayedMessage, ReplayMessages, ReplayMessagesFailure,
                       SaveSnapshot, SaveSnapshotFailure, SaveSnapshotSuccess,
                       SnapshotMetadata, SnapshotOffer, WriteMessageFailure,
                       WriteMessageRejected, WriteMessages,
                       WriteMessagesFailed, WriteMessagesSuccessful,
                       WriteMessageSuccess, DeleteSnapshot, DeleteSnapshots,
                       DeleteSnapshotSuccess, DeleteSnapshotsSuccess,
                       DeleteSnapshotFailure, DeleteSnapshotsFailure,
                       SnapshotSelectionCriteria)
from .persistence import (Persistence, RecoveryPermitGranted,
                          RequestRecoveryPermit, ReturnRecoveryPermit)


class _Invocation:
    """One queued handler: a persist/persistAsync awaiting its write
    confirmation, or a deferAsync awaiting all prior persists. Carries the
    envelope of the command that initiated it so `self.sender` inside the
    handler is the ORIGINAL sender, not the journal (reference: Eventsourced
    keeps the current envelope across the journal round trip)."""

    __slots__ = ("handler", "stashing", "kind", "event", "envelope")

    def __init__(self, handler: Callable[[Any], None], stashing: bool,
                 kind: str = "persist", event: Any = None, envelope=None):
        self.handler = handler
        self.stashing = stashing
        self.kind = kind
        self.event = event
        self.envelope = envelope


class PersistentActor(Actor):
    """Subclass; implement persistence_id, receive_command, receive_recover.

    States (reference Eventsourced: waitingRecoveryPermit →
    recoveryStarted → recovering → processingCommands ⇄ persistingEvents).
    """

    mailbox_requirement = DequeBasedMessageQueue

    journal_plugin_id = ""          # "" -> akka.persistence.journal.plugin
    snapshot_plugin_id = ""

    def __init__(self) -> None:
        super().__init__()
        self._extension = Persistence.get(self.context.system)
        self._journal = self._extension.journal_for(self.journal_plugin_id)
        self._snapshot_store = self._extension.snapshot_store_for(
            self.snapshot_plugin_id)
        self._instance_id = self._extension.next_instance_id()
        self._writer_uuid = uuid.uuid4().hex
        self._last_sequence_nr = 0
        self._snapshot_sequence_nr = 0
        self._state = "waiting-permit"
        self._event_batch: List[AtomicWrite] = []   # built during one receive
        self._journal_batch: List[AtomicWrite] = []
        self._pending: List[_Invocation] = []       # in-flight handler queue
        self._pending_stash_count = 0               # stashing invocations only
        self._internal_stash: List[Envelope] = []
        self._recovery_highest = 0

    # -- user API -------------------------------------------------------------
    @property
    def persistence_id(self) -> str:
        raise NotImplementedError

    def receive_command(self, message: Any) -> Any:
        raise NotImplementedError

    def receive_recover(self, message: Any) -> Any:
        raise NotImplementedError

    @property
    def last_sequence_nr(self) -> int:
        return self._last_sequence_nr

    @property
    def snapshot_sequence_nr(self) -> int:
        return self._snapshot_sequence_nr

    @property
    def recovery_running(self) -> bool:
        return self._state in ("waiting-permit", "recovering-snapshot",
                               "recovering-events")

    def recovery(self) -> Recovery:
        """Override to customize (reference: PersistentActor.recovery)."""
        return Recovery()

    def persist(self, event: Any, handler: Callable[[Any], None]) -> None:
        """Store `event`; run `handler(event)` after the write is confirmed.
        Commands arriving in between are stashed (reference :399)."""
        self._pending.append(_Invocation(handler, stashing=True,
                                         envelope=self.context.current_message))
        self._pending_stash_count += 1
        self._event_batch.append(self._atomic([event]))

    def persist_all(self, events: Sequence[Any],
                    handler: Callable[[Any], None]) -> None:
        if not events:
            return
        for _ in events:
            self._pending.append(_Invocation(
                handler, stashing=True,
                envelope=self.context.current_message))
            self._pending_stash_count += 1
        self._event_batch.append(self._atomic(list(events)))

    def persist_async(self, event: Any, handler: Callable[[Any], None]) -> None:
        """Like persist but does NOT stash commands (reference :437)."""
        self._pending.append(_Invocation(handler, stashing=False,
                                         envelope=self.context.current_message))
        self._event_batch.append(self._atomic([event]))

    def defer_async(self, event: Any, handler: Callable[[Any], None]) -> None:
        """Run handler after all in-flight persists complete; nothing stored."""
        if not any(i.kind == "persist" for i in self._pending) \
                and not self._event_batch:
            handler(event)
        else:
            self._pending.append(_Invocation(
                handler, stashing=False, kind="defer", event=event,
                envelope=self.context.current_message))

    def delete_messages(self, to_sequence_nr: int) -> None:
        self._journal.tell(DeleteMessagesTo(self.persistence_id,
                                            to_sequence_nr, self.self_ref),
                           self.self_ref)

    def save_snapshot(self, snapshot: Any) -> None:
        md = SnapshotMetadata(self.persistence_id, self._last_sequence_nr,
                              time.time())
        self._snapshot_store.tell(SaveSnapshot(md, snapshot), self.self_ref)

    def delete_snapshot(self, sequence_nr: int) -> None:
        self._snapshot_store.tell(DeleteSnapshot(
            SnapshotMetadata(self.persistence_id, sequence_nr)), self.self_ref)

    def delete_snapshots(self, criteria: SnapshotSelectionCriteria) -> None:
        self._snapshot_store.tell(DeleteSnapshots(self.persistence_id,
                                                  criteria), self.self_ref)

    # -- failure hooks (reference: onPersistFailure/onPersistRejected/
    #    onRecoveryFailure — default logs; failure also stops the actor) -----
    def on_persist_failure(self, cause: str, event: Any, seq_nr: int) -> None:
        self.context.system.log.error(
            f"persist failure for {self.persistence_id} seq {seq_nr}: {cause}")

    def on_persist_rejected(self, cause: str, event: Any, seq_nr: int) -> None:
        self.context.system.log.error(
            f"persist rejected for {self.persistence_id} seq {seq_nr}: {cause}")

    def on_recovery_failure(self, cause: str, event: Optional[Any]) -> None:
        self.context.system.log.error(
            f"recovery failure for {self.persistence_id}: {cause}")

    # -- lifecycle ------------------------------------------------------------
    def pre_start(self) -> None:
        self._extension.recovery_permitter.tell(RequestRecoveryPermit(),
                                                self.self_ref)

    def post_stop(self) -> None:
        if self.recovery_running:
            self._extension.recovery_permitter.tell(ReturnRecoveryPermit(),
                                                    self.self_ref)

    # -- dispatch -------------------------------------------------------------
    def around_receive(self, receive: Callable[[Any], Any], msg: Any) -> None:
        if self._state == "waiting-permit":
            self._waiting_permit(msg)
        elif self._state == "recovering-snapshot":
            self._recovering_snapshot(msg)
        elif self._state == "recovering-events":
            self._recovering_events(msg)
        else:
            self._processing(msg)

    def receive(self, message: Any) -> Any:  # unused; around_receive routes
        return NotImplemented

    # -- state: waiting for recovery permit -----------------------------------
    def _waiting_permit(self, msg: Any) -> None:
        if isinstance(msg, RecoveryPermitGranted):
            rec = self.recovery()
            if rec.to_sequence_nr == 0 and rec.replay_max == 0 and \
                    rec.from_snapshot == SnapshotSelectionCriteria.none():
                # Recovery.none
                self._recovery_highest = 0
                self._finish_recovery()
                return
            self._state = "recovering-snapshot"
            self._snapshot_store.tell(
                LoadSnapshot(self.persistence_id, rec.from_snapshot,
                             rec.to_sequence_nr), self.self_ref)
        else:
            self._internal_stash.append(self._current_envelope())

    # -- state: loading snapshot ----------------------------------------------
    def _recovering_snapshot(self, msg: Any) -> None:
        rec = self.recovery()
        if isinstance(msg, LoadSnapshotResult):
            if msg.snapshot is not None:
                md = msg.snapshot.metadata
                self._last_sequence_nr = md.sequence_nr
                self._snapshot_sequence_nr = md.sequence_nr
                self._call_recover(SnapshotOffer(md, msg.snapshot.snapshot))
            self._state = "recovering-events"
            self._journal.tell(
                ReplayMessages(self._last_sequence_nr + 1, rec.to_sequence_nr,
                               rec.replay_max, self.persistence_id,
                               self.self_ref), self.self_ref)
        elif isinstance(msg, LoadSnapshotFailed):
            self.on_recovery_failure(msg.cause, None)
            self.context.stop(self.self_ref)
        else:
            self._internal_stash.append(self._current_envelope())

    # -- state: replaying events ----------------------------------------------
    def _recovering_events(self, msg: Any) -> None:
        if isinstance(msg, ReplayedMessage):
            r = msg.persistent
            self._last_sequence_nr = r.sequence_nr
            try:
                self._call_recover(r.payload)
            except Exception as e:  # noqa: BLE001
                self.on_recovery_failure(str(e), r.payload)
                raise
        elif isinstance(msg, RecoverySuccess):
            self._recovery_highest = msg.highest_sequence_nr
            self._last_sequence_nr = max(self._last_sequence_nr,
                                         msg.highest_sequence_nr)
            self._finish_recovery()
        elif isinstance(msg, ReplayMessagesFailure):
            self.on_recovery_failure(msg.cause, None)
            self.context.stop(self.self_ref)
        else:
            self._internal_stash.append(self._current_envelope())

    def _finish_recovery(self) -> None:
        self._state = "processing"
        self._extension.recovery_permitter.tell(ReturnRecoveryPermit(),
                                                self.self_ref)
        self._call_recover(RecoveryCompleted())
        self._flush_batch()  # RecoveryCompleted handler may have persisted
        self._unstash_internal()

    def _call_recover(self, msg: Any) -> None:
        handled = self.receive_recover(msg)
        if handled is NotImplemented and not isinstance(msg, RecoveryCompleted):
            self.unhandled(msg)

    # -- state: processing commands / persisting ------------------------------
    def _processing(self, msg: Any) -> None:
        if isinstance(msg, WriteMessageSuccess):
            if msg.actor_instance_id != self._instance_id:
                return
            self._last_sequence_nr = max(self._last_sequence_nr,
                                         msg.persistent.sequence_nr)
            self._pop_invocation(msg.persistent.payload)
        elif isinstance(msg, WriteMessageRejected):
            if msg.actor_instance_id != self._instance_id:
                return
            self.on_persist_rejected(msg.cause, msg.persistent.payload,
                                     msg.persistent.sequence_nr)
            self._pop_invocation(msg.persistent.payload, run_handler=False)
        elif isinstance(msg, WriteMessageFailure):
            if msg.actor_instance_id != self._instance_id:
                return
            self.on_persist_failure(msg.cause, msg.persistent.payload,
                                    msg.persistent.sequence_nr)
            self.context.stop(self.self_ref)
        elif isinstance(msg, (WriteMessagesSuccessful, WriteMessagesFailed)):
            pass  # per-message replies drive the state machine
        elif isinstance(msg, (SaveSnapshotSuccess, SaveSnapshotFailure,
                              DeleteMessagesSuccess, DeleteMessagesFailure,
                              DeleteSnapshotSuccess, DeleteSnapshotsSuccess,
                              DeleteSnapshotFailure, DeleteSnapshotsFailure)):
            if isinstance(msg, SaveSnapshotSuccess):
                self._snapshot_sequence_nr = msg.metadata.sequence_nr
            self._forward_to_command(msg)
        elif self._pending_stash_count > 0:
            # a stashing persist is in flight: defer user commands
            self._internal_stash.append(self._current_envelope())
        else:
            self._forward_to_command(msg)
            self._flush_batch()

    def _forward_to_command(self, msg: Any) -> None:
        handled = self.receive_command(msg)
        if handled is NotImplemented:
            self.unhandled(msg)

    def _flush_batch(self) -> None:
        """Send events persisted during this receive to the journal
        (reference: flushBatch / sendBatchedEventsToJournal :354-362)."""
        if not self._event_batch:
            return
        writes, self._event_batch = self._event_batch, []
        self._journal.tell(
            WriteMessages(tuple(writes), self.self_ref, self._instance_id),
            self.self_ref)

    def _atomic(self, events: List[Any]) -> AtomicWrite:
        reprs = []
        for ev in events:
            seq = self._alloc_seq_nr()
            reprs.append(PersistentRepr(ev, seq, self.persistence_id,
                                        writer_uuid=self._writer_uuid))
        return AtomicWrite(tuple(reprs))

    def _alloc_seq_nr(self) -> int:
        nxt = max(self._last_sequence_nr,
                  getattr(self, "_allocated_seq", 0)) + 1
        self._allocated_seq = nxt
        return nxt

    def _pop_invocation(self, payload: Any, run_handler: bool = True) -> None:
        if not self._pending:
            return
        inv = self._pending.pop(0)  # the persist this confirmation is for
        if inv.stashing:
            self._pending_stash_count -= 1
        if run_handler:
            self._run_with_envelope(inv, lambda: inv.handler(payload))
        # defers queued right after it only waited on that persist
        while self._pending and self._pending[0].kind == "defer":
            d = self._pending.pop(0)
            self._run_with_envelope(d, lambda: d.handler(d.event))
        self._flush_batch()  # handlers may have called persist again
        if self._pending_stash_count == 0:
            self._unstash_internal()

    def _run_with_envelope(self, inv: _Invocation, fn: Callable[[], None]
                           ) -> None:
        """Run a handler with self.sender restored to the initiating
        command's sender (the cell's current message is the journal reply)."""
        cell = self.context
        saved_env, saved_sender = cell.current_message, cell.sender
        if inv.envelope is not None:
            cell.current_message = inv.envelope
            cell.sender = (inv.envelope.sender
                           if inv.envelope.sender is not None
                           else cell.system.dead_letters)
        try:
            fn()
        finally:
            cell.current_message, cell.sender = saved_env, saved_sender

    # -- internal stash mechanics ---------------------------------------------
    def _current_envelope(self) -> Envelope:
        env = self.context.current_message
        if env is None:
            raise RuntimeError("no current message")
        return env

    def _unstash_internal(self) -> None:
        if not self._internal_stash:
            return
        mq = self.context.mailbox.message_queue
        if not isinstance(mq, DequeBasedMessageQueue):
            raise RuntimeError("PersistentActor requires a deque mailbox")
        for env in reversed(self._internal_stash):
            mq.enqueue_first(self.context.self_ref, env)
        self._internal_stash = []
