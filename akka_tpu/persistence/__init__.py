"""Persistence (event sourcing): akka-persistence equivalent (SURVEY.md §2.8).

Classic PersistentActor with persist/persistAsync + recovery, typed
EventSourcedBehavior with the Effect API, journal/snapshot plugin SPI with
in-mem and append-only-file implementations, AtLeastOnceDelivery,
persistence-query, a programmable-failure testkit journal, TCK compliance
suites, and TPU slab snapshots (orbax/npz) for the batched runtime.
"""

from .messages import (AtomicWrite, DeleteMessagesFailure,  # noqa: F401
                       DeleteMessagesSuccess, DeleteSnapshotsSuccess,
                       DeleteSnapshotSuccess, LoadSnapshot, LoadSnapshotResult,
                       PersistentRepr, Recovery, RecoveryCompleted,
                       RecoverySuccess, ReplayedMessage, ReplayMessages,
                       SaveSnapshot, SaveSnapshotFailure, SaveSnapshotSuccess,
                       SelectedSnapshot, SnapshotMetadata, SnapshotOffer,
                       SnapshotSelectionCriteria, Tagged, WriteMessages)
from .journal import (FileJournal, InMemJournal, JournalActor,  # noqa: F401
                      JournalPlugin, SharedInMemStore)
from .snapshot import (InMemSnapshotStore, LocalSnapshotStore,  # noqa: F401
                       SnapshotPlugin, SnapshotStoreActor)
from .persistence import (JOURNAL_FILE, JOURNAL_INMEM,  # noqa: F401
                          Persistence, RecoveryPermitter, SNAPSHOT_INMEM,
                          SNAPSHOT_LOCAL)
from .eventsourced import PersistentActor  # noqa: F401
from .adapter import (EventAdapter, EventAdapters, EventSeq,  # noqa: F401
                      IdentityEventAdapter, SnapshotAdapter)
from .at_least_once import (AtLeastOnceDelivery,  # noqa: F401
                            AtLeastOnceDeliverySnapshot,
                            MaxUnconfirmedMessagesExceededException,
                            UnconfirmedDelivery, UnconfirmedWarning)
from .typed import (Effect, EventSourcedBehavior,  # noqa: F401
                    PersistenceId, RetentionCriteria)
from .query import (EventEnvelope, EventStream, NoOffset,  # noqa: F401
                    PersistenceQuery, ReadJournal, Sequence)
from .entity_journal import EntityJournal, OP_ADD  # noqa: F401
from .testkit import (FailIf, FailNextN, PassAll,  # noqa: F401
                      PersistenceTestKitJournal, ProcessingPolicy,
                      RejectNextN, journal_tck, snapshot_store_tck)
from . import slab_snapshot  # noqa: F401

__all__ = [
    "PersistentRepr", "AtomicWrite", "Tagged", "Recovery",
    "RecoveryCompleted", "SnapshotOffer", "SnapshotMetadata",
    "SnapshotSelectionCriteria", "SelectedSnapshot",
    "SaveSnapshotSuccess", "SaveSnapshotFailure", "DeleteMessagesSuccess",
    "JournalPlugin", "InMemJournal", "FileJournal", "JournalActor",
    "SharedInMemStore",
    "SnapshotPlugin", "InMemSnapshotStore", "LocalSnapshotStore",
    "SnapshotStoreActor",
    "Persistence", "RecoveryPermitter",
    "JOURNAL_INMEM", "JOURNAL_FILE", "SNAPSHOT_INMEM", "SNAPSHOT_LOCAL",
    "PersistentActor",
    "EventAdapter", "EventAdapters", "EventSeq", "IdentityEventAdapter",
    "SnapshotAdapter",
    "AtLeastOnceDelivery", "AtLeastOnceDeliverySnapshot",
    "UnconfirmedDelivery", "UnconfirmedWarning",
    "MaxUnconfirmedMessagesExceededException",
    "EventSourcedBehavior", "Effect", "PersistenceId", "RetentionCriteria",
    "PersistenceQuery", "ReadJournal", "EventEnvelope", "EventStream",
    "Sequence", "NoOffset",
    "EntityJournal", "OP_ADD",
    "PersistenceTestKitJournal", "ProcessingPolicy", "PassAll", "FailNextN",
    "RejectNextN", "FailIf", "journal_tck", "snapshot_store_tck",
    "slab_snapshot",
]
