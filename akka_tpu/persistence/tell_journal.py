"""Write-ahead tell journal for the batched runtime (ISSUE 4 tentpole #2).

SURVEY.md §5: "journal = append-only host log of message batches; replay =
re-running jitted steps". Every host-staged batch (`tell` / `seed_inbox`) is
appended to an fsync'd, length-prefixed record log BEFORE it is enqueued
toward the device, tagged with the host-side dispatched-step counter at
staging time. Recovery = load the latest slab snapshot (step S), then replay
journal records with step >= S: each record is re-staged once the replaying
system has been stepped to the record's counter, so the batch is flushed
into the same step that delivered it originally. Pure steps between records
are simply re-run — the jitted step function is deterministic, so the
replayed run is bit-identical to the crashed one up to the crash frontier.

Why `step >= S` is exactly right: staging and stepping serialize on the
system lock, and a batch staged while the counter reads c is flushed by
dispatch c+1. A snapshot at quiescent step S therefore reflects every batch
with c <= S-1 and none with c >= S; replaying the latter (and only the
latter) reconstructs the host staging buffers as they were. `seed_inbox`
writes device slots directly, so a seed record at exactly step S may already
be visible in the snapshot — replaying it overwrites the same slots with
the same values, an idempotent no-op.

Torn tails (kill -9 mid-append) are truncated on open via
journal.repair_record_log with a flight-recorder warning, mirroring the
FileJournal record log this format extends.
"""

from __future__ import annotations

import os
import pickle
import threading
from typing import Any, Dict, Iterator, Optional

import numpy as np

from .journal import repair_record_log, scan_record_log

KIND_TELL = "tell"
KIND_SEED = "seed"


class TellJournal:
    """Append-only WAL of staged tell batches, one file.

    Records are dicts {step, kind, dst, mtype, payload} with numpy payloads
    (host copies — the journal must not pin device buffers). Appends are
    atomic-at-the-record: 8-byte little-endian length prefix + pickle +
    flush + fsync, the FileJournal record idiom.
    """

    def __init__(self, path: str, flight_recorder: Optional[Any] = None,
                 fsync_every_n: int = 1):
        self.path = path
        self.flight_recorder = flight_recorder
        # group commit (akka.persistence.tell-journal.fsync-every-n): fsync
        # once per n appends instead of per record. Every append still
        # flush()es to the OS page cache, so a PROCESS crash (kill -9)
        # loses nothing either way — the batch window only widens the
        # machine-crash exposure to at most n-1 records, and the torn-tail
        # repair path below already truncates any partial batch boundary.
        # Default 1 is bit-identical to the original per-record fsync.
        self.fsync_every_n = max(1, int(fsync_every_n))
        self._since_fsync = 0
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self.truncated_bytes = repair_record_log(path, flight_recorder)
        self._lock = threading.Lock()
        self._fh = open(path, "ab")

    # -- write side ----------------------------------------------------------
    def append(self, step: int, kind: str, dst, payload, mtype) -> None:
        rec: Dict[str, Any] = {
            "step": int(step),
            "kind": kind,
            "dst": np.ascontiguousarray(np.asarray(dst)),
            "mtype": np.ascontiguousarray(np.asarray(mtype)),
            "payload": np.ascontiguousarray(np.asarray(payload)),
        }
        blob = pickle.dumps(rec, protocol=4)
        with self._lock:
            if self._fh is None:
                raise ValueError("TellJournal is closed")
            self._fh.write(len(blob).to_bytes(8, "little"))
            self._fh.write(blob)
            self._fh.flush()
            self._since_fsync += 1
            if self._since_fsync >= self.fsync_every_n:
                os.fsync(self._fh.fileno())
                self._since_fsync = 0

    def sync(self) -> None:
        """Force the deferred group-commit fsync (batch boundary)."""
        with self._lock:
            if self._fh is not None and self._since_fsync:
                self._fh.flush()
                os.fsync(self._fh.fileno())
                self._since_fsync = 0

    # -- read side -----------------------------------------------------------
    def records(self) -> Iterator[Dict[str, Any]]:
        """Iterate intact records oldest-first (reads the file; safe while
        the append handle is open — appends are flushed per-record)."""
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
        for _end, obj in scan_record_log(self.path):
            yield obj

    # -- maintenance ---------------------------------------------------------
    def compact(self, before_step: int) -> int:
        """Drop records with step < before_step (already covered by a
        snapshot at that step). Rewrites atomically: tmp + fsync + replace,
        then reopens the append handle. Returns records retained."""
        kept = [rec for rec in self.records()
                if int(rec["step"]) >= int(before_step)]
        tmp = self.path + ".tmp"
        with self._lock:
            with open(tmp, "wb") as f:
                for rec in kept:
                    blob = pickle.dumps(rec, protocol=4)
                    f.write(len(blob).to_bytes(8, "little"))
                    f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            if self._fh is not None:
                self._fh.close()
            os.replace(tmp, self.path)
            self._fh = open(self.path, "ab")
            self._since_fsync = 0  # the rewrite was fsync'd whole
        return len(kept)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                if self._since_fsync:
                    self._fh.flush()
                    os.fsync(self._fh.fileno())
                    self._since_fsync = 0
                self._fh.close()
                self._fh = None


def replay_journal(system, journal: TellJournal) -> int:
    """Replay journaled batches recorded at/after the system's restored
    step counter, stepping the system forward so each batch is staged at
    the same counter it was staged at originally. Re-journaling is
    suspended for the duration (the records already exist). Returns the
    final host step counter — the crash frontier's last fully-dispatched
    step; batches staged but not yet flushed at the crash are left staged,
    exactly as they were."""
    start = system._host_step
    saved, system.tell_journal = system.tell_journal, None
    try:
        for rec in journal.records():
            step = int(rec["step"])
            if step < start:
                continue
            while system._host_step < step:
                system.step()
            if rec["kind"] == KIND_SEED:
                system.seed_inbox(rec["dst"], rec["payload"], rec["mtype"])
            else:
                system.tell(rec["dst"], rec["payload"], rec["mtype"])
    finally:
        system.tell_journal = saved
    return system._host_step
