"""Typed event sourcing: EventSourcedBehavior + Effect API.

Reference parity: akka-persistence-typed/src/main/scala/akka/persistence/
typed/internal/ — the phase chain RequestingRecoveryPermit.scala →
ReplayingSnapshot.scala → ReplayingEvents.scala → Running.scala;
EventSourcedBehaviorImpl.scala (persistenceId/emptyState/commandHandler/
eventHandler + snapshotWhen/retention/tagger); EffectImpl.scala (Persist/
PersistAll/None/Unhandled/Stop + side effects ThenRun/ThenReply/ThenStop);
RetentionCriteriaImpl.scala (snapshotEvery N keep K, optional delete-events).

Commands arriving during recovery or while a persist is being confirmed are
stashed and replayed in order (Running.scala persistingEvents stash).
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Sequence, Tuple

from ..typed.behavior import Behavior, ExtensibleBehavior, Signal, UNHANDLED
from ..typed.behaviors import Behaviors
from .messages import (AtomicWrite, LoadSnapshot, LoadSnapshotFailed,
                       LoadSnapshotResult, PersistentRepr, RecoveryCompleted,
                       RecoverySuccess, ReplayedMessage, ReplayMessages,
                       ReplayMessagesFailure, SaveSnapshot,
                       SaveSnapshotFailure, SaveSnapshotSuccess,
                       SnapshotMetadata, SnapshotSelectionCriteria, Tagged,
                       DeleteMessagesTo, DeleteSnapshots,
                       WriteMessageFailure, WriteMessageRejected,
                       WriteMessages, WriteMessagesFailed,
                       WriteMessagesSuccessful, WriteMessageSuccess)
from .persistence import (Persistence, RecoveryPermitGranted,
                          RequestRecoveryPermit, ReturnRecoveryPermit)


@dataclass(frozen=True)
class PersistenceId:
    """(reference: typed/PersistenceId.scala — "EntityType|entityId")"""
    id: str

    @staticmethod
    def of(entity_type: str, entity_id: str, separator: str = "|"
           ) -> "PersistenceId":
        return PersistenceId(f"{entity_type}{separator}{entity_id}")

    @staticmethod
    def of_unique_id(id_: str) -> "PersistenceId":
        return PersistenceId(id_)


# -- Effect API (reference: EffectImpl.scala / Effect.scala) -----------------

class Effect:
    """Returned by the command handler."""

    __slots__ = ("events", "kind", "side_effects")

    def __init__(self, kind: str, events: Tuple[Any, ...] = (),
                 side_effects: Tuple = ()):
        self.kind = kind            # persist | none | unhandled | stop | stash
        self.events = events
        self.side_effects = side_effects

    # -- constructors --------------------------------------------------------
    @staticmethod
    def persist(*events: Any) -> "Effect":
        """Effect.persist(ev) or Effect.persist(ev1, ev2) or
        Effect.persist([ev1, ev2]). A tuple is ONE event (events are often
        tuples); only a list is treated as a collection."""
        if len(events) == 1 and isinstance(events[0], list):
            events = tuple(events[0])
        return Effect("persist", tuple(events))

    @staticmethod
    def none() -> "Effect":
        return Effect("none")

    @staticmethod
    def unhandled() -> "Effect":
        return Effect("unhandled")

    @staticmethod
    def stop() -> "Effect":
        return Effect("stop")

    @staticmethod
    def stash() -> "Effect":
        return Effect("stash")

    @staticmethod
    def reply(reply_to, message: Any) -> "Effect":
        return Effect("none").then_reply(reply_to, lambda _s: message)

    # -- chained side effects (run AFTER events are persisted) ---------------
    def then_run(self, fn: Callable[[Any], None]) -> "Effect":
        return Effect(self.kind, self.events,
                      self.side_effects + (("run", fn),))

    def then_reply(self, reply_to, message_fn: Callable[[Any], Any]) -> "Effect":
        return Effect(self.kind, self.events,
                      self.side_effects + (("reply", reply_to, message_fn),))

    def then_stop(self) -> "Effect":
        return Effect(self.kind, self.events,
                      self.side_effects + (("stop",),))

    def then_no_reply(self) -> "Effect":
        return self


@dataclass(frozen=True)
class RetentionCriteria:
    """(reference: RetentionCriteriaImpl.scala)"""
    snapshot_every: int = 0
    keep_n_snapshots: int = 2
    delete_events_on_snapshot: bool = False

    @staticmethod
    def snapshot_every_n(n: int, keep: int = 2,
                         delete_events: bool = False) -> "RetentionCriteria":
        return RetentionCriteria(n, keep, delete_events)


class EventSourcedBehavior(ExtensibleBehavior):
    """Typed ES behavior: command_handler(state, cmd) -> Effect,
    event_handler(state, event) -> state.

    Spawn like any Behavior; internally drives the journal protocol through
    the reference's phase chain.
    """

    def __init__(self, persistence_id: PersistenceId, empty_state: Any,
                 command_handler: Callable[[Any, Any], Effect],
                 event_handler: Callable[[Any, Any], Any],
                 retention: Optional[RetentionCriteria] = None,
                 snapshot_when: Optional[Callable[[Any, Any, int], bool]] = None,
                 tagger: Optional[Callable[[Any], frozenset]] = None,
                 on_signal: Optional[Callable[[Any, Signal], None]] = None,
                 recovery_completed: Optional[Callable[[Any, Any], None]] = None,
                 journal_plugin_id: str = "", snapshot_plugin_id: str = "",
                 snapshot_adapter=None, event_adapter=None):
        self.persistence_id = persistence_id
        self.empty_state = empty_state
        self.command_handler = command_handler
        self.event_handler = event_handler
        self.retention = retention or RetentionCriteria()
        self.snapshot_when = snapshot_when
        self.tagger = tagger
        self.on_signal_cb = on_signal
        self.recovery_completed = recovery_completed
        self.journal_plugin_id = journal_plugin_id
        self.snapshot_plugin_id = snapshot_plugin_id
        # state <-> stored-snapshot mapping incl. old-snapshot upcasts
        # (reference: typed/SnapshotAdapter.scala:14, wired per behavior)
        self.snapshot_adapter = snapshot_adapter
        # per-behavior domain<->journal event mapping with 1->N read
        # upcasting (reference: typed/EventAdapter.scala, applied before
        # the journal — composes with the journal-level EventAdapters
        # registry, which sees this adapter's OUTPUT)
        self.event_adapter = event_adapter
        # per-spawned-actor runtime, keyed by the actor's ref (the same
        # EventSourcedBehavior object may be spawned more than once)
        self._runtimes: dict = {}

    # ExtensibleBehavior protocol: the adapter calls receive for messages.
    # On first activation we build the runtime via Behaviors.setup.
    def receive(self, ctx, msg) -> Behavior:
        rt = self._ensure_runtime(ctx)
        return rt.on_message(ctx, msg)

    def receive_signal(self, ctx, signal: Signal) -> Behavior:
        from ..typed.behavior import PostStop, PreRestart
        if signal is PostStop or signal is PreRestart:
            # drop the runtime: a supervised restart must re-run recovery
            # from the journal, and stopped refs must not leak runtimes
            rt = self._runtimes.pop(ctx.self, None)
            if rt is not None and self.on_signal_cb is not None:
                self.on_signal_cb(rt.state, signal)
            return self
        rt = self._ensure_runtime(ctx)
        return rt.on_signal(ctx, signal)

    def _ensure_runtime(self, ctx) -> "_ESRuntime":
        rt = self._runtimes.get(ctx.self)
        if rt is None:
            rt = self._runtimes[ctx.self] = _ESRuntime(self, ctx)
        return rt


class _ESRuntime:
    """Per-actor mutable machinery (phases mirror akka-persistence-typed
    internal/: RequestingRecoveryPermit → ReplayingSnapshot →
    ReplayingEvents → Running)."""

    def __init__(self, beh: EventSourcedBehavior, ctx):
        self.b = beh
        self.ctx_ref = ctx.self
        system = ctx.system
        self.ext = Persistence.get(system)
        self.journal = self.ext.journal_for(beh.journal_plugin_id)
        self.snapshot_store = self.ext.snapshot_store_for(beh.snapshot_plugin_id)
        self.instance_id = self.ext.next_instance_id()
        self.writer_uuid = uuid.uuid4().hex
        self.state = beh.empty_state
        self.seq_nr = 0
        self.phase = "requesting-permit"
        self.stash: List[Any] = []
        self.pending_effects: List[Effect] = []  # effects awaiting write ack
        self.pending_events = 0
        self.effect_rejected = False
        self.ext.recovery_permitter.tell(RequestRecoveryPermit(), ctx.self)

    # -- message pump ---------------------------------------------------------
    def on_message(self, ctx, msg) -> Behavior:
        if self.phase == "requesting-permit":
            return self._requesting_permit(ctx, msg)
        if self.phase == "replaying-snapshot":
            return self._replaying_snapshot(ctx, msg)
        if self.phase == "replaying-events":
            return self._replaying_events(ctx, msg)
        return self._running(ctx, msg)

    def on_signal(self, ctx, signal) -> Behavior:
        if self.b.on_signal_cb is not None:
            self.b.on_signal_cb(self.state, signal)
            return self.b
        return UNHANDLED

    # -- phases ---------------------------------------------------------------
    def _requesting_permit(self, ctx, msg) -> Behavior:
        if isinstance(msg, RecoveryPermitGranted):
            self.phase = "replaying-snapshot"
            self.snapshot_store.tell(
                LoadSnapshot(self.b.persistence_id.id,
                             SnapshotSelectionCriteria.latest(), 2**63 - 1),
                ctx.self)
        else:
            self.stash.append(msg)
        return self.b

    def _replaying_snapshot(self, ctx, msg) -> Behavior:
        if isinstance(msg, LoadSnapshotResult):
            if msg.snapshot is not None:
                stored = msg.snapshot.snapshot
                self.state = stored if self.b.snapshot_adapter is None \
                    else self.b.snapshot_adapter.from_journal(stored)
                self.seq_nr = msg.snapshot.metadata.sequence_nr
            self.phase = "replaying-events"
            self.journal.tell(
                ReplayMessages(self.seq_nr + 1, 2**63 - 1, 2**63 - 1,
                               self.b.persistence_id.id, ctx.self), ctx.self)
        elif isinstance(msg, LoadSnapshotFailed):
            ctx.system.log.error(
                f"snapshot recovery failed for {self.b.persistence_id.id}: "
                f"{msg.cause}")
            return Behaviors.stopped()
        else:
            self.stash.append(msg)
        return self.b

    def _replaying_events(self, ctx, msg) -> Behavior:
        if isinstance(msg, ReplayedMessage):
            self.seq_nr = msg.persistent.sequence_nr
            payload = msg.persistent.payload
            if self.b.event_adapter is not None:
                for domain in self.b.event_adapter.from_journal(
                        payload, msg.persistent.manifest).events:
                    self.state = self.b.event_handler(self.state, domain)
            else:
                self.state = self.b.event_handler(self.state, payload)
        elif isinstance(msg, RecoverySuccess):
            self.seq_nr = max(self.seq_nr, msg.highest_sequence_nr)
            self.phase = "running"
            self.ext.recovery_permitter.tell(ReturnRecoveryPermit(), ctx.self)
            if self.b.recovery_completed is not None:
                self.b.recovery_completed(self.state, ctx)
            return self._unstash(ctx)
        elif isinstance(msg, ReplayMessagesFailure):
            ctx.system.log.error(
                f"replay failed for {self.b.persistence_id.id}: {msg.cause}")
            return Behaviors.stopped()
        else:
            self.stash.append(msg)
        return self.b

    # -- running --------------------------------------------------------------
    def _running(self, ctx, msg) -> Behavior:
        if isinstance(msg, WriteMessageSuccess):
            if msg.actor_instance_id != self.instance_id:
                return self.b
            return self._on_event_persisted(ctx, msg.persistent)
        if isinstance(msg, WriteMessageRejected):
            if msg.actor_instance_id != self.instance_id:
                return self.b
            ctx.system.log.error(
                f"persist rejected for {self.b.persistence_id.id}: {msg.cause}")
            self.pending_events -= 1
            self.effect_rejected = True  # suppress then_reply/then_run: the
            # event was NOT stored, a success-style reply would lie
            if self.pending_events == 0:
                self._finish_effect(ctx)
                return self._unstash(ctx)
            return self.b
        if isinstance(msg, WriteMessageFailure):
            if msg.actor_instance_id != self.instance_id:
                return self.b
            ctx.system.log.error(
                f"persist failed for {self.b.persistence_id.id}: {msg.cause}")
            return Behaviors.stopped()
        if isinstance(msg, (WriteMessagesSuccessful, WriteMessagesFailed,
                            SaveSnapshotSuccess, SaveSnapshotFailure)):
            return self.b
        if self.pending_events > 0:
            self.stash.append(msg)  # single-writer: wait for confirmations
            return self.b
        return self._handle_command(ctx, msg)

    def _handle_command(self, ctx, cmd) -> Behavior:
        effect = self.b.command_handler(self.state, cmd)
        if effect is None:
            effect = Effect.none()
        if effect.kind == "unhandled":
            self._apply_side_effects(ctx, effect)
            return UNHANDLED
        if effect.kind == "stash":
            self.stash.append(cmd)
            return self.b
        if effect.kind == "persist" and effect.events:
            reprs = []
            for ev in effect.events:
                self.seq_nr += 1
                payload, manifest = ev, ""
                ea = self.b.event_adapter
                if ea is not None:
                    payload = ea.to_journal(ev)
                    manifest = ea.manifest(ev)
                if self.b.tagger is not None:
                    # the tagger sees the DOMAIN event (it is part of the
                    # behavior's vocabulary, not the journal model's)
                    tags = self.b.tagger(ev)
                    if tags:
                        payload = Tagged(payload, frozenset(tags))
                reprs.append(PersistentRepr(payload, self.seq_nr,
                                            self.b.persistence_id.id,
                                            manifest=manifest,
                                            writer_uuid=self.writer_uuid))
            self.pending_events = len(reprs)
            self.pending_effects.append(effect)
            self.journal.tell(
                WriteMessages((AtomicWrite(tuple(reprs)),), ctx.self,
                              self.instance_id), ctx.self)
            return self.b
        # none / stop without events
        self._apply_side_effects(ctx, effect)
        if effect.kind == "stop" or ("stop",) in effect.side_effects:
            return Behaviors.stopped()
        return self.b

    def _on_event_persisted(self, ctx, persistent: PersistentRepr) -> Behavior:
        ev = persistent.payload
        if isinstance(ev, Tagged):
            ev = ev.payload
        # the journal echoes the JOURNAL model; the event handler's (and
        # snapshot_when's) vocabulary is the domain model — the adapter's
        # read side is authoritative for the mapping (1->N folds in order)
        events = [ev] if self.b.event_adapter is None else \
            self.b.event_adapter.from_journal(ev, persistent.manifest).events
        for domain in events:
            self.state = self.b.event_handler(self.state, domain)
        self.pending_events -= 1
        if events:
            self._maybe_snapshot(ctx, events[-1], persistent.sequence_nr)
        if self.pending_events == 0:
            stop = self._finish_effect(ctx)
            if stop:
                return Behaviors.stopped()
            return self._unstash(ctx)
        return self.b

    def _finish_effect(self, ctx) -> bool:
        if not self.pending_effects:
            return False
        effect = self.pending_effects.pop(0)
        rejected = getattr(self, "effect_rejected", False)
        self.effect_rejected = False
        if not rejected:
            self._apply_side_effects(ctx, effect)
        return (not rejected) and (
            effect.kind == "stop" or ("stop",) in effect.side_effects)

    def _apply_side_effects(self, ctx, effect: Effect) -> None:
        for se in effect.side_effects:
            if se[0] == "run":
                se[1](self.state)
            elif se[0] == "reply":
                se[1].tell(se[2](self.state), ctx.self)
            elif se[0] == "stop":
                pass  # handled by callers

    def _maybe_snapshot(self, ctx, event: Any, seq_nr: int) -> None:
        ret = self.b.retention
        should = False
        if ret.snapshot_every > 0 and seq_nr % ret.snapshot_every == 0:
            should = True
        if self.b.snapshot_when is not None and \
                self.b.snapshot_when(self.state, event, seq_nr):
            should = True
        if not should:
            return
        md = SnapshotMetadata(self.b.persistence_id.id, seq_nr, time.time())
        stored = self.state if self.b.snapshot_adapter is None \
            else self.b.snapshot_adapter.to_journal(self.state)
        self.snapshot_store.tell(SaveSnapshot(md, stored), ctx.self)
        if ret.snapshot_every > 0:
            keep_from = seq_nr - ret.snapshot_every * ret.keep_n_snapshots
            if keep_from > 0:
                self.snapshot_store.tell(
                    DeleteSnapshots(self.b.persistence_id.id,
                                    SnapshotSelectionCriteria(
                                        max_sequence_nr=keep_from)), ctx.self)
                if ret.delete_events_on_snapshot:
                    self.journal.tell(
                        DeleteMessagesTo(self.b.persistence_id.id, keep_from,
                                         ctx.self), ctx.self)

    def _unstash(self, ctx) -> Behavior:
        """Replay stashed messages. Iterates over a snapshot so a handler
        returning Effect.stash() re-stashes without looping forever, and
        propagates a stop result instead of discarding it."""
        from ..typed.behavior import is_alive
        while self.stash and self.pending_events == 0:
            msgs, self.stash = self.stash, []
            for i, msg in enumerate(msgs):
                result = self.on_message(ctx, msg)
                if not is_alive(result):
                    # requeue the rest as dead letters' would-be input: they
                    # follow the actor into termination (reference drops them)
                    return result
                if self.pending_events > 0:
                    # a persist is in flight again: keep the rest stashed,
                    # in order, ahead of anything stashed meanwhile
                    self.stash = msgs[i + 1:] + self.stash
                    return self.b
            if self.stash == msgs:
                break  # everything re-stashed itself: avoid a busy loop
        return self.b
