"""Persistence extension: plugin registry + recovery permitter.

Reference parity: akka-persistence/src/main/scala/akka/persistence/
Persistence.scala (journalFor/snapshotStoreFor resolve config-path plugin ids
to one actor per plugin, `plugin` default keys) and RecoveryPermitter.scala
(token bucket limiting concurrent recoveries, max-concurrent-recoveries=35).

Plugin ids mirror the reference's config paths:
  akka.persistence.journal.plugin        = "akka.persistence.journal.inmem"
  akka.persistence.snapshot-store.plugin = "akka.persistence.snapshot-store.local"
Custom plugins register a factory under their own id via
`Persistence.register_journal_plugin` (the Dispatchers-registry seam,
reference: Persistence.scala journalFor + dispatch/Dispatchers.scala:184).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

from ..actor.actor import Actor
from ..actor.props import Props
from ..actor.ref import ActorRef
from ..actor.system import ActorSystem
from .journal import FileJournal, InMemJournal, JournalActor, JournalPlugin
from .snapshot import (InMemSnapshotStore, LocalSnapshotStore, SnapshotPlugin,
                       SnapshotStoreActor)


# -- recovery permitter (reference: RecoveryPermitter.scala) -----------------

@dataclass(frozen=True)
class RequestRecoveryPermit:
    pass


@dataclass(frozen=True)
class RecoveryPermitGranted:
    pass


@dataclass(frozen=True)
class ReturnRecoveryPermit:
    pass


class RecoveryPermitter(Actor):
    def __init__(self, max_permits: int):
        super().__init__()
        self.max_permits = max_permits
        self.holders: set = set()   # refs that were actually GRANTED
        self.waiting: list = []

    def receive(self, message: Any) -> Any:
        if isinstance(message, RequestRecoveryPermit):
            self.context.watch(self.sender)
            if len(self.holders) < self.max_permits:
                self.holders.add(self.sender)
                self.sender.tell(RecoveryPermitGranted(), self.self_ref)
            else:
                self.waiting.append(self.sender)
        elif isinstance(message, ReturnRecoveryPermit):
            # a Return from an actor still queued (stopped while waiting)
            # must NOT decrement — it never held a permit
            if self.sender in self.holders:
                self._return_permit(self.sender)
            elif self.sender in self.waiting:
                self.waiting.remove(self.sender)
                self.context.unwatch(self.sender)
        else:
            from ..actor.messages import Terminated
            if isinstance(message, Terminated):
                if message.actor in self.waiting:
                    self.waiting.remove(message.actor)
                elif message.actor in self.holders:
                    self._return_permit(message.actor, watched_gone=True)
            else:
                return NotImplemented

    def _return_permit(self, ref: ActorRef, watched_gone: bool = False) -> None:
        if not watched_gone:
            self.context.unwatch(ref)
        self.holders.discard(ref)
        while self.waiting and len(self.holders) < self.max_permits:
            nxt = self.waiting.pop(0)
            self.holders.add(nxt)
            nxt.tell(RecoveryPermitGranted(), self.self_ref)


# -- extension ---------------------------------------------------------------

JOURNAL_INMEM = "akka.persistence.journal.inmem"
JOURNAL_FILE = "akka.persistence.journal.file"
SNAPSHOT_LOCAL = "akka.persistence.snapshot-store.local"
SNAPSHOT_INMEM = "akka.persistence.snapshot-store.inmem"


class Persistence:
    """Obtain via Persistence.get(system)."""

    _instances: Dict[ActorSystem, "Persistence"] = {}
    _lock = threading.Lock()
    # plugin-id -> factory(system, plugin_config) -> plugin object
    _journal_factories: Dict[str, Callable] = {}
    _snapshot_factories: Dict[str, Callable] = {}

    @staticmethod
    def get(system: ActorSystem) -> "Persistence":
        with Persistence._lock:
            inst = Persistence._instances.get(system)
            if inst is None:
                inst = Persistence._instances[system] = Persistence(system)
                system.register_on_termination(
                    lambda: Persistence._instances.pop(system, None))
            return inst

    @staticmethod
    def register_journal_plugin(plugin_id: str, factory: Callable) -> None:
        Persistence._journal_factories[plugin_id] = factory

    @staticmethod
    def register_snapshot_plugin(plugin_id: str, factory: Callable) -> None:
        Persistence._snapshot_factories[plugin_id] = factory

    def __init__(self, system: ActorSystem):
        self.system = system
        cfg = system.settings.config.get_config("akka.persistence")
        self.default_journal_id = cfg.get_string("journal.plugin",
                                                 JOURNAL_INMEM)
        self.default_snapshot_id = cfg.get_string("snapshot-store.plugin",
                                                  SNAPSHOT_INMEM)
        self.max_concurrent_recoveries = cfg.get_int(
            "max-concurrent-recoveries", 35)
        self._journals: Dict[str, ActorRef] = {}
        self._journal_plugins: Dict[str, JournalPlugin] = {}
        self._event_adapters: Dict[str, Any] = {}  # plugin-id -> EventAdapters
        self._snapshots: Dict[str, ActorRef] = {}
        self._snapshot_plugins: Dict[str, SnapshotPlugin] = {}
        self._counter = 0
        self._instance_lock = threading.Lock()
        self.recovery_permitter = system.system_actor_of(
            Props.create(RecoveryPermitter, self.max_concurrent_recoveries),
            "recoveryPermitter")

    def _plugin_config(self, plugin_id: str):
        return self.system.settings.config.get_config(plugin_id)

    def _plugin_dir(self, configured: str) -> str:
        """Relative plugin dirs (reference default `journal`/`snapshots`) are
        rooted per system under /tmp so concurrent systems don't collide and
        the repo cwd stays clean."""
        if os.path.isabs(configured):
            return configured
        return os.path.join("/tmp", f"akka-tpu-{self.system.name}", configured)

    def _make_journal_plugin(self, plugin_id: str) -> JournalPlugin:
        factory = Persistence._journal_factories.get(plugin_id)
        if factory is not None:
            return factory(self.system, self._plugin_config(plugin_id))
        if plugin_id == JOURNAL_INMEM:
            return InMemJournal()
        if plugin_id == JOURNAL_FILE:
            d = self._plugin_dir(
                self._plugin_config(plugin_id).get_string("dir", "journal"))
            return FileJournal(d)
        raise ValueError(f"unknown journal plugin id {plugin_id!r}")

    def _make_snapshot_plugin(self, plugin_id: str) -> SnapshotPlugin:
        factory = Persistence._snapshot_factories.get(plugin_id)
        if factory is not None:
            return factory(self.system, self._plugin_config(plugin_id))
        if plugin_id == SNAPSHOT_INMEM:
            return InMemSnapshotStore()
        if plugin_id == SNAPSHOT_LOCAL:
            d = self._plugin_dir(
                self._plugin_config(plugin_id).get_string("dir", "snapshots"))
            return LocalSnapshotStore(d)
        raise ValueError(f"unknown snapshot plugin id {plugin_id!r}")

    def register_event_adapters(self, plugin_id: str, adapters) -> None:
        """Bind an EventAdapters registry to a journal plugin id BEFORE its
        first use (reference: the per-journal event-adapters config block,
        EventAdapters.scala:25). Late registration raises — adapters must
        see every write."""
        pid = plugin_id or self.default_journal_id
        with self._instance_lock:
            if pid in self._journals:
                raise RuntimeError(
                    f"journal '{pid}' already started; register event "
                    f"adapters before the first persistence use")
            self._event_adapters[pid] = adapters

    def journal_for(self, plugin_id: str = "") -> ActorRef:
        pid = plugin_id or self.default_journal_id
        with self._instance_lock:
            ref = self._journals.get(pid)
            if ref is None:
                plugin = self._make_journal_plugin(pid)
                self._journal_plugins[pid] = plugin
                name = f"journal-{len(self._journals)}"
                ref = self._journals[pid] = self.system.system_actor_of(
                    Props.create(JournalActor, plugin,
                                 self._event_adapters.get(pid)), name)
            return ref

    def journal_plugin_for(self, plugin_id: str = "") -> JournalPlugin:
        """The underlying sync plugin (persistence-query reads through it)."""
        pid = plugin_id or self.default_journal_id
        self.journal_for(pid)
        return self._journal_plugins[pid]

    def snapshot_store_for(self, plugin_id: str = "") -> ActorRef:
        pid = plugin_id or self.default_snapshot_id
        with self._instance_lock:
            ref = self._snapshots.get(pid)
            if ref is None:
                plugin = self._make_snapshot_plugin(pid)
                self._snapshot_plugins[pid] = plugin
                name = f"snapshotStore-{len(self._snapshots)}"
                ref = self._snapshots[pid] = self.system.system_actor_of(
                    Props.create(SnapshotStoreActor, plugin), name)
            return ref

    def next_instance_id(self) -> int:
        with self._instance_lock:
            self._counter += 1
            return self._counter
