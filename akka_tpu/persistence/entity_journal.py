"""Per-entity event journal with wave-granular group commit (ISSUE 15).

The gateway's durable frontier so far is batch-shaped but entity-blind:
the tell WAL (tell_journal.py) replays whole staged batches, so crash
recovery re-runs the step program — exact, but priced per step and
unable to answer "what is entity X's durable state?" without a full
replay. This module is the entity-shaped half: each ok ask-wave emits
per-entity events (entity_id, op, value) that are appended as ONE
group-committed record at the coalesced-flush boundary — the
PGAS-actors argument that durable per-entity state must ride the same
batched substrate instead of a per-entity sync write.

Format: the length-prefixed record log (8-byte LE length + pickle) the
FileJournal/TellJournal family shares, with the same torn-tail
truncation on open (journal.repair_record_log). One record per wave:

    {"step": S, "events": [(entity_id, op, value), ...],
     "snaps": {entity_id: total},
     "replies": [(tenant, request_id, status, value), ...]}

`replies` (ISSUE 20) is the gateway's dedup frontier: the ok reply of
every idempotent-session request resolved in this wave, committed in
the SAME record as the events it acknowledges — commit-before-ack now
covers the reply cache, so kill -9 + restore replays the frontier
(`replies()`) and a post-restore retry returns the cached reply instead
of re-applying. The live fold keeps the newest `max_replies` of them in
arrival order (the gateway's per-tenant windows re-bound them on
rehydrate). Absent on pre-ISSUE-20 records — replay tolerates both.

`events` are deltas in wave-linearization order; `snaps` are per-entity
snapshots piggybacked into the SAME write whenever an entity has
accumulated `snapshot_every` events since its last snapshot — snapshot
durability costs zero extra fsyncs. Replay folds oldest→newest: a snap
resets the entity's total, events accumulate on top (within one record
events precede snaps, because a snap is the post-wave total). The fold
is kept LIVE in memory (`totals()`), so a restore reads the acked
frontier without touching the device.

Group commit rides the tell-journal fsync-every-n seam, counted in
WAVES: every append flush()es (kill -9 of the process loses nothing —
the page cache survives), and fsync lands every n waves (n=1 default:
one fsync per ask wave, machine-crash-safe before any ack goes out).
`per_event_fsync=True` degrades to one record+fsync per EVENT — the
bench A/B's "what a per-entity sync write would cost" leg, never the
serving configuration.

Compaction: `compact()` rewrites the log as one snap-all record
(tmp + fsync + replace, the TellJournal.compact idiom); the region
calls it at checkpoint(), and the journal self-compacts once
`compact_every` events accumulate past the last rewrite, so the tail
an entity must fold on replay stays bounded by `snapshot_every` and
the file by `compact_every`.
"""

from __future__ import annotations

import os
import pickle
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .journal import repair_record_log, scan_record_log

__all__ = ["EntityJournal", "OP_ADD"]

OP_ADD = 0  # fold: total += value (the counter/additive entity family)


def _fold(total: float, op: int, value: float) -> float:
    # single op family today; the op byte is journaled so richer entity
    # state machines can extend the fold without a format change
    return total + value if op == OP_ADD else total


class EntityJournal:
    """Append-only per-entity event log, one file, group-committed per
    ask wave. Thread-safe; the in-memory fold (`totals`) is the acked
    frontier — an event is appended only after its wave observed the ok
    reply, and fsync'd before the ack leaves the gateway."""

    def __init__(self, path: str, flight_recorder: Optional[Any] = None,
                 fsync_every_n: int = 1, snapshot_every: int = 64,
                 compact_every: int = 8192, registry=None,
                 max_replies: int = 1 << 16):
        self.path = path
        self.flight_recorder = flight_recorder
        self.fsync_every_n = max(1, int(fsync_every_n))
        self.snapshot_every = max(1, int(snapshot_every))
        self.compact_every = max(self.snapshot_every, int(compact_every))
        self.max_replies = max(1, int(max_replies))
        self._since_fsync = 0
        self._events_since_compact = 0
        self._lock = threading.Lock()
        self._totals: Dict[str, float] = {}
        self._counts: Dict[str, int] = {}  # events since entity's last snap
        # insertion-ordered dedup frontier: (tenant, id) -> (status, value)
        self._replies: Dict[Tuple[str, int], Tuple[int, float]] = {}
        self._last_step = 0
        self._stats = {"waves": 0, "events": 0, "snaps": 0, "fsyncs": 0,
                       "compactions": 0, "replies": 0}
        self._h_batch = self._h_fsync = self._h_replay = None
        self._registry = registry
        if registry is not None:
            self._h_batch = registry.histogram(
                "entity_journal_batch_size",
                "entity events group-committed per ask wave")
            self._h_fsync = registry.histogram(
                "entity_journal_fsync_ms",
                "wall ms of the wave-boundary group-commit fsync")
            self._h_replay = registry.histogram(
                "entity_replay_events",
                "events folded per entity during restore replay")
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self.truncated_bytes = repair_record_log(path, flight_recorder)
        self._fh = open(path, "ab")
        self._fold_existing()

    # -- open-time fold ------------------------------------------------------
    def _fold_existing(self) -> None:
        """Replay the on-disk log into the live fold: snapshot + event
        tail per entity. Runs at open, so a fresh process's journal is
        query-ready (`totals()`) before any device work happens."""
        replayed: Dict[str, int] = {}
        for _end, rec in scan_record_log(self.path):
            self._apply_record(rec, replayed)
        if replayed and self._h_replay is not None:
            step = self._registry.step if self._registry else None
            self._h_replay.observe_many(
                [float(n) for n in replayed.values()], step=step)
        # the "entity_replayed" flight-recorder event is emitted by the
        # region's _replay_entities (the device write), not the fold here
        self._replayed_events = replayed

    def _apply_record(self, rec: Dict[str, Any],
                      replayed: Optional[Dict[str, int]] = None) -> None:
        self._last_step = max(self._last_step, int(rec.get("step", 0)))
        for eid, op, value in rec.get("events", ()):
            self._totals[eid] = _fold(self._totals.get(eid, 0.0),
                                      int(op), float(value))
            self._counts[eid] = self._counts.get(eid, 0) + 1
            if replayed is not None:
                replayed[eid] = replayed.get(eid, 0) + 1
        # snaps are post-wave totals: they override the event fold above
        for eid, total in (rec.get("snaps") or {}).items():
            self._totals[eid] = float(total)
            self._counts[eid] = 0
        for tenant, rid, status, value in rec.get("replies", ()):
            self._fold_reply((str(tenant), int(rid)),
                             int(status), float(value))

    def _fold_reply(self, key: Tuple[str, int], status: int,
                    value: float) -> None:
        # re-insert moves the key to the newest end (dict order)
        self._replies.pop(key, None)
        self._replies[key] = (status, value)
        while len(self._replies) > self.max_replies:
            del self._replies[next(iter(self._replies))]

    # -- write side ----------------------------------------------------------
    def append_wave(self, step: int,
                    events: Sequence[Tuple[str, int, float]],
                    per_event_fsync: bool = False,
                    replies: Optional[
                        Sequence[Tuple[str, int, int, float]]] = None
                    ) -> int:
        """Group-commit one ask wave's ok events: fold them into the live
        totals, piggyback a snapshot for every entity that crossed
        `snapshot_every` events, and write it all as ONE record. Returns
        the number of events committed. `per_event_fsync` is the bench's
        degenerate leg: one record + one fsync per event.

        `replies` (ISSUE 20): the wave's resolved idempotent-session
        replies `(tenant, request_id, status, value)`, committed in the
        same record — the dedup frontier rides the exact fsync that
        covers the events it acknowledges. A wave of pure gets has
        replies but no nonzero events; it still writes a record so the
        reply cache survives a crash."""
        events = [(str(e), int(op), float(v)) for e, op, v in events]
        replies = [(str(t), int(r), int(st), float(v))
                   for t, r, st, v in (replies or ())]
        if not events and not replies:
            return 0
        with self._lock:
            if self._fh is None:
                raise ValueError("EntityJournal is closed")
            snaps: Dict[str, float] = {}
            for eid, op, value in events:
                self._totals[eid] = _fold(self._totals.get(eid, 0.0),
                                          op, value)
                n = self._counts.get(eid, 0) + 1
                if n >= self.snapshot_every:
                    snaps[eid] = self._totals[eid]
                    n = 0
                self._counts[eid] = n
            for tenant, rid, status, value in replies:
                self._fold_reply((tenant, rid), status, value)
            if per_event_fsync:
                for eid, op, value in events:
                    self._write_record({"step": int(step),
                                        "events": [(eid, op, value)],
                                        "snaps": {}})
                    self._fsync_locked()
                if replies:
                    self._write_record({"step": int(step), "events": [],
                                        "snaps": {}, "replies": replies})
                    self._fsync_locked()
            else:
                rec = {"step": int(step), "events": events, "snaps": snaps}
                if replies:
                    rec["replies"] = replies
                self._write_record(rec)
                self._since_fsync += 1
                if self._since_fsync >= self.fsync_every_n:
                    self._fsync_locked()
            self._stats["waves"] += 1
            self._stats["events"] += len(events)
            self._stats["snaps"] += len(snaps)
            self._stats["replies"] += len(replies)
            self._events_since_compact += len(events)
            need_compact = self._events_since_compact >= self.compact_every
        step_stamp = self._registry.step if self._registry else None
        if self._h_batch is not None:
            self._h_batch.observe(float(len(events)), step=step_stamp)
        if self.flight_recorder is not None and getattr(
                self.flight_recorder, "enabled", False):
            self.flight_recorder.event(
                "entity_events_committed", n=len(events),
                snaps=len(snaps), step=int(step))
        if need_compact:
            self.compact()
        return len(events)

    def _write_record(self, rec: Dict[str, Any]) -> None:
        blob = pickle.dumps(rec, protocol=4)
        self._fh.write(len(blob).to_bytes(8, "little"))
        self._fh.write(blob)
        self._fh.flush()

    def _fsync_locked(self) -> None:
        t0 = time.perf_counter()
        os.fsync(self._fh.fileno())
        self._since_fsync = 0
        self._stats["fsyncs"] += 1
        if self._h_fsync is not None:
            self._h_fsync.observe(
                (time.perf_counter() - t0) * 1e3,
                step=self._registry.step if self._registry else None)

    def sync(self) -> None:
        """Force the deferred group-commit fsync (wave-batch boundary)."""
        with self._lock:
            if self._fh is not None and self._since_fsync:
                self._fh.flush()
                self._fsync_locked()

    # -- read side -----------------------------------------------------------
    def totals(self) -> Dict[str, float]:
        """The durable acked frontier: entity_id -> folded total
        (snapshot + event tail). This is what restore writes back into
        the device rows."""
        with self._lock:
            return dict(self._totals)

    def replayed_events(self) -> Dict[str, int]:
        """Per-entity event-tail lengths folded by the open-time replay
        (empty for a journal that was born in this process)."""
        return dict(self._replayed_events)

    def replies(self) -> List[Tuple[str, int, int, float]]:
        """The durable dedup frontier in arrival order:
        `(tenant, request_id, status, value)` per remembered reply —
        what the gateway feeds `ReplyCacheTable.load` on restore."""
        with self._lock:
            return [(t, r, st, v)
                    for (t, r), (st, v) in self._replies.items()]

    def records(self) -> List[Dict[str, Any]]:
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
        return [rec for _end, rec in scan_record_log(self.path)]

    def stats(self) -> Dict[str, float]:
        with self._lock:
            out = {k: float(v) for k, v in self._stats.items()}
            out["entities"] = float(len(self._totals))
            out["cached_replies"] = float(len(self._replies))
            out["bytes"] = float(os.path.getsize(self.path)
                                 if os.path.exists(self.path) else 0)
        return out

    # -- maintenance ---------------------------------------------------------
    def compact(self) -> int:
        """Rewrite the log as ONE snap-all record covering the live fold
        (every event so far is subsumed by its entity's snapshot).
        Atomic: tmp + fsync + replace, then the append handle reopens.
        Returns the compacted file's entity count."""
        with self._lock:
            if self._fh is None:
                raise ValueError("EntityJournal is closed")
            rec = {"step": int(self._last_step), "events": [],
                   "snaps": dict(self._totals)}
            if self._replies:
                rec["replies"] = [(t, r, st, v) for (t, r), (st, v)
                                  in self._replies.items()]
            blob = pickle.dumps(rec, protocol=4)
            tmp = self.path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(len(blob).to_bytes(8, "little"))
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            self._fh.close()
            os.replace(tmp, self.path)
            self._fh = open(self.path, "ab")
            self._since_fsync = 0  # the rewrite was fsync'd whole
            self._events_since_compact = 0
            self._counts = {eid: 0 for eid in self._totals}
            self._stats["compactions"] += 1
            return len(self._totals)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                if self._since_fsync:
                    self._fh.flush()
                    os.fsync(self._fh.fileno())
                    self._since_fsync = 0
                self._fh.close()
                self._fh = None
