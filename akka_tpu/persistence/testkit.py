"""Persistence testkit: programmable-failure journal + TCK compliance suites.

Reference parity: akka-persistence-testkit/.../PersistenceTestKitPlugin.scala
+ ProcessingPolicy.scala (accept / reject / fail the nth write, pass-all,
fail-next-n — policies swappable at runtime), and akka-persistence-tck's
reusable plugin compliance specs (persistence-tck/.../journal/JournalSpec.scala,
snapshot/SnapshotStoreSpec.scala): any JournalPlugin / SnapshotPlugin
implementation can be run through journal_tck()/snapshot_store_tck().
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional

from .journal import InMemJournal, JournalPlugin, _MemStore
from .messages import (AtomicWrite, PersistentRepr, SelectedSnapshot,
                       SnapshotMetadata, SnapshotSelectionCriteria)
from .snapshot import SnapshotPlugin


# -- processing policies (reference: ProcessingPolicy.scala) -----------------

class ProcessingPolicy:
    """Decide the fate of each write: "pass" | ("reject", msg) | ("fail", msg)."""

    def decide(self, persistence_id: str, batch: AtomicWrite):
        return "pass"


class PassAll(ProcessingPolicy):
    pass


class FailNextN(ProcessingPolicy):
    def __init__(self, n: int, cause: str = "injected failure"):
        self.n = n
        self.cause = cause
        self._lock = threading.Lock()

    def decide(self, persistence_id, batch):
        with self._lock:
            if self.n > 0:
                self.n -= 1
                return ("fail", self.cause)
        return "pass"


class RejectNextN(ProcessingPolicy):
    def __init__(self, n: int, cause: str = "injected rejection"):
        self.n = n
        self.cause = cause
        self._lock = threading.Lock()

    def decide(self, persistence_id, batch):
        with self._lock:
            if self.n > 0:
                self.n -= 1
                return ("reject", self.cause)
        return "pass"


class FailIf(ProcessingPolicy):
    def __init__(self, predicate: Callable[[str, AtomicWrite], bool],
                 cause: str = "injected failure"):
        self.predicate = predicate
        self.cause = cause

    def decide(self, persistence_id, batch):
        if self.predicate(persistence_id, batch):
            return ("fail", self.cause)
        return "pass"


class PersistenceTestKitJournal(InMemJournal):
    """In-mem journal with a swappable write policy (reference:
    PersistenceTestKitPlugin)."""

    def __init__(self, store: Optional[_MemStore] = None):
        super().__init__(store)
        self.policy: ProcessingPolicy = PassAll()

    def set_policy(self, policy: ProcessingPolicy) -> None:
        self.policy = policy

    def reset_policy(self) -> None:
        self.policy = PassAll()

    def write_atomic(self, write: AtomicWrite):
        decision = self.policy.decide(write.persistence_id, write)
        if decision == "pass":
            return super().write_atomic(write)
        kind, cause = decision
        if kind == "reject":
            return cause
        raise IOError(cause)


# -- TCK (reference: persistence-tck JournalSpec/SnapshotStoreSpec) ----------

def journal_tck(make_plugin: Callable[[], JournalPlugin]) -> None:
    """Run the journal compliance suite against a fresh plugin instance.
    Raises AssertionError on the first violated contract."""

    def reprs(pid: str, nrs: List[int]) -> AtomicWrite:
        return AtomicWrite(tuple(
            PersistentRepr(f"ev-{n}", n, pid) for n in nrs))

    # 1. write + replay round trip, order preserved
    j = make_plugin()
    assert j.write_atomic(reprs("p1", [1, 2, 3])) is None
    assert j.write_atomic(reprs("p1", [4, 5])) is None
    got: List[PersistentRepr] = []
    j.replay("p1", 1, 2**63 - 1, 2**63 - 1, got.append)
    assert [r.sequence_nr for r in got] == [1, 2, 3, 4, 5], got
    assert [r.payload for r in got] == [f"ev-{n}" for n in range(1, 6)]

    # 2. range + max bounds
    got.clear()
    j.replay("p1", 2, 4, 2**63 - 1, got.append)
    assert [r.sequence_nr for r in got] == [2, 3, 4]
    got.clear()
    j.replay("p1", 1, 2**63 - 1, 2, got.append)
    assert [r.sequence_nr for r in got] == [1, 2]

    # 3. highest sequence nr, also after delete
    assert j.highest_sequence_nr("p1", 0) == 5
    j.delete_to("p1", 3)
    got.clear()
    j.replay("p1", 1, 2**63 - 1, 2**63 - 1, got.append)
    assert [r.sequence_nr for r in got] == [4, 5], \
        "logically deleted events must not replay"
    assert j.highest_sequence_nr("p1", 0) == 5, \
        "delete must NOT lower the highest sequence nr"

    # 4. per-id isolation
    assert j.write_atomic(reprs("p2", [1])) is None
    got.clear()
    j.replay("p2", 1, 2**63 - 1, 2**63 - 1, got.append)
    assert [r.sequence_nr for r in got] == [1]

    # 5. unknown id: empty replay, highest == 0
    got.clear()
    j.replay("nope", 1, 2**63 - 1, 2**63 - 1, got.append)
    assert got == []
    assert j.highest_sequence_nr("nope", 0) == 0


def snapshot_store_tck(make_plugin: Callable[[], SnapshotPlugin]) -> None:
    s = make_plugin()
    md = [SnapshotMetadata("p1", n, float(10 + n)) for n in (1, 5, 9)]
    for m in md:
        s.save(m, {"state": m.sequence_nr})

    # newest matching snapshot wins
    sel = s.load("p1", SnapshotSelectionCriteria.latest())
    assert sel is not None and sel.metadata.sequence_nr == 9

    # criteria bounds
    sel = s.load("p1", SnapshotSelectionCriteria(max_sequence_nr=6))
    assert sel is not None and sel.metadata.sequence_nr == 5
    sel = s.load("p1", SnapshotSelectionCriteria(max_sequence_nr=0))
    assert sel is None

    # overwrite same (seq, ts)
    s.save(md[2], {"state": "new"})
    sel = s.load("p1", SnapshotSelectionCriteria.latest())
    assert sel is not None and sel.snapshot == {"state": "new"}

    # single delete
    s.delete(md[2])
    sel = s.load("p1", SnapshotSelectionCriteria.latest())
    assert sel is not None and sel.metadata.sequence_nr == 5

    # delete matching criteria
    s.delete_matching("p1", SnapshotSelectionCriteria(max_sequence_nr=5))
    assert s.load("p1", SnapshotSelectionCriteria.latest()) is None

    # unknown id
    assert s.load("zzz", SnapshotSelectionCriteria.latest()) is None
