"""The BASELINE.json bench topologies as batched-behavior 'models'.

These mirror akka-bench-jmh's harnesses (SURVEY.md §6):
- ring:      1M-actor ring, every actor holds one token and forwards to the
             next each step (the ForkJoinActorBenchmark ping-pong generalized)
- fan_in:    1M leaves -> 1k collectors (the segment_sum hot path)
- ping_pong: 2-actor TellOnlyBenchmark equivalent
- router:    RoundRobinPool-style index-map routing, 100k routees
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..batched import BatchedSystem, Ctx, Emit, Inbox, behavior
from ..batched.sharded import ShardedBatchedSystem

PAYLOAD_W = 4


@behavior("ring", {"received": ((), jnp.int32)})
def ring_behavior(state, inbox, ctx):
    nxt = (ctx.actor_id + 1) % ctx.n_actors
    return ({"received": state["received"] + inbox.count},
            Emit.single(nxt, inbox.sum, 1, PAYLOAD_W, when=inbox.count > 0))


def make_fan_in_leaf(n_collectors: int = 1000):
    """Leaf behavior targeting `n_collectors` collectors by id hash — a
    factory so the emitted destinations always agree with the static
    topology build_fan_in compiles for the same count."""

    @behavior(f"leaf{n_collectors}", {}, always_on=True)
    def fan_in_leaf(state, inbox, ctx):
        dst = ctx.actor_id % n_collectors
        return {}, Emit.single(dst, jnp.array([1.0, 0, 0, 0]), 1, PAYLOAD_W,
                               when=ctx.actor_id >= n_collectors)

    return fan_in_leaf


@behavior("collector", {"total": ((), jnp.float32), "msgs": ((), jnp.int32)})
def fan_in_collector(state, inbox, ctx):
    return ({"total": state["total"] + inbox.sum[0],
             "msgs": state["msgs"] + inbox.count}, Emit.none(1, PAYLOAD_W))


def build_ring(n: int = 1 << 20, sharded: bool = False, n_devices=None,
               static: bool = True, delivery: str = "auto"):
    if sharded:
        sys = ShardedBatchedSystem(capacity=n, behaviors=[ring_behavior],
                                   n_devices=n_devices, payload_width=PAYLOAD_W,
                                   host_inbox_per_shard=8, delivery=delivery)
    else:
        topo = None
        if static:
            # the ring's wiring is fixed -> compile delivery to a gather
            from akka_tpu.ops.segment import StaticTopology
            dst_table = ((np.arange(n, dtype=np.int64) + 1) % n)[:, None]
            topo = StaticTopology.from_dst_table(dst_table)
        sys = BatchedSystem(capacity=n, behaviors=[ring_behavior],
                            payload_width=PAYLOAD_W, host_inbox=8,
                            topology=topo)
    sys.spawn_block(ring_behavior, n)
    return sys


def seed_ring_full(sys) -> None:
    """Every actor holds one token (uniform 1-msg mailbox per BASELINE config)."""
    n = sys.capacity
    dst = jnp.arange(n, dtype=jnp.int32)
    payload = jnp.zeros((n, PAYLOAD_W), dtype=jnp.float32).at[:, 0].set(1.0)
    if hasattr(sys, "seed_inbox"):
        sys.seed_inbox(dst, payload)
    else:  # sharded: place into each shard's exchange region
        seed_sharded_ring(sys)


def seed_sharded_ring(sys: ShardedBatchedSystem) -> None:
    """Seed one token per actor directly into each shard's self-chunk of the
    exchange buffer (slot layout: shard s's inbox[s*pair_cap + r])."""
    import jax
    n = sys.capacity
    # inbox is globally [n_shards * m_local]; shard s's block starts at s*m_local;
    # its self-chunk (from shard s) is at offset s*pair_cap within the block
    idxs, dsts = [], []
    for s in range(sys.n_shards):
        base = s * sys.m_local + sys.spill_cap + s * sys.pair_cap
        for r in range(min(sys.local_n, sys.pair_cap)):
            idxs.append(base + r)
            dsts.append(s * sys.local_n + r)
    idx = jnp.asarray(idxs)
    sys.inbox_dst = sys.inbox_dst.at[idx].set(jnp.asarray(dsts, jnp.int32))
    sys.inbox_payload = sys.inbox_payload.at[idx, 0].set(1.0)
    sys.inbox_valid = sys.inbox_valid.at[idx].set(True)


def build_fan_in(n_leaves: int = 1 << 20, n_collectors: int = 1000,
                 static: bool = True):
    n = n_leaves + n_collectors
    if n % n_collectors:
        # round capacity so the topology compiler can use the reshape-reduce
        # (mod) delivery; the padding rows are never spawned
        n += n_collectors - n % n_collectors
    topo = None
    if static:
        from akka_tpu.ops.segment import StaticTopology
        ids = np.arange(n, dtype=np.int64)
        dst_table = np.where(ids >= n_collectors, ids % n_collectors, -1)[:, None]
        topo = StaticTopology.from_dst_table(dst_table)
    leaf = make_fan_in_leaf(n_collectors)
    sys = BatchedSystem(capacity=n, behaviors=[fan_in_collector, leaf],
                        payload_width=PAYLOAD_W, host_inbox=8, topology=topo)
    sys.spawn_block(fan_in_collector, n_collectors)
    sys.spawn_block(leaf, n_leaves)
    return sys


def make_router_producer(routee_base: int, n_routees: int):
    """RoundRobinPool semantics, tensorized (BASELINE config 4): each
    producer's successive messages hit successive routees — the pool's
    routing logic is an index map applied at emission (SURVEY.md §2.11;
    reference: routing/Router.scala:116 route fan-out without the router's
    mailbox). The shifting (id + step) pattern defeats the static-topology
    compiler on purpose: this bench measures DYNAMIC delivery."""

    @behavior(f"producer{n_routees}", {}, always_on=True)
    def producer(state, inbox, ctx):
        dst = routee_base + (ctx.actor_id + ctx.step) % n_routees
        return {}, Emit.single(dst, jnp.array([1.0, 0, 0, 0]), 1, PAYLOAD_W,
                               when=ctx.actor_id >= routee_base + n_routees)

    return producer


@behavior("routee", {"hits": ((), jnp.int32)})
def routee(state, inbox, ctx):
    return ({"hits": state["hits"] + inbox.count}, Emit.none(1, PAYLOAD_W))


def build_router(n_producers: int = 1 << 20, n_routees: int = 100_000):
    """Config 4: RoundRobin router pool, 100k routees, producers telling
    every step. Routees occupy rows [0, n_routees); producers the rest."""
    n = n_routees + n_producers
    producer = make_router_producer(0, n_routees)
    sys = BatchedSystem(capacity=n, behaviors=[routee, producer],
                        payload_width=PAYLOAD_W, host_inbox=8)
    sys.spawn_block(routee, n_routees)
    sys.spawn_block(producer, n_producers)
    return sys


def make_router_api_producer(routee_base: int, n_routees: int):
    """Config 4 through the PUBLIC routing seam: identical traffic pattern
    to make_router_producer, but the routee index comes from
    routing.batched.BatchedRouter.route (the Router.scala:116 analogue)
    rather than a hand-rolled expression — this prices the abstraction
    users actually touch. Still dynamic: the step term defeats the
    static-topology compiler the same way."""
    from ..routing.batched import BatchedRouter

    router = BatchedRouter("round-robin", routee_base, n_routees)

    @behavior(f"producer-api{n_routees}", {}, always_on=True)
    def producer(state, inbox, ctx):
        dst = router.route(ctx.actor_id, ctx.step)
        return {}, Emit.single(dst, jnp.array([1.0, 0, 0, 0]), 1, PAYLOAD_W,
                               when=ctx.actor_id >= routee_base + n_routees)

    return producer


def build_router_api(n_producers: int = 1 << 20, n_routees: int = 100_000):
    """build_router, but emission goes through BatchedRouter (bench config
    'router-api'; VERDICT r2 next #10)."""
    n = n_routees + n_producers
    producer = make_router_api_producer(0, n_routees)
    sys = BatchedSystem(capacity=n, behaviors=[routee, producer],
                        payload_width=PAYLOAD_W, host_inbox=8)
    sys.spawn_block(routee, n_routees)
    sys.spawn_block(producer, n_producers)
    return sys


def make_crossshard_behavior(local_n: int):
    """Entity that forwards its token to the SAME slot in the next device
    shard — every single message crosses the mesh (all_to_all hot path)."""

    @behavior("xshard", {"received": ((), jnp.int32)})
    def xshard(state, inbox, ctx):
        nxt = (ctx.actor_id + local_n) % ctx.n_actors
        return ({"received": state["received"] + inbox.count},
                Emit.single(nxt, inbox.sum, 1, PAYLOAD_W,
                            when=inbox.count > 0))

    return xshard


def build_cross_shard(n_shards: int = 256, entities_per_shard: int = 4096,
                      n_devices=None):
    """Config 5: 256 logical shards x 4k entities on the device mesh with
    cross-shard tells (sharding/ShardRegion.scala:1046 deliverMessage as an
    all_to_all). Logical shards are folded onto the devices; every tell hops
    one device shard, so all traffic rides the exchange."""
    import jax as _jax
    n = n_shards * entities_per_shard
    if n_devices is None:
        n_devices = len(_jax.devices())
    if n % n_devices:
        n += n_devices - n % n_devices
    b = make_crossshard_behavior(n // n_devices)
    sys = ShardedBatchedSystem(capacity=n, behaviors=[b],
                               n_devices=n_devices, payload_width=PAYLOAD_W,
                               host_inbox_per_shard=8)
    sys.spawn_block(b, n)
    return sys


def build_ping_pong():
    @behavior("pp", {"hits": ((), jnp.int32)})
    def pp(state, inbox, ctx):
        other = 1 - ctx.actor_id
        return ({"hits": state["hits"] + inbox.count},
                Emit.single(other, inbox.sum, 1, PAYLOAD_W, when=inbox.count > 0))

    sys = BatchedSystem(capacity=2, behaviors=[pp], payload_width=PAYLOAD_W,
                        host_inbox=8)
    sys.spawn_block(pp, 2)
    return sys
