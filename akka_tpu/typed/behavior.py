"""Typed Behavior: immutable message -> Behavior functions, tag-interpreted.

Reference parity: akka-actor-typed/src/main/scala/akka/actor/typed/Behavior.scala
(:41) — `interpretMessage` (:229) and the tag switch (:244-278); behavior tags
from typed/internal/BehaviorImpl.scala:20. Signals from typed/Signal.scala.

This same tag model is what the TPU-batched runtime compiles: a BatchedBehavior
is the vmapped analogue of ReceiveBehavior, with the tag switch becoming
lax.switch over behavior ids (see akka_tpu/batched).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional

# -- signals (reference: akka/actor/typed/Signal.scala) ---------------------


class Signal:
    __slots__ = ()


class _PreRestart(Signal):
    def __repr__(self):
        return "PreRestart"


class _PostStop(Signal):
    def __repr__(self):
        return "PostStop"


PreRestart = _PreRestart()
PostStop = _PostStop()


@dataclass(frozen=True)
class Terminated(Signal):
    ref: Any


@dataclass(frozen=True)
class ChildFailed(Terminated):
    cause: BaseException = None  # type: ignore[assignment]


# -- behavior tags ----------------------------------------------------------


class Behavior:
    """Base. Subclass tags mirror BehaviorTags (typed/internal/BehaviorImpl.scala:20)."""

    __slots__ = ()

    def narrow(self) -> "Behavior":
        return self


class ExtensibleBehavior(Behavior):
    """User-extensible: receive(ctx, msg) -> Behavior, receive_signal(ctx, sig)
    (reference: typed/ExtensibleBehavior.scala / AbstractBehavior)."""

    def receive(self, ctx, msg) -> "Behavior":
        raise NotImplementedError

    def receive_signal(self, ctx, signal: Signal) -> "Behavior":
        return UNHANDLED


class ReceiveBehavior(ExtensibleBehavior):
    __slots__ = ("on_message", "on_signal")

    def __init__(self, on_message: Callable[[Any, Any], Behavior],
                 on_signal: Optional[Callable[[Any, Signal], Behavior]] = None):
        self.on_message = on_message
        self.on_signal = on_signal

    def receive(self, ctx, msg) -> Behavior:
        return self.on_message(ctx, msg)

    def receive_signal(self, ctx, signal: Signal) -> Behavior:
        if self.on_signal is None:
            return UNHANDLED
        return self.on_signal(ctx, signal)


class DeferredBehavior(Behavior):
    """Behaviors.setup — materialized on start (reference: BehaviorImpl.DeferredBehavior)."""

    __slots__ = ("factory",)

    def __init__(self, factory: Callable[[Any], Behavior]):
        self.factory = factory

    def __call__(self, ctx) -> Behavior:
        return self.factory(ctx)


class _Same(Behavior):
    def __repr__(self):
        return "Behaviors.same"


class _Unhandled(Behavior):
    def __repr__(self):
        return "Behaviors.unhandled"


class _Empty(Behavior):
    def __repr__(self):
        return "Behaviors.empty"


class _Ignore(Behavior):
    def __repr__(self):
        return "Behaviors.ignore"


class StoppedBehavior(Behavior):
    __slots__ = ("post_stop_cb",)

    def __init__(self, post_stop_cb: Optional[Callable[[], None]] = None):
        self.post_stop_cb = post_stop_cb

    def __repr__(self):
        return "Behaviors.stopped"


class FailedBehavior(Behavior):
    __slots__ = ("cause",)

    def __init__(self, cause: BaseException):
        self.cause = cause


SAME = _Same()
UNHANDLED = _Unhandled()
EMPTY = _Empty()
IGNORE = _Ignore()
STOPPED = StoppedBehavior()


class BehaviorInterceptor:
    """Decorator around a nested behavior (reference: typed/BehaviorInterceptor.scala)."""

    def around_receive(self, ctx, msg, target: Callable[[Any, Any], Behavior]) -> Behavior:
        return target(ctx, msg)

    def around_signal(self, ctx, signal: Signal, target: Callable[[Any, Signal], Behavior]) -> Behavior:
        return target(ctx, signal)

    def around_start(self, ctx, target: Callable[[Any], Behavior]) -> Behavior:
        return target(ctx)

    def is_same(self, other: "BehaviorInterceptor") -> bool:
        return type(self) is type(other)


class InterceptedBehavior(Behavior):
    __slots__ = ("interceptor", "nested")

    def __init__(self, interceptor: BehaviorInterceptor, nested: Behavior):
        self.interceptor = interceptor
        self.nested = nested


# -- interpretation (reference: Behavior.scala:229,244-278) ------------------


def start(behavior: Behavior, ctx) -> Behavior:
    """Undefer setup chains until a concrete behavior emerges."""
    while isinstance(behavior, (DeferredBehavior, InterceptedBehavior)):
        if isinstance(behavior, DeferredBehavior):
            behavior = behavior(ctx)
        else:
            started = behavior.interceptor.around_start(ctx, lambda c: start(behavior.nested, c))
            if started is behavior.nested or isinstance(started, _Same):
                started = behavior.nested
            if isinstance(started, (DeferredBehavior,)):
                started = start(started, ctx)
            return InterceptedBehavior(behavior.interceptor, started) \
                if not isinstance(started, (StoppedBehavior, FailedBehavior)) else started
    return behavior


def is_alive(behavior: Behavior) -> bool:
    return not isinstance(behavior, (StoppedBehavior, FailedBehavior))

def is_unhandled(behavior: Behavior) -> bool:
    return isinstance(behavior, _Unhandled)


def canonicalize(behavior: Behavior, current: Behavior, ctx) -> Behavior:
    if isinstance(behavior, _Same) or behavior is current:
        return current
    if isinstance(behavior, _Unhandled):
        return current
    if isinstance(behavior, DeferredBehavior):
        return canonicalize(start(behavior, ctx), current, ctx)
    return behavior


def interpret_message(behavior: Behavior, ctx, msg) -> Behavior:
    return _interpret(behavior, ctx, msg, is_signal=False)


def interpret_signal(behavior: Behavior, ctx, signal: Signal) -> Behavior:
    return _interpret(behavior, ctx, signal, is_signal=True)


def _interpret(behavior: Behavior, ctx, payload, is_signal: bool) -> Behavior:
    if isinstance(behavior, (_Same, _Unhandled)):
        raise ValueError(f"cannot execute {behavior!r} as an initial behavior")
    if isinstance(behavior, DeferredBehavior):
        raise ValueError("deferred behavior must be start()ed before interpretation")
    if isinstance(behavior, (StoppedBehavior, FailedBehavior, _Empty)):
        return UNHANDLED if not isinstance(behavior, StoppedBehavior) else behavior
    if isinstance(behavior, _Ignore):
        return SAME
    if isinstance(behavior, InterceptedBehavior):
        nested = behavior.nested

        def target(c, m):
            inner = _interpret(nested, c, m, is_signal)
            return inner

        if is_signal:
            result = behavior.interceptor.around_signal(ctx, payload, target)
        else:
            result = behavior.interceptor.around_receive(ctx, payload, target)
        result = canonicalize(result, nested, ctx)
        if result is nested:
            return behavior
        if not is_alive(result):
            return result
        return InterceptedBehavior(behavior.interceptor, result)
    if isinstance(behavior, ExtensibleBehavior):
        if is_signal:
            return behavior.receive_signal(ctx, payload)
        return behavior.receive(ctx, payload)
    raise TypeError(f"unknown behavior tag: {type(behavior).__name__}")
