"""Receptionist: typed service discovery registry.

Reference parity: akka-actor-typed/src/main/scala/akka/actor/typed/
receptionist/Receptionist.scala (:26-37 ServiceKey; Register/Deregister/
Find/Subscribe/Listing) with the local registry
(internal/receptionist/LocalReceptionist.scala — watch registered refs,
drop on Terminated) and the cluster implementation's semantics
(akka-cluster-typed/.../internal/receptionist/ClusterReceptionist.scala —
registry replicated as an ORMultiMap through the ddata Replicator, entries
keyed by service key, values = (node, path), pruned when members are
removed).

One receptionist actor per system at /system/receptionist; it picks the
cluster-backed registry automatically when the provider is clustered.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Dict, FrozenSet, Optional, Set

from ..actor.actor import Actor
from ..actor.messages import Terminated
from ..actor.props import Props
from ..actor.ref import ActorRef
from ..actor.system import ActorSystem


@dataclass(frozen=True)
class ServiceKey:
    """(reference: Receptionist.scala:26-37)"""
    id: str


# -- protocol ----------------------------------------------------------------

@dataclass(frozen=True)
class Register:
    key: ServiceKey
    service: ActorRef
    reply_to: Optional[ActorRef] = None


@dataclass(frozen=True)
class Registered:
    key: ServiceKey
    service: ActorRef


@dataclass(frozen=True)
class Deregister:
    key: ServiceKey
    service: ActorRef
    reply_to: Optional[ActorRef] = None


@dataclass(frozen=True)
class Deregistered:
    key: ServiceKey
    service: ActorRef


@dataclass(frozen=True)
class Find:
    key: ServiceKey
    reply_to: ActorRef


@dataclass(frozen=True)
class Subscribe:
    key: ServiceKey
    subscriber: ActorRef


@dataclass(frozen=True)
class Listing:
    key: ServiceKey
    service_instances: FrozenSet[ActorRef]

    def for_key(self, key: ServiceKey) -> FrozenSet[ActorRef]:
        return self.service_instances


@dataclass(frozen=True)
class _ReplicatorChanged:
    entries: Dict[str, FrozenSet[str]]  # key id -> paths


_DDATA_KEY = "ReceptionistKey"


class ReceptionistActor(Actor):
    """Local registry + optional ddata replication for cluster visibility."""

    def __init__(self):
        super().__init__()
        self.local: Dict[str, Set[ActorRef]] = {}      # key id -> local refs
        self.remote: Dict[str, Set[str]] = {}          # key id -> remote paths
        self.subscribers: Dict[str, Set[ActorRef]] = {}
        self.watched: Dict[ActorRef, Set[str]] = {}
        self.clustered = False
        self.self_addr = ""
        self._replicator = None
        self._node_id = ""
        provider = self.context.system.provider
        if getattr(provider, "local_address", None) is not None:
            try:
                from ..cluster.cluster import Cluster
                from ..ddata.replicator import DistributedData
                Cluster.get(self.context.system)  # asserts cluster provider
                dd = DistributedData.get(self.context.system)
                self._replicator = dd.replicator
                self._node_id = dd.self_unique_address
                self.self_addr = str(provider.default_address)
                self.clustered = True
            except Exception:  # noqa: BLE001 — not a cluster system
                self.clustered = False

    def pre_start(self) -> None:
        if self.clustered:
            from ..ddata.replicator import Subscribe as DSub, Key
            self._replicator.tell(DSub(Key(_DDATA_KEY), self.self_ref),
                                  self.self_ref)

    # -- helpers -------------------------------------------------------------
    def _all_instances(self, key_id: str) -> FrozenSet[ActorRef]:
        out = set(self.local.get(key_id, set()))
        provider = self.context.system.provider
        for path in self.remote.get(key_id, set()):
            if self.self_addr and path.startswith(self.self_addr):
                continue  # our own entries come from self.local (live refs)
            try:
                out.add(provider.resolve_actor_ref(path))
            except Exception:  # noqa: BLE001 — unresolvable stale entry
                continue
        return frozenset(out)

    def _notify(self, key_id: str) -> None:
        listing = Listing(ServiceKey(key_id), self._all_instances(key_id))
        for sub in self.subscribers.get(key_id, set()):
            sub.tell(listing, self.self_ref)

    def _ddata_update(self, fn) -> None:
        from ..ddata.crdt import ORMultiMap
        from ..ddata.replicator import Key, Update, WriteLocal
        self._replicator.tell(
            Update(Key(_DDATA_KEY), ORMultiMap.empty(), WriteLocal(), fn),
            self.self_ref)

    def _full_path(self, ref: ActorRef) -> str:
        p = ref.path.to_string_without_address()
        return f"{self.self_addr}{p}" if self.self_addr else p

    # -- receive -------------------------------------------------------------
    def receive(self, message: Any) -> Any:  # noqa: C901
        if isinstance(message, Register):
            kid = message.key.id
            self.local.setdefault(kid, set()).add(message.service)
            self.watched.setdefault(message.service, set()).add(kid)
            self.context.watch(message.service)
            if message.reply_to is not None:
                message.reply_to.tell(Registered(message.key, message.service),
                                      self.self_ref)
            if self.clustered:
                path, node = self._full_path(message.service), self._node_id
                self._ddata_update(
                    lambda m: m.add_binding(node, kid, path))
            self._notify(kid)
        elif isinstance(message, Deregister):
            kid = message.key.id
            self.local.get(kid, set()).discard(message.service)
            keys = self.watched.get(message.service)
            if keys is not None:
                keys.discard(kid)
            if message.reply_to is not None:
                message.reply_to.tell(
                    Deregistered(message.key, message.service), self.self_ref)
            if self.clustered:
                path, node = self._full_path(message.service), self._node_id
                self._ddata_update(
                    lambda m: m.remove_binding(node, kid, path))
            self._notify(kid)
        elif isinstance(message, Find):
            message.reply_to.tell(
                Listing(message.key, self._all_instances(message.key.id)),
                self.self_ref)
        elif isinstance(message, Subscribe):
            self.subscribers.setdefault(message.key.id, set()).add(
                message.subscriber)
            message.subscriber.tell(
                Listing(message.key, self._all_instances(message.key.id)),
                self.self_ref)
        elif isinstance(message, Terminated):
            keys = self.watched.pop(message.actor, set())
            for kid in keys:
                self.local.get(kid, set()).discard(message.actor)
                if self.clustered:
                    path, node = self._full_path(message.actor), self._node_id
                    self._ddata_update(
                        lambda m, k=kid, p=path: m.remove_binding(node, k, p))
                self._notify(kid)
        else:
            # ddata Changed notifications
            try:
                from ..ddata.replicator import Changed
            except Exception:  # noqa: BLE001
                return NotImplemented
            if isinstance(message, Changed) and message.key.id == _DDATA_KEY:
                new_remote: Dict[str, Set[str]] = {}
                for kid, paths in message.data.entries.items():
                    new_remote[kid] = set(paths)
                old_remote, self.remote = self.remote, new_remote
                for kid in set(new_remote) | set(old_remote):
                    if new_remote.get(kid, set()) != old_remote.get(kid, set()):
                        self._notify(kid)  # only keys whose paths changed
            else:
                return NotImplemented


class Receptionist:
    """`Receptionist.get(system).ref` — tell it Register/Find/Subscribe."""

    _instances: Dict[ActorSystem, "Receptionist"] = {}
    _lock = threading.Lock()

    @staticmethod
    def get(system) -> "Receptionist":
        classic = getattr(system, "classic", system)
        with Receptionist._lock:
            inst = Receptionist._instances.get(classic)
            if inst is None:
                inst = Receptionist._instances[classic] = Receptionist(classic)
                classic.register_on_termination(
                    lambda: Receptionist._instances.pop(classic, None))
            return inst

    def __init__(self, system: ActorSystem):
        self.system = system
        self.ref = system.system_actor_of(Props.create(ReceptionistActor),
                                          "receptionist")

    # convenience API
    def register(self, key: ServiceKey, service: ActorRef,
                 reply_to: Optional[ActorRef] = None) -> None:
        self.ref.tell(Register(key, service, reply_to), None)

    def find(self, key: ServiceKey, reply_to: ActorRef) -> None:
        self.ref.tell(Find(key, reply_to), None)

    def subscribe(self, key: ServiceKey, subscriber: ActorRef) -> None:
        self.ref.tell(Subscribe(key, subscriber), None)
