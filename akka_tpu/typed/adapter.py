"""Adapter: typed behaviors run as classic actors.

Reference parity: akka-actor-typed/src/main/scala/akka/actor/typed/internal/adapter/
ActorAdapter.scala (:55 — receive → Behavior.interpretMessage :123-129),
ActorSystemAdapter, PropsAdapter. The typed ActorContext wraps the classic cell.
"""

from __future__ import annotations

from concurrent.futures import Future
from typing import Any, Callable, Optional

from ..actor.actor import Actor
from ..actor.messages import Terminated as ClassicTerminated
from ..actor.props import Props
from ..actor.ref import ActorRef
from ..event.logging import LoggingAdapter
from .behavior import (Behavior, ChildFailed, FailedBehavior, PostStop,
                       PreRestart, StoppedBehavior, Terminated, canonicalize,
                       interpret_message, interpret_signal, is_alive,
                       is_unhandled, start)


class TypedActorContext:
    """Typed ActorContext facade over the classic ActorCell
    (reference: typed/internal/adapter/ActorContextAdapter.scala)."""

    def __init__(self, cell):
        self._cell = cell
        self._current_behavior: Optional[Behavior] = None
        self._adapters: dict = {}
        self.log = LoggingAdapter(cell.system.event_stream, str(cell.self_ref.path))

    # -- identity ------------------------------------------------------------
    @property
    def self(self) -> ActorRef:  # noqa: A003 — mirrors the reference name
        return self._cell.self_ref

    @property
    def system(self):
        return self._cell.system

    @property
    def children(self):
        return self._cell.children

    def child(self, name: str):
        return self._cell.child(name)

    # -- spawning ------------------------------------------------------------
    def spawn(self, behavior: Behavior, name: Optional[str] = None,
              props: Optional[Props] = None) -> ActorRef:
        p = props_from_behavior(behavior) if props is None else props
        return self._cell.actor_of(p, name)

    def spawn_anonymous(self, behavior: Behavior) -> ActorRef:
        return self.spawn(behavior, None)

    def stop(self, child: ActorRef) -> None:
        self._cell.stop(child)

    def watch(self, ref: ActorRef) -> None:
        self._cell.watch(ref)

    def watch_with(self, ref: ActorRef, msg: Any) -> None:
        self._cell.watch(ref, msg)

    def unwatch(self, ref: ActorRef) -> None:
        self._cell.unwatch(ref)

    def set_receive_timeout(self, timeout: float, msg: Any) -> None:
        self._receive_timeout_msg = msg
        self._cell.set_receive_timeout(timeout)

    def cancel_receive_timeout(self) -> None:
        self._cell.set_receive_timeout(None)

    # -- scheduling / interop -------------------------------------------------
    def schedule_once(self, delay: float, target: ActorRef, msg: Any):
        return self.system.scheduler.schedule_tell_once(delay, target, msg, self.self)

    def message_adapter(self, fn: Callable[[Any], Any], for_type: type = object) -> ActorRef:
        """Adapter ref translating foreign replies into our protocol.
        Re-registering for the same type replaces the function (reference:
        ActorContext.messageAdapter semantics)."""
        key = for_type
        self._adapter_fns = getattr(self, "_adapter_fns", {})
        self._adapter_fns[key] = fn
        if key in self._adapters:
            return self._adapters[key]
        me = self.self
        fns = self._adapter_fns

        def _handler(msg, sender):
            me.tell(fns[key](msg), sender)

        ref = self.system.provider.create_function_ref(_handler)
        self._adapters[key] = ref
        return ref

    def _release_resources(self) -> None:
        """Stop adapter refs + cancel timers when the actor stops."""
        for ref in self._adapters.values():
            try:
                self.system.provider.stop_function_ref(ref)
            except Exception:  # noqa: BLE001
                pass
        self._adapters.clear()
        for ts in getattr(self, "_timer_schedulers", []):
            ts.cancel_all()

    def pipe_to_self(self, future: Future, map_result: Callable[[Any, Optional[BaseException]], Any]) -> None:
        me = self.self

        def _done(f: Future):
            exc = f.exception()
            me.tell(map_result(None, exc) if exc is not None else map_result(f.result(), None))

        future.add_done_callback(_done)

    def ask(self, target: ActorRef, make_message: Callable[[ActorRef], Any],
            adapt: Callable[[Any, Optional[BaseException]], Any], timeout: float = 5.0) -> None:
        """Typed ask: reply adapted into our own protocol and self-told."""
        from ..pattern.ask import ask as _ask
        fut = _ask(target, make_message, timeout=timeout, system=self.system)
        self.pipe_to_self(fut, adapt)


class TypedActorAdapter(Actor):
    """(reference: typed/internal/adapter/ActorAdapter.scala:55)"""

    def __init__(self, behavior: Behavior):
        super().__init__()
        self._initial = behavior
        self.ctx = TypedActorContext(self.context)
        self._behavior: Optional[Behavior] = None

    def pre_start(self) -> None:
        self._behavior = start(self._initial, self.ctx)
        self.ctx._current_behavior = self._behavior
        self._last_alive: Optional[Behavior] = self._behavior if is_alive(self._behavior) else None
        self._check_alive()

    def receive(self, message: Any):
        try:
            self._receive(message)
        except Exception as e:  # noqa: BLE001
            # typed default: an unhandled exception STOPS the actor (reference:
            # typed failure handling — no restart unless Behaviors.supervise)
            self.ctx.log.error(f"typed behavior failed, stopping: {e!r}", e)
            self._behavior = FailedBehavior(e)
            self.context.stop()

    def _receive(self, message: Any):
        if isinstance(message, ClassicTerminated):
            cause = getattr(message, "cause", None)
            is_child = message.actor.path.parent == self.context.self_ref.path
            sig = (ChildFailed(message.actor, cause) if (cause is not None and is_child)
                   else Terminated(message.actor))
            nxt = interpret_signal(self._behavior, self.ctx, sig)
            if is_unhandled(nxt):
                # typed semantics: unhandled Terminated throws DeathPactException
                from ..actor.messages import DeathPactException
                raise DeathPactException(message.actor)
        else:
            timeout_msg = getattr(self.ctx, "_receive_timeout_msg", None)
            from ..actor.messages import ReceiveTimeout as _RT
            if message is _RT and timeout_msg is not None:
                message = timeout_msg
            nxt = interpret_message(self._behavior, self.ctx, message)
            if is_unhandled(nxt):
                from ..actor.messages import UnhandledMessage
                self.context.system.event_stream.publish(
                    UnhandledMessage(message, self.context.sender, self.context.self_ref))
        self._behavior = canonicalize(nxt, self._behavior, self.ctx)
        self.ctx._current_behavior = self._behavior
        if is_alive(self._behavior):
            self._last_alive = self._behavior
        self._check_alive()

    def _check_alive(self) -> None:
        if not is_alive(self._behavior):
            self.context.stop()

    def post_stop(self) -> None:
        self.ctx._release_resources()
        b = self._behavior
        if isinstance(b, StoppedBehavior) and b.post_stop_cb is not None:
            try:
                b.post_stop_cb()
            except Exception:  # noqa: BLE001
                pass
        else:
            target = b if (b is not None and is_alive(b)) else getattr(self, "_last_alive", None)
            if target is not None:
                try:
                    interpret_signal(target, self.ctx, PostStop)
                except Exception:  # noqa: BLE001
                    pass

    def pre_restart(self, reason, message) -> None:
        if self._behavior is not None and is_alive(self._behavior):
            try:
                interpret_signal(self._behavior, self.ctx, PreRestart)
            except Exception:  # noqa: BLE001
                pass
        super().pre_restart(reason, message)


def props_from_behavior(behavior: Behavior, dispatcher: Optional[str] = None) -> Props:
    p = Props.create(TypedActorAdapter, behavior)
    return p.with_dispatcher(dispatcher) if dispatcher else p
