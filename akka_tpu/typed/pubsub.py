"""Typed local pub-sub Topic.

Reference parity: akka-actor-typed/src/main/scala/akka/actor/typed/pubsub/
Topic.scala — a Topic actor per topic name; Subscribe/Unsubscribe local
refs; Publish fans out; when clustered, topics find each other through the
Receptionist (the reference uses the receptionist for topic discovery too),
so a publish on one node reaches subscribers everywhere.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Set

from ..actor.actor import Actor
from ..actor.messages import Terminated
from ..actor.props import Props
from ..actor.ref import ActorRef
from .receptionist import Listing, Receptionist, ServiceKey


@dataclass(frozen=True)
class TopicSubscribe:
    subscriber: ActorRef


@dataclass(frozen=True)
class TopicUnsubscribe:
    subscriber: ActorRef


@dataclass(frozen=True)
class Publish:
    message: Any


@dataclass(frozen=True)
class _TopicMessage:
    message: Any


class TopicActor(Actor):
    def __init__(self, topic_name: str):
        super().__init__()
        self.topic_name = topic_name
        self.key = ServiceKey(f"topic-{topic_name}")
        self.subscribers: Set[ActorRef] = set()
        self.peers: Set[ActorRef] = set()

    def pre_start(self) -> None:
        rec = Receptionist.get(self.context.system)
        rec.register(self.key, self.self_ref)
        rec.subscribe(self.key, self.self_ref)

    def receive(self, message: Any) -> Any:
        if isinstance(message, TopicSubscribe):
            self.subscribers.add(message.subscriber)
            self.context.watch(message.subscriber)
        elif isinstance(message, TopicUnsubscribe):
            self.subscribers.discard(message.subscriber)
            self.context.unwatch(message.subscriber)
        elif isinstance(message, Terminated):
            self.subscribers.discard(message.actor)
        elif isinstance(message, Publish):
            for peer in self.peers:
                peer.tell(_TopicMessage(message.message), self.self_ref)
            if not self.peers:  # not yet discovered (at least ourselves)
                self._deliver(message.message)
        elif isinstance(message, _TopicMessage):
            self._deliver(message.message)
        elif isinstance(message, Listing):
            self.peers = set(message.service_instances)
        else:
            return NotImplemented

    def _deliver(self, msg: Any) -> None:
        for sub in list(self.subscribers):
            sub.tell(msg, self.self_ref)


class Topic:
    """Topic.create(system, name) -> ref accepting Subscribe/Publish."""

    @staticmethod
    def create(system, topic_name: str, actor_name: str = None) -> ActorRef:
        classic = getattr(system, "classic", system)
        return classic.actor_of(Props.create(TopicActor, topic_name),
                                actor_name)
