"""Behaviors factory DSL + typed supervision.

Reference parity: akka-actor-typed/src/main/scala/akka/actor/typed/scaladsl/Behaviors.scala
and typed/internal/Supervision.scala (:60 AbstractSupervisor, :188 RestartSupervisor) —
restart / resume / stop / restart-with-backoff as behavior decorators.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Type

from .behavior import (SAME, STOPPED, UNHANDLED, EMPTY, IGNORE, Behavior,
                       BehaviorInterceptor, DeferredBehavior, InterceptedBehavior,
                       PreRestart, ReceiveBehavior, Signal, StoppedBehavior,
                       canonicalize, interpret_message, interpret_signal, start,
                       is_alive)


class Behaviors:
    same: Behavior = SAME
    unhandled: Behavior = UNHANDLED
    empty: Behavior = EMPTY
    ignore: Behavior = IGNORE

    @staticmethod
    def receive(on_message: Callable[[Any, Any], Behavior],
                on_signal: Optional[Callable[[Any, Signal], Behavior]] = None) -> Behavior:
        return ReceiveBehavior(on_message, on_signal)

    @staticmethod
    def receive_message(on_message: Callable[[Any], Behavior]) -> Behavior:
        return ReceiveBehavior(lambda _ctx, msg: on_message(msg))

    @staticmethod
    def receive_signal(on_signal: Callable[[Any, Signal], Behavior]) -> Behavior:
        return ReceiveBehavior(lambda _ctx, _msg: UNHANDLED, on_signal)

    @staticmethod
    def setup(factory: Callable[[Any], Behavior]) -> Behavior:
        return DeferredBehavior(factory)

    @staticmethod
    def stopped(post_stop: Optional[Callable[[], None]] = None) -> Behavior:
        return StoppedBehavior(post_stop) if post_stop else STOPPED

    @staticmethod
    def supervise(behavior: Behavior) -> "Supervise":
        return Supervise(behavior)

    @staticmethod
    def with_timers(factory: Callable[["TimerScheduler"], Behavior]) -> Behavior:
        def _setup(ctx):
            timers = TimerScheduler(ctx)
            # registered so the adapter / supervisor cancels them on
            # stop/restart (the reference cancels on PostStop/PreRestart)
            if not hasattr(ctx, "_timer_schedulers"):
                ctx._timer_schedulers = []
            ctx._timer_schedulers.append(timers)
            return factory(timers)
        return DeferredBehavior(_setup)

    @staticmethod
    def monitor(monitor_ref, behavior: Behavior) -> Behavior:
        """Forward every message to `monitor_ref` before processing
        (reference: Behaviors.monitor)."""

        class _Monitor(BehaviorInterceptor):
            def around_receive(self, ctx, msg, target):
                monitor_ref.tell(msg)
                return target(ctx, msg)

        return InterceptedBehavior(_Monitor(), behavior)

    @staticmethod
    def with_stash(capacity: int, factory: Callable[["StashBuffer"], Behavior]) -> Behavior:
        def _setup(ctx):
            return factory(StashBuffer(ctx, capacity))
        return DeferredBehavior(_setup)

    @staticmethod
    def intercept(interceptor_factory: Callable[[], BehaviorInterceptor],
                  behavior: Behavior) -> Behavior:
        return InterceptedBehavior(interceptor_factory(), behavior)


# -- typed supervision (reference: typed/internal/Supervision.scala) ---------


@dataclass(frozen=True)
class SupervisorStrategy:
    kind: str = "restart"           # restart | resume | stop | backoff
    max_restarts: int = -1
    within: float = float("inf")
    min_backoff: float = 0.2
    max_backoff: float = 30.0
    random_factor: float = 0.2
    stop_children: bool = True

    @staticmethod
    def restart(max_restarts: int = -1, within: float = float("inf")) -> "SupervisorStrategy":
        return SupervisorStrategy("restart", max_restarts, within)

    @staticmethod
    def resume() -> "SupervisorStrategy":
        return SupervisorStrategy("resume")

    @staticmethod
    def stop() -> "SupervisorStrategy":
        return SupervisorStrategy("stop")

    @staticmethod
    def restart_with_backoff(min_backoff: float, max_backoff: float,
                             random_factor: float = 0.2) -> "SupervisorStrategy":
        return SupervisorStrategy("backoff", min_backoff=min_backoff,
                                  max_backoff=max_backoff, random_factor=random_factor)


@dataclass(frozen=True)
class _ScheduledRestart:
    generation: int


class _Supervisor(BehaviorInterceptor):
    """(reference: typed/internal/Supervision.scala:60,188)"""

    def __init__(self, initial: Behavior, strategy: SupervisorStrategy,
                 exc_type: Type[BaseException] = Exception):
        self.initial = initial
        self.strategy = strategy
        self.exc_type = exc_type
        self._restarts: list[float] = []
        self._backoff_count = 0
        self._generation = 0

    def is_same(self, other: BehaviorInterceptor) -> bool:
        return isinstance(other, _Supervisor) and other.exc_type is self.exc_type

    def around_start(self, ctx, target):
        try:
            return target(ctx)
        except self.exc_type as e:
            return self._handle(ctx, e)

    def around_receive(self, ctx, msg, target):
        if isinstance(msg, _ScheduledRestart):
            if msg.generation == self._generation:
                return start(self.initial, ctx)
            return SAME
        try:
            return target(ctx, msg)
        except self.exc_type as e:
            return self._handle(ctx, e)

    def around_signal(self, ctx, signal, target):
        try:
            return target(ctx, signal)
        except self.exc_type as e:
            return self._handle(ctx, e)

    def _handle(self, ctx, exc: BaseException) -> Behavior:
        from .behavior import FailedBehavior
        s = self.strategy
        ctx.log.error(f"supervised behavior failed: {exc!r} -> {s.kind}", exc)
        if s.kind == "resume":
            return SAME
        if s.kind == "stop":
            return FailedBehavior(exc)
        if s.kind == "restart":
            now = time.monotonic()
            if s.within != float("inf"):
                self._restarts = [t for t in self._restarts if now - t < s.within]
            if s.max_restarts >= 0 and len(self._restarts) >= s.max_restarts:
                return FailedBehavior(exc)
            self._restarts.append(now)
            self._signal_restart(ctx)
            self._stop_children(ctx)
            return start(self.initial, ctx)
        if s.kind == "backoff":
            delay = min(s.min_backoff * (2 ** self._backoff_count), s.max_backoff)
            delay *= 1.0 + random.random() * s.random_factor
            self._backoff_count += 1
            self._generation += 1
            self._signal_restart(ctx)
            self._stop_children(ctx)
            gen = self._generation
            ctx.schedule_once(delay, ctx.self, _ScheduledRestart(gen))
            # while backing off, messages are dropped (the reference dead-letters)
            return Behaviors.ignore
        return FailedBehavior(exc)

    def _stop_children(self, ctx) -> None:
        if not self.strategy.stop_children:
            return
        cell = getattr(ctx, "_cell", None)
        for child in list(ctx.children):
            ctx.stop(child)
            # free the name immediately so a re-run setup can respawn it: the
            # old incarnation keeps terminating under a distinct uid (diverges
            # from the reference, which reserves the name until termination)
            if cell is not None:
                cell._children.pop(child.path.name, None)
                cell._child_stats.pop(child.path.name, None)

    def _signal_restart(self, ctx) -> None:
        """Deliver PreRestart to the NESTED behavior (not through this
        interceptor — a raising PreRestart handler must not recurse into
        _handle and burn the restart budget)."""
        try:
            cur = getattr(ctx, "_current_behavior", None)
            while isinstance(cur, InterceptedBehavior):
                if cur.interceptor is self:
                    cur = cur.nested
                    break
                cur = cur.nested
            if cur is not None and is_alive(cur):
                interpret_signal(cur, ctx, PreRestart)
        except Exception:  # noqa: BLE001
            pass
        # cancel this incarnation's timers (with_timers registers on the ctx)
        for ts in getattr(ctx, "_timer_schedulers", []):
            ts.cancel_all()


class Supervise:
    def __init__(self, behavior: Behavior):
        self.behavior = behavior

    def on_failure(self, strategy: SupervisorStrategy,
                   exc_type: Type[BaseException] = Exception) -> Behavior:
        # deferred so each spawned actor gets a FRESH supervisor instance —
        # the interceptor holds per-actor state (_restarts/_generation)
        behavior = self.behavior
        return DeferredBehavior(lambda _ctx: InterceptedBehavior(
            _Supervisor(behavior, strategy, exc_type), behavior))


# -- timers (reference: typed/scaladsl/TimerScheduler, TimerSchedulerImpl) ----


class TimerScheduler:
    def __init__(self, ctx):
        self._ctx = ctx
        self._timers: dict = {}

    def start_single_timer(self, key: Any, msg: Any, delay: float) -> None:
        self.cancel(key)
        task = self._ctx.schedule_once(delay, self._ctx.self, msg)
        self._timers[key] = task

    def start_timer_with_fixed_delay(self, key: Any, msg: Any, delay: float,
                                     initial_delay: Optional[float] = None) -> None:
        self.cancel(key)
        task = self._ctx.system.scheduler.schedule_tell_with_fixed_delay(
            initial_delay if initial_delay is not None else delay, delay,
            self._ctx.self, msg)
        self._timers[key] = task

    start_timer_at_fixed_rate = start_timer_with_fixed_delay

    def is_timer_active(self, key: Any) -> bool:
        t = self._timers.get(key)
        return t is not None and not t.is_cancelled

    def cancel(self, key: Any) -> None:
        t = self._timers.pop(key, None)
        if t is not None:
            t.cancel()

    def cancel_all(self) -> None:
        for t in self._timers.values():
            t.cancel()
        self._timers.clear()


# -- stash buffer (reference: typed/internal/StashBufferImpl.scala) ----------


class StashException(Exception):
    pass


class StashBuffer:
    def __init__(self, ctx, capacity: int):
        self._ctx = ctx
        self.capacity = capacity
        self._buf: list = []

    def stash(self, msg: Any) -> None:
        if len(self._buf) >= self.capacity:
            raise StashException(f"stash buffer full ({self.capacity})")
        self._buf.append(msg)

    @property
    def is_empty(self) -> bool:
        return not self._buf

    @property
    def is_full(self) -> bool:
        return len(self._buf) >= self.capacity

    @property
    def size(self) -> int:
        return len(self._buf)

    def unstash_all(self, behavior: Behavior) -> Behavior:
        """Process all stashed messages through `behavior` synchronously
        (reference: StashBufferImpl.unstashAll)."""
        b = start(behavior, self._ctx)
        msgs, self._buf = self._buf, []
        for i, m in enumerate(msgs):
            if not is_alive(b):
                # dead-letter the rest (mirrors classic Stash.post_stop)
                from ..actor.messages import DeadLetter
                dl = self._ctx.system.dead_letters
                for rest in msgs[i:]:
                    dl.tell(DeadLetter(rest, self._ctx.self, self._ctx.self), None)
                break
            nxt = interpret_message(b, self._ctx, m)
            b = canonicalize(nxt, b, self._ctx)
        return b

    def foreach(self, fn: Callable[[Any], None]) -> None:
        for m in self._buf:
            fn(m)
