"""Typed actor API (reference: akka-actor-typed).

Usage:
    from akka_tpu.typed import ActorSystem, Behaviors

    def counter(count=0):
        def on_message(ctx, msg):
            if msg == "inc":
                return counter(count + 1)
            ...
        return Behaviors.receive(on_message)

    system = ActorSystem.create(counter(), "counter")
"""

from .behavior import (Behavior, Signal, PreRestart, PostStop, Terminated,  # noqa: F401
                       ChildFailed)
from .behaviors import (Behaviors, SupervisorStrategy, TimerScheduler,  # noqa: F401
                        StashBuffer, StashException)
from .adapter import TypedActorContext, props_from_behavior  # noqa: F401
from .actor_system import ActorSystem  # noqa: F401
from .receptionist import (Deregister, Deregistered, Find, Listing,  # noqa: F401
                           Receptionist, Register, Registered, ServiceKey,
                           Subscribe)
from . import delivery  # noqa: F401
from .pubsub import Publish, Topic, TopicSubscribe, TopicUnsubscribe  # noqa: F401
from .routers import Routers  # noqa: F401
