"""Reliable delivery: sequenced producer/consumer controllers with resend,
flow control, work pulling, and an optional durable queue.

Reference parity: akka-actor-typed/src/main/scala/akka/actor/typed/delivery/
— ProducerController.scala / ConsumerController.scala (demand: Request
(confirmedSeqNr, requestUpToSeqNr), SequencedMessage(producerId, seqNr,
first, ack), gap detection + Resend(fromSeqNr), Ack on confirm),
WorkPullingProducerController.scala (workers discovered via a Receptionist
ServiceKey, each with its own demand), DurableProducerQueue.scala +
EventSourcedProducerQueue (unconfirmed messages replayed after producer
restart), impl in delivery/internal/ProducerControllerImpl.scala:334.

Implemented as classic actors (our typed behaviors run on the same cells;
refs interoperate) with the reference's message protocol.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..actor.actor import Actor
from ..actor.messages import Terminated
from ..actor.props import Props
from ..actor.ref import ActorRef


# -- producer-facing API (reference: ProducerController object) --------------

@dataclass(frozen=True)
class Start:
    """Producer (or consumer) registers itself."""
    ref: ActorRef


@dataclass(frozen=True)
class RequestNext:
    """Demand: send ONE message to `send_next_to` (reference:
    ProducerController.RequestNext)."""
    producer_id: str
    current_seq_nr: int
    send_next_to: ActorRef


@dataclass(frozen=True)
class MessageWithConfirmation:
    """Send + ask for an ack when the consumer confirms."""
    message: Any
    reply_to: ActorRef


@dataclass(frozen=True)
class RegisterConsumer:
    consumer_controller: ActorRef


# -- consumer-facing API (reference: ConsumerController object) --------------

@dataclass(frozen=True)
class Delivery:
    producer_id: str
    seq_nr: int
    message: Any
    confirm_to: ActorRef


@dataclass(frozen=True)
class Confirmed:
    pass


@dataclass(frozen=True)
class RegisterToProducerController:
    producer_controller: ActorRef


# -- wire protocol (reference: ConsumerController.SequencedMessage etc.) -----

@dataclass(frozen=True)
class SequencedMessage:
    producer_id: str
    seq_nr: int
    message: Any
    first: bool
    ack: bool
    producer_controller: ActorRef


@dataclass(frozen=True)
class Request:
    confirmed_seq_nr: int
    request_up_to_seq_nr: int
    support_resend: bool = True


@dataclass(frozen=True)
class Resend:
    from_seq_nr: int


@dataclass(frozen=True)
class Ack:
    confirmed_seq_nr: int


# -- durable queue protocol (reference: DurableProducerQueue.scala) ----------

@dataclass(frozen=True)
class StoreMessageSent:
    seq_nr: int
    message: Any
    reply_to: ActorRef


@dataclass(frozen=True)
class StoreMessageSentAck:
    stored_seq_nr: int


@dataclass(frozen=True)
class StoreMessageConfirmed:
    seq_nr: int


@dataclass(frozen=True)
class LoadState:
    reply_to: ActorRef


@dataclass(frozen=True)
class DurableState:
    current_seq_nr: int       # next unallocated seq nr
    highest_confirmed_seq_nr: int
    unconfirmed: Tuple[Tuple[int, Any], ...]


def _make_durable_queue_props(persistence_id: str) -> Props:
    """Durable queue backed by the persistence journal (reference:
    EventSourcedProducerQueue.scala). Events: ("sent", seq, msg) and
    ("confirmed", seq)."""
    from ..persistence.eventsourced import PersistentActor
    from ..persistence.messages import RecoveryCompleted, SnapshotOffer

    class _ESQueue(PersistentActor):
        def __init__(self):
            super().__init__()
            self.seq_nr = 1
            self.confirmed = 0
            self.unconfirmed: Dict[int, Any] = {}

        @property
        def persistence_id(self) -> str:
            return f"durable-queue|{persistence_id}"

        def receive_recover(self, message):
            if isinstance(message, SnapshotOffer):
                self.seq_nr, self.confirmed, unconf = message.snapshot
                self.unconfirmed = dict(unconf)
            elif isinstance(message, tuple):
                self._apply(message)
            elif isinstance(message, RecoveryCompleted):
                pass
            else:
                return NotImplemented

        def _apply(self, ev):
            if ev[0] == "sent":
                self.unconfirmed[ev[1]] = ev[2]
                self.seq_nr = max(self.seq_nr, ev[1] + 1)
            else:  # confirmed
                self.confirmed = max(self.confirmed, ev[1])
                for s in [s for s in self.unconfirmed if s <= ev[1]]:
                    del self.unconfirmed[s]

        def receive_command(self, message):
            if isinstance(message, StoreMessageSent):
                def done(ev):
                    self._apply(ev)
                    message.reply_to.tell(StoreMessageSentAck(ev[1]),
                                          self.self_ref)
                self.persist(("sent", message.seq_nr, message.message), done)
            elif isinstance(message, StoreMessageConfirmed):
                self.persist(("confirmed", message.seq_nr), self._apply)
            elif isinstance(message, LoadState):
                message.reply_to.tell(DurableState(
                    self.seq_nr, self.confirmed,
                    tuple(sorted(self.unconfirmed.items()))), self.self_ref)
            else:
                return NotImplemented
    return Props.create(_ESQueue)


class ProducerController(Actor):
    """(reference: ProducerControllerImpl.scala) One per producer; connects
    to exactly one ConsumerController."""

    def __init__(self, producer_id: str,
                 durable_queue_props: Optional[Props] = None):
        super().__init__()
        self.producer_id = producer_id
        self.producer: Optional[ActorRef] = None
        self.consumer_controller: Optional[ActorRef] = None
        self.current_seq = 1           # next seq nr to assign
        self.confirmed_seq = 0
        self.requested_up_to = 0
        self.unconfirmed: Dict[int, Any] = {}
        self.first_sent = False
        self.pending_replies: Dict[int, ActorRef] = {}  # seq -> ask reply_to
        self.durable: Optional[ActorRef] = None
        self._durable_props = durable_queue_props
        self._demand_outstanding = False
        self._replay: List[Tuple[int, Any]] = []

    def pre_start(self) -> None:
        if self._durable_props is not None:
            self.durable = self.context.actor_of(self._durable_props,
                                                 "durable")
            self.durable.tell(LoadState(self.self_ref), self.self_ref)

    # -- helpers -------------------------------------------------------------
    def _maybe_request_next(self) -> None:
        if (self.producer is not None and not self._demand_outstanding
                and self.consumer_controller is not None
                and self.current_seq <= self.requested_up_to):
            self._demand_outstanding = True
            self.producer.tell(RequestNext(self.producer_id,
                                           self.current_seq, self.self_ref),
                               self.self_ref)

    def _send(self, seq: int, msg: Any) -> None:
        # `first` marks the first message of the SESSION with this consumer
        # controller (reset on RegisterConsumer) so a fresh consumer can
        # adopt the sequence base instead of demanding a resend from 1
        self.consumer_controller.tell(
            SequencedMessage(self.producer_id, seq, msg,
                             first=not self.first_sent,
                             ack=seq in self.pending_replies,
                             producer_controller=self.self_ref),
            self.self_ref)
        self.first_sent = True

    def _on_new_message(self, msg: Any, reply_to: Optional[ActorRef]) -> None:
        seq = self.current_seq
        self.current_seq += 1
        self._demand_outstanding = False
        if reply_to is not None:
            self.pending_replies[seq] = reply_to
        if self.durable is not None:
            self.durable.tell(StoreMessageSent(seq, msg, self.self_ref),
                              self.self_ref)
            # optimistic send; redelivery covers a crash before the ack
        self.unconfirmed[seq] = msg
        if self.consumer_controller is not None:
            self._send(seq, msg)
        self._maybe_request_next()

    # -- receive -------------------------------------------------------------
    def receive(self, message: Any) -> Any:  # noqa: C901
        if isinstance(message, Start):
            self.producer = message.ref
            self._maybe_request_next()
        elif isinstance(message, RegisterConsumer):
            self.consumer_controller = message.consumer_controller
            self.first_sent = False  # new session: next send carries first=True
            # resend everything outstanding to the (new) consumer controller
            for seq in sorted(self.unconfirmed):
                self._send(seq, self.unconfirmed[seq])
        elif isinstance(message, MessageWithConfirmation):
            self._on_new_message(message.message, message.reply_to)
        elif isinstance(message, Request):
            self.requested_up_to = max(self.requested_up_to,
                                       message.request_up_to_seq_nr)
            self._confirm_through(message.confirmed_seq_nr)
            self._maybe_request_next()
        elif isinstance(message, Resend):
            for seq in sorted(self.unconfirmed):
                if seq >= message.from_seq_nr:
                    self._send(seq, self.unconfirmed[seq])
        elif isinstance(message, Ack):
            self._confirm_through(message.confirmed_seq_nr)
        elif isinstance(message, DurableState):
            self.current_seq = max(self.current_seq, message.current_seq_nr)
            self.confirmed_seq = max(self.confirmed_seq,
                                     message.highest_confirmed_seq_nr)
            for seq, msg in message.unconfirmed:
                self.unconfirmed.setdefault(seq, msg)
                if self.consumer_controller is not None:
                    self._send(seq, msg)
            self._maybe_request_next()
        elif isinstance(message, StoreMessageSentAck):
            pass
        else:
            # a plain message from the producer answering RequestNext
            self._on_new_message(message, None)

    def _confirm_through(self, seq: int) -> None:
        if seq <= self.confirmed_seq:
            return
        self.confirmed_seq = seq
        for s in [s for s in self.unconfirmed if s <= seq]:
            del self.unconfirmed[s]
        for s in [s for s in self.pending_replies if s <= seq]:
            self.pending_replies.pop(s).tell(s, self.self_ref)
        if self.durable is not None:
            self.durable.tell(StoreMessageConfirmed(seq), self.self_ref)


class ConsumerController(Actor):
    """(reference: ConsumerControllerImpl.scala) Delivers in order, detects
    gaps, confirms, and keeps `flow_control_window` demand open."""

    def __init__(self, flow_control_window: int = 20,
                 resend_interval: float = 1.0):
        super().__init__()
        self.window = flow_control_window
        self.resend_interval = resend_interval
        self.consumer: Optional[ActorRef] = None
        self.producer_controller: Optional[ActorRef] = None
        self.producer_id = ""
        self.received_seq = 0         # highest in-order received
        self.confirmed_seq = 0
        self.requested_up_to = 0
        self.delivering = False       # waiting for Confirmed from consumer
        self.stash: List[SequencedMessage] = []
        self._task = None

    def pre_start(self) -> None:
        self._task = self.context.system.scheduler.schedule_tell_with_fixed_delay(
            self.resend_interval, self.resend_interval, self.self_ref,
            _RetryTick())

    def post_stop(self) -> None:
        if self._task:
            self._task.cancel()

    def _request_more(self) -> None:
        if self.producer_controller is None:
            return
        new_up_to = self.confirmed_seq + self.window
        if new_up_to > self.requested_up_to:
            self.requested_up_to = new_up_to
            self.producer_controller.tell(
                Request(self.confirmed_seq, new_up_to), self.self_ref)

    def _deliver_next(self) -> None:
        if self.delivering or self.consumer is None:
            return
        while self.stash and self.stash[0].seq_nr <= self.received_seq:
            self.stash.pop(0)  # duplicates
        if self.stash and self.stash[0].seq_nr == self.received_seq + 1:
            sm = self.stash.pop(0)
            self.received_seq = sm.seq_nr
            self.delivering = True
            self.consumer.tell(Delivery(sm.producer_id, sm.seq_nr, sm.message,
                                        self.self_ref), self.self_ref)

    def receive(self, message: Any) -> Any:  # noqa: C901
        if isinstance(message, Start):
            self.consumer = message.ref
            self._deliver_next()
        elif isinstance(message, RegisterToProducerController):
            self.producer_controller = message.producer_controller
            message.producer_controller.tell(RegisterConsumer(self.self_ref),
                                             self.self_ref)
            self._request_more()
        elif isinstance(message, SequencedMessage):
            if self.producer_controller is None:
                self.producer_controller = message.producer_controller
                self._request_more()
            self.producer_id = message.producer_id
            if message.first and message.seq_nr > self.received_seq + 1:
                # adopt the producer's base: a session's first message may
                # start past 1 (restart with confirmed history) — reference
                # ConsumerControllerImpl sets receivedSeqNr = seqNr - 1
                self.received_seq = message.seq_nr - 1
            if message.seq_nr <= self.received_seq:
                pass  # duplicate
            elif message.seq_nr == self.received_seq + 1:
                self.stash.append(message)
                self.stash.sort(key=lambda m: m.seq_nr)
                self._deliver_next()
            else:
                # gap: buffer out-of-order, ask for resend
                self.stash.append(message)
                self.stash.sort(key=lambda m: m.seq_nr)
                message.producer_controller.tell(
                    Resend(self.received_seq + 1), self.self_ref)
        elif isinstance(message, Confirmed):
            self.confirmed_seq = self.received_seq
            self.delivering = False
            if self.producer_controller is not None:
                self.producer_controller.tell(Ack(self.confirmed_seq),
                                              self.self_ref)
            self._request_more()
            self._deliver_next()
        elif isinstance(message, _RetryTick):
            if self.producer_controller is not None and \
                    self.stash and not self.delivering and \
                    self.stash[0].seq_nr > self.received_seq + 1:
                self.producer_controller.tell(Resend(self.received_seq + 1),
                                              self.self_ref)
        else:
            return NotImplemented


@dataclass(frozen=True)
class _RetryTick:
    pass


# -- work pulling ------------------------------------------------------------

@dataclass(frozen=True)
class WorkPullingRequestNext:
    """Demand from the pool: send ONE job to `send_next_to`."""
    send_next_to: ActorRef


class WorkPullingProducerController(Actor):
    """Distributes messages to whichever registered worker has demand
    (reference: WorkPullingProducerController.scala — workers register via
    a Receptionist ServiceKey; each worker pair gets its own session)."""

    def __init__(self, producer_id: str, worker_service_key):
        super().__init__()
        from .receptionist import Receptionist
        self.producer_id = producer_id
        self.key = worker_service_key
        self.producer: Optional[ActorRef] = None
        # worker consumer-controller ref -> session state
        self.sessions: Dict[ActorRef, Dict[str, Any]] = {}
        self.queue: List[Any] = []   # unsent jobs
        self.seq = 1
        self._demand_outstanding = False
        Receptionist.get(self.context.system).subscribe(self.key,
                                                        self.self_ref)

    def _maybe_request_next(self) -> None:
        if self.producer is None or self._demand_outstanding:
            return
        if any(s["demand"] > 0 for s in self.sessions.values()) or \
                len(self.queue) < 100:
            self._demand_outstanding = True
            self.producer.tell(WorkPullingRequestNext(self.self_ref),
                               self.self_ref)

    @staticmethod
    def _new_session() -> Dict[str, Any]:
        return {"demand": 0, "next_seq": 1, "confirmed": 0,
                "unconfirmed": {}, "active": True, "bootstrapped": False}

    def _dispatch(self) -> None:
        while self.queue:
            target = None
            for cc, s in self.sessions.items():
                if s["active"] and s["demand"] > 0:
                    target = cc
                    break
            if target is None:
                # no open demand: bootstrap a session with ONE first=True
                # message — the consumer controller learns the producer from
                # it and answers with Request (reference: first=true send)
                for cc, s in self.sessions.items():
                    if s["active"] and not s["bootstrapped"] \
                            and not s["unconfirmed"]:
                        target = cc
                        s["demand"] = 1
                        s["bootstrapped"] = True
                        break
            if target is None:
                return
            job = self.queue.pop(0)
            s = self.sessions[target]
            seq = s["next_seq"]
            s["next_seq"] += 1
            s["demand"] -= 1
            s["unconfirmed"][seq] = job
            target.tell(SequencedMessage(self.producer_id, seq, job,
                                         first=(seq == 1), ack=False,
                                         producer_controller=self.self_ref),
                        self.self_ref)

    def receive(self, message: Any) -> Any:  # noqa: C901
        from .receptionist import Listing
        if isinstance(message, Start):
            self.producer = message.ref
            self._maybe_request_next()
        elif isinstance(message, Listing):
            current = set(message.service_instances)
            for cc in list(self.sessions):
                if cc not in current and self.sessions[cc]["active"]:
                    # worker gone: requeue its unconfirmed jobs in order.
                    # Keep the session (with its seq counter) — a transient
                    # listing flap must NOT reset next_seq to 1, or the
                    # worker's consumer controller would discard the
                    # redelivered jobs as duplicates
                    s = self.sessions[cc]
                    s["active"] = False
                    jobs = [s["unconfirmed"][seq]
                            for seq in sorted(s["unconfirmed"])]
                    s["unconfirmed"].clear()
                    s["demand"] = 0
                    self.queue[:0] = jobs
            for cc in current:
                if cc not in self.sessions:
                    self.sessions[cc] = self._new_session()
                else:
                    self.sessions[cc]["active"] = True
            self._dispatch()
            self._maybe_request_next()
        elif isinstance(message, Request):
            s = self.sessions.get(self.sender)
            if s is not None:
                s["demand"] = max(
                    s["demand"],
                    message.request_up_to_seq_nr - s["next_seq"] + 1)
                self._confirm(self.sender, message.confirmed_seq_nr)
            self._dispatch()
            self._maybe_request_next()
        elif isinstance(message, Ack):
            self._confirm(self.sender, message.confirmed_seq_nr)
        elif isinstance(message, Resend):
            s = self.sessions.get(self.sender)
            if s is not None:
                for seq in sorted(s["unconfirmed"]):
                    if seq >= message.from_seq_nr:
                        self.sender.tell(
                            SequencedMessage(self.producer_id, seq,
                                             s["unconfirmed"][seq],
                                             first=(seq == 1), ack=False,
                                             producer_controller=self.self_ref),
                            self.self_ref)
        elif isinstance(message, RegisterConsumer):
            if message.consumer_controller not in self.sessions:
                self.sessions[message.consumer_controller] = \
                    self._new_session()
        else:
            # job from the producer answering WorkPullingRequestNext
            self._demand_outstanding = False
            self.queue.append(message)
            self._dispatch()
            self._maybe_request_next()

    def _confirm(self, cc: ActorRef, seq: int) -> None:
        s = self.sessions.get(cc)
        if s is None:
            return
        s["confirmed"] = max(s["confirmed"], seq)
        for k in [k for k in s["unconfirmed"] if k <= seq]:
            del s["unconfirmed"][k]


def producer_controller_props(producer_id: str,
                              durable_queue_name: Optional[str] = None
                              ) -> Props:
    dq = _make_durable_queue_props(durable_queue_name) \
        if durable_queue_name else None
    return Props.create(ProducerController, producer_id, dq)


def consumer_controller_props(flow_control_window: int = 20,
                              resend_interval: float = 1.0) -> Props:
    return Props.create(ConsumerController, flow_control_window,
                        resend_interval)


def work_pulling_producer_props(producer_id: str, worker_service_key) -> Props:
    return Props.create(WorkPullingProducerController, producer_id,
                        worker_service_key)
