"""Typed routers: pool and group (reference parity:
akka-actor-typed/src/main/scala/akka/actor/typed/scaladsl/Routers.scala:24,36
— PoolRouter spawns N children of one behavior and routes over them;
GroupRouter routes over receptionist Listings for a ServiceKey; impl in
typed/internal/routing/).

Both are plain Behaviors: spawn them like any other —
    system.spawn(Routers.pool(4, worker_behavior), "workers")
    system.spawn(Routers.group(key), "proxy")
"""

from __future__ import annotations

import itertools
import random as _random
from typing import Any, Callable, List, Optional

from .behavior import Behavior
from .behaviors import Behaviors
from .receptionist import Listing, Receptionist, ServiceKey, Subscribe


_LOGICS = ("round-robin", "random")
_GROUP_BUFFER = 1024  # messages held while awaiting the first Listing


def _check_logic(logic: str) -> None:
    if logic not in _LOGICS:
        raise ValueError(f"unknown routing logic {logic!r}; one of {_LOGICS}")


class Routers:
    @staticmethod
    def pool(pool_size: int, behavior: Behavior | Callable[[], Behavior],
             logic: str = "round-robin") -> Behavior:
        """A pool router: spawns `pool_size` children running `behavior`
        and routes incoming messages over them (PoolRouter). Children are
        watched; a crashed-and-stopped child leaves the pool (the typed
        reference restarts by wrapping `behavior` in supervision — pass a
        supervised behavior for that)."""
        if pool_size <= 0:
            raise ValueError("pool_size must be > 0")
        _check_logic(logic)

        def factory():
            # Behavior instances (incl. DeferredBehavior, which defines
            # __call__(ctx)) are used as-is; only plain zero-arg factories
            # are invoked — `callable()` alone would mis-call Deferred
            return behavior if isinstance(behavior, Behavior) else behavior()

        def setup(ctx):
            routees: List[Any] = [
                ctx.spawn(factory(), f"pool-{i}") for i in range(pool_size)]
            for r in routees:
                ctx.watch(r)
            rr = itertools.count()

            def on_message(ctx_, msg):
                if not routees:
                    # every child terminated: the loss must be VISIBLE
                    from ..actor.messages import DeadLetter
                    ctx.system.dead_letters.tell(
                        DeadLetter(msg, None, ctx.self), None)
                    return Behaviors.same
                if logic == "random":
                    target = _random.choice(routees)
                else:  # round-robin
                    target = routees[next(rr) % len(routees)]
                target.tell(msg)
                return Behaviors.same

            def on_signal(ctx_, sig):
                from ..actor.messages import Terminated as _T
                if isinstance(sig, _T):
                    actor = getattr(sig, "actor", None) or \
                        getattr(sig, "ref", None)
                    if actor is not None:
                        routees[:] = [r for r in routees if r != actor]
                return Behaviors.same

            return Behaviors.receive(on_message, on_signal)

        return Behaviors.setup(setup)

    @staticmethod
    def group(key: ServiceKey, logic: str = "round-robin") -> Behavior:
        """A group router: routes over the receptionist's current Listing
        for `key` (GroupRouter). Messages arriving before the first listing
        are buffered (BOUNDED — overflow goes to dead letters, so a never-
        registered key cannot grow memory without bound)."""
        _check_logic(logic)

        def setup(ctx):
            routees: List[Any] = []
            pending: List[Any] = []
            rr = itertools.count()
            Receptionist.get(ctx.system).subscribe(key, ctx.self)

            def route(msg):
                if logic == "random":
                    _random.choice(routees).tell(msg)
                else:
                    routees[next(rr) % len(routees)].tell(msg)

            def on_message(ctx_, msg):
                if isinstance(msg, Listing):
                    # deterministic round-robin order over the frozenset
                    routees[:] = sorted(msg.service_instances,
                                        key=lambda r: str(r.path))
                    if routees and pending:
                        for m in pending:
                            route(m)
                        pending.clear()
                    return Behaviors.same
                if not routees:
                    if len(pending) < _GROUP_BUFFER:
                        pending.append(msg)
                    else:
                        from ..actor.messages import DeadLetter
                        ctx.system.dead_letters.tell(
                            DeadLetter(msg, None, ctx.self), None)
                    return Behaviors.same
                route(msg)
                return Behaviors.same

            return Behaviors.receive_message(
                lambda msg: on_message(ctx, msg))

        return Behaviors.setup(setup)
