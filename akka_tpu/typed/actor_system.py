"""Typed ActorSystem facade: the system IS an ActorRef to the guardian.

Reference parity: akka-actor-typed/src/main/scala/akka/actor/typed/ActorSystem.scala
+ internal/adapter/ActorSystemAdapter.scala — `ActorSystem(guardianBehavior, name)`
spawns the user guardian from a Behavior; tell on the system reaches the guardian.
"""

from __future__ import annotations

from typing import Any, Optional

from ..actor.system import ActorSystem as ClassicActorSystem
from ..config import Config
from .adapter import props_from_behavior
from .behavior import Behavior


class ActorSystem:
    def __init__(self, guardian_behavior: Behavior, name: str = "default",
                 config: Optional[Config | dict] = None):
        self.classic = ClassicActorSystem(name, config)
        self.guardian = self.classic.actor_of(props_from_behavior(guardian_behavior), "guardian")
        self.name = name

    @staticmethod
    def create(guardian_behavior: Behavior, name: str = "default",
               config: Optional[Config | dict] = None) -> "ActorSystem":
        return ActorSystem(guardian_behavior, name, config)

    # the system acts as the guardian's ref (reference: ActorSystem extends ActorRef)
    def tell(self, message: Any, sender=None) -> None:
        self.guardian.tell(message, sender)

    @property
    def path(self):
        return self.guardian.path

    @property
    def scheduler(self):
        return self.classic.scheduler

    @property
    def event_stream(self):
        return self.classic.event_stream

    @property
    def settings(self):
        return self.classic.settings

    @property
    def log(self):
        return self.classic.log

    def spawn(self, behavior: Behavior, name: Optional[str] = None):
        """Spawn a top-level actor next to the guardian (SpawnProtocol-ish)."""
        return self.classic.actor_of(props_from_behavior(behavior), name)

    def terminate(self) -> None:
        self.classic.terminate()

    def await_termination(self, timeout: Optional[float] = None) -> bool:
        return self.classic.await_termination(timeout)

    @property
    def when_terminated(self):
        return self.classic.when_terminated

    def __repr__(self) -> str:
        return f"typed.ActorSystem({self.name})"
