"""Serialization registry, fixed-schema wire codec, and schema-evolution
(versioned manifests + migrations) serializers. See serialization.py and
versioned.py for the reference mapping."""

from .serialization import (JsonSerializer, PickleSerializer,  # noqa: F401
                            SerializationError, Serialization, Serializer,
                            StringSerializer, TensorSerializer,
                            transport_information)
from .versioned import SchemaMigration, VersionedJsonSerializer  # noqa: F401
from . import frames  # noqa: F401  (binary gateway frame format)

__all__ = [
    "Serialization", "Serializer", "SerializationError",
    "PickleSerializer", "StringSerializer", "JsonSerializer",
    "TensorSerializer", "transport_information",
    "SchemaMigration", "VersionedJsonSerializer", "frames",
]
