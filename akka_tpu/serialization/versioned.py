"""Schema-evolution serialization: versioned manifests + migrations.

Reference parity: akka-serialization-jackson — JacksonMigration.scala:22
(`currentVersion`, `transform(fromVersion, json)`, `transformClassName`)
layered on the JsonSerializer seam: every payload is written with a
"TypeName#version" manifest; on read, a registered SchemaMigration
upgrades old-version payloads (and renamed types) BEFORE the object is
rebuilt, so journals and cluster peers written by older application
versions keep deserializing after a rolling upgrade.

Usage:

    ser = VersionedJsonSerializer()
    ser.register_type(ItemAdded)                      # dataclass: automatic
    ser.register_migration("ItemAdded", ItemAddedMigration())
    serialization.add_binding(ItemAdded, ser)

A migration for version N receives every payload written at versions
< N and must return the CURRENT shape. Renames go through
transform_class_name, exactly like the reference's transformClassName.
"""

from __future__ import annotations

import json
import threading
from dataclasses import fields, is_dataclass
from typing import Any, Callable, Dict, Optional, Tuple, Type

from .serialization import SerializationError, Serializer


class SchemaMigration:
    """(reference: JacksonMigration.scala:22)"""

    #: version this application writes NOW; payloads read at lower
    #: versions go through transform()
    current_version: int = 1

    def transform(self, from_version: int, payload: dict) -> dict:
        """Upgrade a payload written at `from_version` to the current
        shape. Called once per event (not per version step) — inspect
        from_version and apply whatever steps are needed."""
        return payload

    def transform_class_name(self, from_version: int, name: str) -> str:
        """Map a historical type name to the current one (renames)."""
        return name


class VersionedJsonSerializer(Serializer):
    """JSON with "TypeName#version" manifests and migration hooks."""

    identifier = 7
    include_manifest = True

    def __init__(self):
        self._lock = threading.Lock()
        # name -> (cls, to_dict, from_dict)
        self._types: Dict[str, Tuple[type, Callable, Callable]] = {}
        self._names: Dict[type, str] = {}
        self._migrations: Dict[str, SchemaMigration] = {}

    # -- registry -------------------------------------------------------------
    def register_type(self, cls: type, name: Optional[str] = None,
                      to_dict: Optional[Callable[[Any], dict]] = None,
                      from_dict: Optional[Callable[[dict], Any]] = None
                      ) -> "VersionedJsonSerializer":
        """Register a serializable type. Dataclasses work with no
        converters (shallow field dict; nested dataclasses need explicit
        converters). Returns self for chaining."""
        n = name or cls.__name__
        if to_dict is None:
            if not is_dataclass(cls):
                raise SerializationError(
                    f"{cls.__name__}: non-dataclass types need explicit "
                    f"to_dict/from_dict converters")
            flds = [f.name for f in fields(cls)]

            def to_dict(obj, _flds=flds):  # noqa: A001
                return {k: getattr(obj, k) for k in _flds}
        if from_dict is None:
            def from_dict(payload, _cls=cls):
                return _cls(**payload)
        with self._lock:
            self._types[n] = (cls, to_dict, from_dict)
            self._names[cls] = n
        return self

    def register_migration(self, name: str, migration: SchemaMigration
                           ) -> "VersionedJsonSerializer":
        with self._lock:
            self._migrations[name] = migration
        return self

    # -- Serializer SPI -------------------------------------------------------
    def _entry(self, obj: Any):
        name = self._names.get(type(obj))
        if name is None:
            raise SerializationError(
                f"{type(obj).__name__} is not registered with the "
                f"versioned serializer (register_type first)")
        return name

    def manifest(self, obj: Any) -> str:
        name = self._entry(obj)
        mig = self._migrations.get(name)
        version = mig.current_version if mig is not None else 1
        return f"{name}#{version}"

    def to_binary(self, obj: Any) -> bytes:
        name = self._entry(obj)
        _, to_dict, _ = self._types[name]
        try:
            return json.dumps(to_dict(obj),
                              separators=(",", ":")).encode("utf-8")
        except (TypeError, ValueError) as e:
            raise SerializationError(
                f"{name}: payload not JSON-serializable: {e}") from e

    def from_binary(self, data: bytes, manifest: str = "") -> Any:
        name, _, ver_s = manifest.partition("#")
        try:
            from_version = int(ver_s) if ver_s else 1
        except ValueError as e:
            raise SerializationError(
                f"malformed versioned manifest {manifest!r}") from e
        payload = json.loads(data.decode("utf-8"))
        # renames first (the historical name owns the migration), then the
        # payload transform — JacksonSerializer.fromBinary order
        mig = self._migrations.get(name)
        current_name = name
        if mig is not None:
            current_name = mig.transform_class_name(from_version, name)
            if current_name != name:
                mig = self._migrations.get(current_name, mig)
        entry = self._types.get(current_name)
        if entry is None:
            raise SerializationError(
                f"versioned payload of unregistered type {current_name!r} "
                f"(manifest {manifest!r})")
        cls, _, from_dict = entry
        current = mig.current_version if mig is not None else 1
        if mig is not None and from_version < current:
            payload = mig.transform(from_version, payload)
        elif from_version > current:
            raise SerializationError(
                f"{current_name}: payload version {from_version} is NEWER "
                f"than this node's {current} — cannot downgrade")
        return from_dict(payload)
