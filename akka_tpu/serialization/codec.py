"""Fixed-schema wire codec: tag-based values + allowlisted object graphs.

Reference parity: akka-remote's protobuf serializers for internal messages
(remote/serialization/, the shaded akka-protobuf runtime) and the Artery
envelope layout discipline (remote/artery/Codecs.scala): a fixed binary
layout, integer serializer ids, string manifests — and NO arbitrary code
execution on the inbound path. Java serialization exists behind
`allow-java-serialization` (off in 2.6); our pickle fallback mirrors that:
explicit opt-in only (akka.remote.allow-pickle).

Decoding here can only ever:
- build primitives/containers (None/bool/int/float/str/bytes/list/tuple/
  set/frozenset/dict), numpy arrays from raw buffers,
- resolve ActorRefs through the provider (transport_information),
- instantiate ALLOWLISTED classes via cls.__new__ + object.__setattr__ of
  decoded fields — never __init__, never __reduce__, never a callable from
  the wire. Allowlisted = anything under the framework's own namespace
  (internal control-plane messages are framework dataclasses) plus classes
  registered explicitly with register_wire_class (the user's
  serialization-bindings analogue, Serialization.scala:45).
"""

from __future__ import annotations

import enum
import importlib
import io
import struct
import threading
from typing import Any, Callable, Dict, Optional, Set, Tuple

import numpy as np

_I64 = struct.Struct(">q")
_F64 = struct.Struct(">d")
_U32 = struct.Struct(">I")

# public aliases: the wire-integer primitives other fixed-schema layouts
# build on (serialization/frames.py — the gateway's binary frame format
# shares this module's big-endian convention)
I64, F64, U32 = _I64, _F64, _U32

_TRUSTED_PREFIX = "akka_tpu."

_registry_lock = threading.Lock()
_registered: Dict[str, type] = {}        # "module:qualname" -> class
_registered_rev: Dict[type, str] = {}


class WireCodecError(Exception):
    pass


def register_wire_class(cls: type, key: Optional[str] = None) -> type:
    """Allow `cls` on the wire (usable as a decorator). Framework-internal
    classes (akka_tpu.*) are implicitly trusted; user message classes must
    be registered on BOTH ends."""
    k = key or f"{cls.__module__}:{cls.__qualname__}"
    with _registry_lock:
        _registered[k] = cls
        _registered_rev[cls] = k
    return cls


def _class_key(cls: type) -> str:
    k = _registered_rev.get(cls)
    if k is not None:
        return k
    if "<locals>" in cls.__qualname__:
        raise WireCodecError(
            f"cannot wire-encode local class {cls.__qualname__}: register it "
            "with register_wire_class or define it at module scope")
    return f"{cls.__module__}:{cls.__qualname__}"


def _resolve_class(key: str) -> type:
    with _registry_lock:
        cls = _registered.get(key)
    if cls is not None:
        return cls
    module, _, qual = key.partition(":")
    if not module.startswith(_TRUSTED_PREFIX):
        raise WireCodecError(
            f"refusing to decode unregistered class {key!r}: call "
            "register_wire_class on both ends (or enable "
            "akka.remote.allow-pickle explicitly)")
    try:
        obj: Any = importlib.import_module(module)
        for part in qual.split("."):
            obj = getattr(obj, part)
    except (ImportError, AttributeError) as e:
        raise WireCodecError(f"cannot resolve wire class {key!r}: {e}") from e
    if not isinstance(obj, type):
        raise WireCodecError(f"wire class key {key!r} is not a class")
    with _registry_lock:
        _registered[key] = obj
        _registered_rev.setdefault(obj, key)
    return obj


# ---------------------------------------------------------------- primitives
def _w_bytes(out: io.BytesIO, b: bytes) -> None:
    out.write(_U32.pack(len(b)))
    out.write(b)


def _read_exact(inp: io.BytesIO, n: int) -> bytes:
    data = inp.read(n)
    if len(data) != n:
        raise WireCodecError("truncated frame")
    return data


def _r_bytes(inp: io.BytesIO) -> bytes:
    (n,) = _U32.unpack(_read_exact(inp, 4))
    return _read_exact(inp, n)


def _w_str(out: io.BytesIO, s: str) -> None:
    _w_bytes(out, s.encode("utf-8"))


def _r_str(inp: io.BytesIO) -> str:
    return _r_bytes(inp).decode("utf-8")


def _is_cycle_kind(obj: Any) -> bool:
    """True for kinds that get a memo slot (exact list/set/dict + O-coded
    objects) — encode and decode MUST register the same kinds in the same
    order or every later backref is misaligned (silent corruption). The
    isinstance checks therefore mirror the encode dispatch exactly:
    NamedTuples (tuple subclasses, 'n'-coded) and refused builtin
    subclasses never take a slot."""
    t = type(obj)
    if t in (list, set, dict):
        return True
    if obj is None or isinstance(
            obj, (bool, int, float, str, bytes, tuple, frozenset, list, set,
                  dict, np.ndarray, np.generic, enum.Enum)):
        return False
    if t.__name__ == "ArrayImpl" or _is_actor_ref(obj):
        return False
    return True


def encode_value(obj: Any, out: io.BytesIO,
                 memo: Optional[Dict[int, int]] = None,
                 keep: Optional[list] = None) -> None:
    """One-byte tag + payload, recursive. Raises WireCodecError for types
    with no fixed-schema representation.

    Cyclic graphs are legal for the cycle-capable kinds (list/set/dict/
    object — e.g. a delta-CRDT whose _delta is itself): each one gets a
    memo index on first encode and later occurrences emit an `R` backref —
    pickle's memoization discipline. Decode registers the same kinds in
    the same order, so indices line up by construction."""
    if memo is None:
        memo = {}
        keep = []
    if _is_cycle_kind(obj):
        idx = memo.get(id(obj))
        if idx is not None:
            out.write(b"R")
            out.write(_U32.pack(idx))
            return
        memo[id(obj)] = len(memo)
        keep.append(obj)  # pin: id() must stay unique for the whole encode
    if obj is None:
        out.write(b"N")
    elif obj is True:
        out.write(b"T")
    elif obj is False:
        out.write(b"F")
    elif type(obj) is int:
        if -(1 << 63) <= obj < (1 << 63):
            out.write(b"i")
            out.write(_I64.pack(obj))
        else:  # arbitrary-precision: sign byte + big-endian magnitude
            out.write(b"I")
            out.write(b"-" if obj < 0 else b"+")
            mag = abs(obj)
            _w_bytes(out, mag.to_bytes((mag.bit_length() + 7) // 8 or 1, "big"))
    elif type(obj) is float:
        out.write(b"f")
        out.write(_F64.pack(obj))
    elif type(obj) is str:
        out.write(b"s")
        _w_str(out, obj)
    elif type(obj) is bytes:
        out.write(b"b")
        _w_bytes(out, obj)
    elif type(obj) is list:
        out.write(b"l")
        out.write(_U32.pack(len(obj)))
        for x in obj:
            encode_value(x, out, memo, keep)
    elif type(obj) is tuple:
        out.write(b"t")
        out.write(_U32.pack(len(obj)))
        for x in obj:
            encode_value(x, out, memo, keep)
    elif type(obj) is set or type(obj) is frozenset:
        out.write(b"S" if type(obj) is set else b"Z")
        out.write(_U32.pack(len(obj)))
        for x in obj:
            encode_value(x, out, memo, keep)
    elif type(obj) is dict:
        out.write(b"d")
        out.write(_U32.pack(len(obj)))
        for k, v in obj.items():
            encode_value(k, out, memo, keep)
            encode_value(v, out, memo, keep)
    elif isinstance(obj, np.ndarray) or type(obj).__name__ == "ArrayImpl":
        arr = np.asarray(obj)
        out.write(b"a")
        _w_str(out, arr.dtype.str)
        out.write(_U32.pack(arr.ndim))
        for dim in arr.shape:
            out.write(_U32.pack(dim))
        _w_bytes(out, np.ascontiguousarray(arr).tobytes())
    elif isinstance(obj, np.generic):
        encode_value(obj.item(), out, memo, keep)
    elif isinstance(obj, enum.Enum):
        out.write(b"E")
        _w_str(out, _class_key(type(obj)))
        _w_str(out, obj.name)
    elif _is_actor_ref(obj):
        out.write(b"r")
        _w_str(out, ref_wire_path(obj))
    elif isinstance(obj, type):
        # class REFERENCE (not instance): e.g. the zero_tag a map delta op
        # carries so first-sight replicas reconstruct the right wrapper.
        # Decode goes through _resolve_class, so only trusted/registered
        # classes ever resolve.
        out.write(b"C")
        _w_str(out, _class_key(obj))
    elif isinstance(obj, tuple) and hasattr(type(obj), "_fields"):
        # NamedTuple: state lives in the tuple payload, not __dict__
        cls = type(obj)
        key = _class_key(cls)
        if not key.startswith(_TRUSTED_PREFIX) and cls not in _registered_rev:
            raise WireCodecError(
                f"no fixed-schema codec for NamedTuple {key!r}: register it "
                "with register_wire_class (both ends)")
        out.write(b"n")
        _w_str(out, key)
        out.write(_U32.pack(len(obj)))
        for x in obj:
            encode_value(x, out, memo, keep)
    elif isinstance(obj, (tuple, list, dict, set, frozenset, str, bytes,
                          int, float)):
        # builtin subclass (not a NamedTuple): the builtin payload would be
        # silently lost by attribute-walking — refuse loudly
        raise WireCodecError(
            f"no fixed-schema codec for builtin subclass "
            f"{type(obj).__qualname__}: its {type(obj).__mro__[-2].__name__} "
            "payload is not capturable as attributes")
    else:
        _encode_object(obj, out, memo, keep)


def ref_wire_path(ref) -> str:
    """Full-address serialization path when a transport context is
    installed; local-scope path otherwise (local-only digesting /
    persistence — decoding across systems requires the context)."""
    from .serialization import SerializationError, serialized_ref_path
    try:
        return serialized_ref_path(ref)
    except SerializationError:
        return ref.path.to_serialization_format()


def _is_actor_ref(obj: Any) -> bool:
    from ..actor.ref import ActorRef
    return isinstance(obj, ActorRef)


def _fields_of(obj: Any) -> Dict[str, Any]:
    """Instance state = __dict__ merged with slot attributes: a class whose
    base lacks __slots__ has BOTH (an often-empty __dict__ plus slots)."""
    fields: Dict[str, Any] = dict(getattr(obj, "__dict__", ()) or {})
    for klass in type(obj).__mro__:
        slots = getattr(klass, "__slots__", ())
        if isinstance(slots, str):
            slots = (slots,)
        for slot in slots:
            if slot not in fields and slot != "__dict__" and \
                    hasattr(obj, slot):
                fields[slot] = getattr(obj, slot)
    return fields


def _encode_object(obj: Any, out: io.BytesIO, memo: Dict[int, int],
                   keep: list) -> None:
    cls = type(obj)
    key = _class_key(cls)
    if not key.startswith(_TRUSTED_PREFIX) and cls not in _registered_rev:
        raise WireCodecError(
            f"no fixed-schema codec for {key!r}: register it with "
            "register_wire_class (both ends) or enable "
            "akka.remote.allow-pickle explicitly")
    fields = _fields_of(obj)
    try:
        out.write(b"O")
        _w_str(out, key)
        out.write(_U32.pack(len(fields)))
        for name, value in fields.items():
            _w_str(out, name)
            encode_value(value, out, memo, keep)
    except WireCodecError:
        raise
    except (struct.error, TypeError) as e:
        raise WireCodecError(f"field of {key!r} not wire-encodable: {e}") from e


def decode_value(inp: io.BytesIO, memo: Optional[list] = None) -> Any:
    if memo is None:
        memo = []
    tag = inp.read(1)
    if tag == b"N":
        return None
    if tag == b"T":
        return True
    if tag == b"F":
        return False
    if tag == b"i":
        return _I64.unpack(_read_exact(inp, 8))[0]
    if tag == b"I":
        sign = _read_exact(inp, 1)
        mag = int.from_bytes(_r_bytes(inp), "big")
        return -mag if sign == b"-" else mag
    if tag == b"f":
        return _F64.unpack(_read_exact(inp, 8))[0]
    if tag == b"s":
        return _r_str(inp)
    if tag == b"b":
        return _r_bytes(inp)
    if tag == b"R":
        (idx,) = _U32.unpack(_read_exact(inp, 4))
        try:
            return memo[idx]
        except IndexError:
            raise WireCodecError(f"dangling backref {idx}") from None
    if tag == b"l":
        (n,) = _U32.unpack(_read_exact(inp, 4))
        out: list = []
        memo.append(out)  # register BEFORE children: self-references resolve
        for _ in range(n):
            out.append(decode_value(inp, memo))
        return out
    if tag == b"S":
        (n,) = _U32.unpack(_read_exact(inp, 4))
        s: set = set()
        memo.append(s)
        for _ in range(n):
            s.add(decode_value(inp, memo))
        return s
    if tag == b"d":
        (n,) = _U32.unpack(_read_exact(inp, 4))
        d: dict = {}
        memo.append(d)
        for _ in range(n):
            k = decode_value(inp, memo)
            d[k] = decode_value(inp, memo)
        return d
    if tag in (b"t", b"Z"):
        (n,) = _U32.unpack(_read_exact(inp, 4))
        items = [decode_value(inp, memo) for _ in range(n)]
        return tuple(items) if tag == b"t" else frozenset(items)
    if tag == b"a":
        dtype_s = _r_str(inp)
        (ndim,) = _U32.unpack(_read_exact(inp, 4))
        shape = tuple(_U32.unpack(_read_exact(inp, 4))[0] for _ in range(ndim))
        buf = _r_bytes(inp)
        return np.frombuffer(buf, dtype=np.dtype(dtype_s)).reshape(shape).copy()
    if tag == b"E":
        cls = _resolve_class(_r_str(inp))
        if not issubclass(cls, enum.Enum):
            raise WireCodecError(f"{cls!r} is not an Enum")
        return cls[_r_str(inp)]
    if tag == b"r":
        from .serialization import resolve_ref
        return resolve_ref(_r_str(inp))
    if tag == b"C":
        return _resolve_class(_r_str(inp))
    if tag == b"n":
        cls = _resolve_class(_r_str(inp))
        (n,) = _U32.unpack(_read_exact(inp, 4))
        if not (issubclass(cls, tuple) and hasattr(cls, "_fields")):
            raise WireCodecError(f"{cls!r} is not a NamedTuple")
        items = [decode_value(inp, memo) for _ in range(n)]
        return cls(*items)
    if tag == b"O":
        cls = _resolve_class(_r_str(inp))
        (n,) = _U32.unpack(_read_exact(inp, 4))
        obj = cls.__new__(cls)
        memo.append(obj)  # register BEFORE fields: self-references resolve
        for _ in range(n):
            name = _r_str(inp)
            object.__setattr__(obj, name, decode_value(inp, memo))
        return obj
    raise WireCodecError(f"unknown wire tag {tag!r}")


def dumps(obj: Any) -> bytes:
    out = io.BytesIO()
    encode_value(obj, out)
    return out.getvalue()


def loads(data: bytes) -> Any:
    return decode_value(io.BytesIO(data))
