"""Wire-speed binary gateway frames: fixed-schema records, batch decode.

The gateway's JSON protocol (gateway/ingress.py) pays a Python
dict-construction round per request — `json.loads`, per-key coercion,
a dict built and torn down before anything touches the staging slab.
This module is the Artery/Aeron move applied to our front door: a
versioned fixed-schema binary layout whose payload is a packed array of
identical records, so a whole window of requests decodes in ONE
`np.frombuffer` into columns (id, op, tenant, entity, value) and a whole
wave of replies encodes in one structured-array assignment. Zero
per-request Python objects on either pass.

Frame body layout (the u32-BE length prefix is the transport's — the
same `simpleFramingProtocol` framing JSON rides, so both encodings
coexist on one connection, sniffed by the first body byte):

    offset  size  field
    0       1     magic     0xAB  (never a JSON first byte: '{' = 0x7B)
    1       1     version   1
    2       1     kind      0 = request batch, 1 = reply batch
    3       1     reserved  0
    4       4     count     u32 BE, number of records
    8       n*R   records   `count` packed records (R = record size)

Request record (57 bytes, big-endian numerics — the codec.py wire
convention):

    id i64 | op u8 (0=get, 1=add) | tenant S16 | entity S24 | value f64

Reply record (53 bytes):

    id i64 | status u8 (0=ok, 1=shed, 2=error) | reason S32
    | value f64 | retry_after_ms u32

Traced reply record (version 2, 61 bytes — ISSUE 12): the same fields
plus a trailing `trace u64`, the causal trace id minted at ingress, so a
client-reported failure is greppable in the span JSONL. Version 2 is
emitted ONLY when some record in the wave actually carries a nonzero
trace id (tracing enabled AND the request sampled) — an untraced wave's
bytes are bit-identical to version 1, and version-1 decoders never see a
frame they cannot parse unless tracing was deliberately turned on.
Request frames stay version 1.

Replica reply record (version 3, 65 bytes — ISSUE 14): version 2's
fields plus a trailing `step_lag i32` — ≥ 0 marks a replica-served read
(the value is its bounded staleness in device steps on the shared
ATT_STEP axis), −1 marks the authoritative wave path. Version 3 is
emitted ONLY when some record in the wave was actually replica-served,
mirroring the version-2 discipline: a gateway without a replica cache
(or a wave with no replica hits) never changes the wire.

Dedup reply record (version 4, 66 bytes — ISSUE 20): version 3's
fields plus a trailing `dedup u1` — 1 marks a reply served from the
journaled reply cache (a duplicate request id short-circuited before
the ask wave; the value/status are the FIRST attempt's, replayed
verbatim). Version 4 is emitted ONLY when some record in the wave was
actually dedup-served, same discipline as versions 2/3: a gateway
without a dedup table never changes the wire.

String fields are NUL-padded UTF-8; a reason longer than 32 bytes is
truncated (every typed gateway reason fits). A batch of one is the solo
ask — bit-identical semantics to its JSON twin, tested in
tests/test_gateway_binary.py. Admin ops stay JSON-only (the debuggable
channel; binary frames addressed to the admin tenant get a typed error).

Decoding is bounds-checked and type-safe by construction: records are
fixed-width scalars/bytes — there is no tag dispatch, no object graph,
nothing allowlisted to resolve (contrast codec.py's general object
codec, whose `struct` primitives this layout builds on).
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence

import numpy as np

from .codec import _U32

__all__ = ["MAGIC", "VERSION", "VERSION_TRACED", "VERSION_REPLICA",
           "VERSION_DEDUP",
           "KIND_REQUEST",
           "KIND_REPLY", "OP_GET", "OP_ADD", "OP_NAMES", "OP_CODES",
           "ST_OK", "ST_SHED", "ST_ERROR",
           "REQUEST_DTYPE", "REPLY_DTYPE", "REPLY_DTYPE_TRACED",
           "REPLY_DTYPE_REPLICA", "REPLY_DTYPE_DEDUP",
           "DEFAULT_MAX_FRAME",
           "FrameFormatError", "is_binary", "frame",
           "encode_request_batch", "decode_request_batch",
           "check_request_batch", "decode_request_batches",
           "encode_reply_batch", "decode_reply_batch", "reply_to_dict",
           "decode_replies"]

MAGIC = 0xAB
VERSION = 1
VERSION_TRACED = 2  # replies only: VERSION layout + trailing trace u64
VERSION_REPLICA = 3  # replies only: VERSION_TRACED layout + step_lag i32
VERSION_DEDUP = 4  # replies only: VERSION_REPLICA layout + dedup u1
KIND_REQUEST = 0
KIND_REPLY = 1

OP_GET = 0
OP_ADD = 1
OP_NAMES = {OP_GET: "get", OP_ADD: "add"}
OP_CODES = {"get": OP_GET, "add": OP_ADD}

ST_OK = 0
ST_SHED = 1
ST_ERROR = 2
_ST_NAMES = {ST_OK: "ok", ST_SHED: "shed", ST_ERROR: "error"}

# ONE frame-size limit for both ends of the wire (ISSUE 11 satellite:
# the client's reader and the server's framing stages used to disagree —
# 1<<20 vs 1<<16 — so a server-legal reply near the boundary could kill
# the client that asked for it).
DEFAULT_MAX_FRAME = 1 << 20

_HEADER = np.dtype([("magic", "u1"), ("version", "u1"), ("kind", "u1"),
                    ("reserved", "u1"), ("count", ">u4")])

TENANT_BYTES = 16
ENTITY_BYTES = 24
REASON_BYTES = 32

REQUEST_DTYPE = np.dtype([("id", ">i8"), ("op", "u1"),
                          ("tenant", f"S{TENANT_BYTES}"),
                          ("entity", f"S{ENTITY_BYTES}"),
                          ("value", ">f8")])

REPLY_DTYPE = np.dtype([("id", ">i8"), ("status", "u1"),
                        ("reason", f"S{REASON_BYTES}"),
                        ("value", ">f8"), ("retry_after_ms", ">u4")])

# version-2 reply record: version 1 + the causal trace id (ISSUE 12)
REPLY_DTYPE_TRACED = np.dtype(REPLY_DTYPE.descr + [("trace", ">u8")])

# version-3 reply record: version 2 + the replica step-lag marker
# (ISSUE 14): step_lag >= 0 <=> served from the read replica, that many
# device steps behind the authoritative state; -1 <=> wave path
REPLY_DTYPE_REPLICA = np.dtype(REPLY_DTYPE_TRACED.descr
                               + [("step_lag", ">i4")])

# version-4 reply record: version 3 + the reply-cache dedup marker
# (ISSUE 20): dedup == 1 <=> this reply was replayed from the journaled
# reply cache (the request id was a duplicate; the effect applied once)
REPLY_DTYPE_DEDUP = np.dtype(REPLY_DTYPE_REPLICA.descr
                             + [("dedup", "u1")])


class FrameFormatError(ValueError):
    """Malformed binary frame. `code` is the short typed-reason slug the
    gateway surfaces as `bad_frame:<code>` — mirrors the JSON path's
    `bad_request:<ExcName>` discipline."""

    def __init__(self, code: str, detail: str = ""):
        super().__init__(f"{code}: {detail}" if detail else code)
        self.code = code


def is_binary(body: bytes) -> bool:
    """Frame sniffing: binary bodies start with MAGIC, JSON bodies with
    '{' (or whitespace) — the two encodings share a connection."""
    return len(body) >= 1 and body[0] == MAGIC


def frame(body: bytes) -> bytes:
    """Length-prefix a frame body (the shared server/client/binary
    encode helper — `simpleFramingProtocol`'s u32-BE convention)."""
    return _U32.pack(len(body)) + body


def _header(kind: int, count: int, version: int = VERSION) -> bytes:
    h = np.zeros((), _HEADER)
    h["magic"] = MAGIC
    h["version"] = version
    h["kind"] = kind
    h["count"] = count
    return h.tobytes()


def _encode_str_col(out: np.ndarray, field: str, values: Sequence[Any],
                    width: int, what: str) -> None:
    enc = [v if isinstance(v, bytes) else str(v).encode("utf-8")
           for v in values]
    for i, b in enumerate(enc):
        if len(b) > width:
            raise FrameFormatError(
                f"{what}_too_long", f"{b!r} exceeds {width} bytes")
    out[field] = enc


# ------------------------------------------------------------------ requests
def encode_request_batch(ids: Sequence[int], tenants: Sequence[Any],
                         entities: Sequence[Any], ops: Sequence[Any],
                         values: Sequence[float]) -> bytes:
    """Pack a request window into one binary frame body. `ops` accepts
    op names ("add"/"get") or raw codes; columns are assigned
    vectorized — no per-request dict ever exists."""
    n = len(ids)
    rec = np.zeros((n,), REQUEST_DTYPE)
    rec["id"] = np.asarray(ids, np.int64)
    rec["op"] = [OP_CODES[o] if isinstance(o, str) else int(o) for o in ops]
    _encode_str_col(rec, "tenant", tenants, TENANT_BYTES, "tenant")
    _encode_str_col(rec, "entity", entities, ENTITY_BYTES, "entity")
    rec["value"] = np.asarray(values, np.float64)
    return _header(KIND_REQUEST, n) + rec.tobytes()


def _check_records(body: bytes, kind: int, dtype: np.dtype,
                   max_frame: int, version: int = VERSION) -> int:
    """Validate one frame body's header/length; returns the record
    count. Split from the decode so a cross-connection window can
    validate EVERY body first and then reinterpret all record bytes in
    one pass (decode_request_batches)."""
    if len(body) > max_frame:
        raise FrameFormatError("oversize",
                               f"{len(body)} bytes exceeds {max_frame}")
    if len(body) < _HEADER.itemsize:
        raise FrameFormatError("truncated_header",
                               f"{len(body)} bytes < {_HEADER.itemsize}")
    h = np.frombuffer(body[:_HEADER.itemsize], _HEADER)[0]
    if int(h["magic"]) != MAGIC:
        raise FrameFormatError("bad_magic", hex(int(h["magic"])))
    if int(h["version"]) != version:
        raise FrameFormatError("unsupported_version", str(int(h["version"])))
    if int(h["kind"]) != kind:
        raise FrameFormatError("wrong_kind",
                               f"got {int(h['kind'])}, expected {kind}")
    n = int(h["count"])
    expect = _HEADER.itemsize + n * dtype.itemsize
    if len(body) != expect:
        raise FrameFormatError(
            "bad_length", f"{n} records need {expect} bytes, got {len(body)}")
    if n == 0:
        raise FrameFormatError("empty_batch")
    return n


def _decode_records(body: bytes, kind: int, dtype: np.dtype,
                    max_frame: int, version: int = VERSION) -> np.ndarray:
    n = _check_records(body, kind, dtype, max_frame, version)
    # THE batch decode: one zero-copy reinterpret of the whole window
    return np.frombuffer(body, dtype, count=n, offset=_HEADER.itemsize)


def decode_request_batch(body: bytes,
                         max_frame: int = DEFAULT_MAX_FRAME) -> np.ndarray:
    """Decode a request window into its column view (a structured array:
    rec["op"], rec["entity"], rec["value"], ... are numpy columns).
    Raises FrameFormatError with a typed code for malformed frames."""
    return _decode_records(body, KIND_REQUEST, REQUEST_DTYPE, max_frame)


def check_request_batch(body: bytes,
                        max_frame: int = DEFAULT_MAX_FRAME) -> int:
    """Validate a request body without decoding; returns its record
    count (the aggregator's window-close unit). Raises FrameFormatError
    with the same typed codes as decode_request_batch."""
    return _check_records(body, KIND_REQUEST, REQUEST_DTYPE, max_frame)


def decode_request_batches(bodies: Sequence[bytes],
                           max_frame: int = DEFAULT_MAX_FRAME):
    """Merged window decode (ISSUE 13): many frame bodies — from many
    connections — validated individually, then ALL their record bytes
    reinterpreted in ONE `np.frombuffer`. Returns `(rec, counts)` where
    `counts[i]` is body i's record count (the demux offsets). A single
    body keeps the zero-copy solo path; callers wanting per-body typed
    errors should pre-filter with check_request_batch."""
    counts = [_check_records(b, KIND_REQUEST, REQUEST_DTYPE, max_frame)
              for b in bodies]
    if len(bodies) == 1:
        return (np.frombuffer(bodies[0], REQUEST_DTYPE, count=counts[0],
                              offset=_HEADER.itemsize), counts)
    payload = b"".join(bytes(memoryview(b)[_HEADER.itemsize:])
                       for b in bodies)
    return np.frombuffer(payload, REQUEST_DTYPE), counts


# ------------------------------------------------------------------- replies
def encode_reply_batch(ids: np.ndarray, statuses: np.ndarray,
                       reasons: np.ndarray, values: np.ndarray,
                       retry_after_ms: np.ndarray,
                       traces: Any = None,
                       step_lags: Any = None,
                       dedups: Any = None) -> bytes:
    """Encode a whole reply wave in one vectorized pass (columns in,
    bytes out — the readback twin of decode_request_batch).

    `traces` (ISSUE 12): optional aligned u64 trace-id column. When any
    id is nonzero the wave is encoded as version 2 (trailing trace
    field); otherwise the output is bit-identical to the pre-tracing
    version-1 bytes — an untraced server never changes the wire.

    `step_lags` (ISSUE 14): optional aligned i32 replica-marker column
    (−1 = authoritative, ≥ 0 = replica-served at that step lag). When
    any row was replica-served the wave is version 3 (trace column
    included, zeros if untraced); otherwise the column is dropped and
    the version-2/1 rules above apply unchanged.

    `dedups` (ISSUE 20): optional aligned u1 dedup-marker column (1 =
    served from the reply cache). When any row was dedup-served the
    wave is version 4 (trace/step_lag columns included, zeros/−1 when
    inert); otherwise the column is dropped and the version-3/2/1 rules
    above apply unchanged."""
    n = len(ids)
    traced = traces is not None and bool(np.any(np.asarray(traces)))
    replica = step_lags is not None and \
        bool(np.any(np.asarray(step_lags) >= 0))
    deduped = dedups is not None and bool(np.any(np.asarray(dedups)))
    if deduped:
        rec = np.zeros((n,), REPLY_DTYPE_DEDUP)
    elif replica:
        rec = np.zeros((n,), REPLY_DTYPE_REPLICA)
    else:
        rec = np.zeros((n,), REPLY_DTYPE_TRACED if traced else REPLY_DTYPE)
    rec["id"] = ids
    rec["status"] = statuses
    rec["reason"] = reasons
    rec["value"] = values
    rec["retry_after_ms"] = retry_after_ms
    if deduped:
        if traced:
            rec["trace"] = np.asarray(traces, np.uint64)
        rec["step_lag"] = (np.asarray(step_lags, np.int32)
                           if step_lags is not None else -1)
        rec["dedup"] = np.asarray(dedups, np.uint8)
        return _header(KIND_REPLY, n, VERSION_DEDUP) + rec.tobytes()
    if replica:
        if traced:
            rec["trace"] = np.asarray(traces, np.uint64)
        rec["step_lag"] = np.asarray(step_lags, np.int32)
        return _header(KIND_REPLY, n, VERSION_REPLICA) + rec.tobytes()
    if traced:
        rec["trace"] = np.asarray(traces, np.uint64)
        return _header(KIND_REPLY, n, VERSION_TRACED) + rec.tobytes()
    return _header(KIND_REPLY, n) + rec.tobytes()


def decode_reply_batch(body: bytes,
                       max_frame: int = DEFAULT_MAX_FRAME) -> np.ndarray:
    """Decode a reply wave to its record columns (client half). Accepts
    both reply versions: 1 (53B records) and 2 (61B traced records) —
    the record array's dtype tells the caller which it got."""
    if len(body) >= 2 and body[1] == VERSION_DEDUP:
        return _decode_records(body, KIND_REPLY, REPLY_DTYPE_DEDUP,
                               max_frame, VERSION_DEDUP)
    if len(body) >= 2 and body[1] == VERSION_REPLICA:
        return _decode_records(body, KIND_REPLY, REPLY_DTYPE_REPLICA,
                               max_frame, VERSION_REPLICA)
    if len(body) >= 2 and body[1] == VERSION_TRACED:
        return _decode_records(body, KIND_REPLY, REPLY_DTYPE_TRACED,
                               max_frame, VERSION_TRACED)
    return _decode_records(body, KIND_REPLY, REPLY_DTYPE, max_frame)


def reply_to_dict(rec) -> Dict[str, Any]:
    """One reply record -> the exact dict the JSON protocol would have
    produced (key set depends on status — the equivalence contract the
    property test pins). A version-2 record's nonzero trace id maps to
    the "trace" key, exactly as the JSON path mirrors it."""
    status = _ST_NAMES.get(int(rec["status"]), "error")
    out: Dict[str, Any] = {"id": int(rec["id"]), "status": status}
    if status == "ok":
        out["value"] = float(rec["value"])
    elif status == "shed":
        out["reason"] = bytes(rec["reason"]).decode("utf-8", "replace")
        out["retry_after_ms"] = int(rec["retry_after_ms"])
    else:
        out["reason"] = bytes(rec["reason"]).decode("utf-8", "replace")
    if "trace" in (rec.dtype.names or ()) and int(rec["trace"]):
        out["trace"] = int(rec["trace"])
    if "step_lag" in (rec.dtype.names or ()) and int(rec["step_lag"]) >= 0:
        out["replica"] = True
        out["step_lag"] = int(rec["step_lag"])
    if "dedup" in (rec.dtype.names or ()) and int(rec["dedup"]):
        out["dedup"] = True
    return out


def decode_replies(body: bytes,
                   max_frame: int = DEFAULT_MAX_FRAME) -> List[Dict[str, Any]]:
    """Client convenience: reply wave -> list of JSON-twin dicts."""
    return [reply_to_dict(r) for r in decode_reply_batch(body, max_frame)]
