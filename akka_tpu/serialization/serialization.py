"""Serialization: id->serializer registry with class bindings + manifests.

Reference parity: akka-actor/src/main/scala/akka/serialization/ —
`Serialization.findSerializerFor` walks class->serializer bindings (most
specific class wins, Serialization.scala:291), serializers carry integer ids
and optional string manifests (Serializer.scala SerializerWithStringManifest),
bindings come from config `serialization-bindings` (Serialization.scala:45)
plus runtime registration.

TPU note: message payloads that are jax/numpy arrays use the tensor serializer
(raw little-endian buffers + dtype/shape manifest) so remote tells of tensor
blocks don't round-trip through pickle.
"""

from __future__ import annotations

import io
import json
import pickle
import struct
import threading
from dataclasses import is_dataclass, asdict
from typing import Any, Dict, Optional, Tuple, Type

import numpy as np


class Serializer:
    identifier: int = 0
    include_manifest: bool = False

    def manifest(self, obj: Any) -> str:
        return ""

    def to_binary(self, obj: Any) -> bytes:
        raise NotImplementedError

    def from_binary(self, data: bytes, manifest: str = "") -> Any:
        raise NotImplementedError


class PickleSerializer(Serializer):
    """The reference's JavaSerializer analogue — and like it, OFF on the
    wire unless explicitly enabled (akka.remote.allow-pickle; reference:
    allow-java-serialization, off since 2.6). `enabled` is enforced on BOTH
    directions so a peer can't feed us pickles just because it built some."""

    identifier = 1

    def __init__(self, enabled: bool = True):
        self.enabled = enabled

    def to_binary(self, obj: Any) -> bytes:
        if not self.enabled:
            raise SerializationError(
                f"pickle serialization of {type(obj).__name__} is disabled "
                "(set akka.remote.allow-pickle = true to opt in, or register "
                "the class with register_wire_class)")
        return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)

    def from_binary(self, data: bytes, manifest: str = "") -> Any:
        if not self.enabled:
            raise SerializationError(
                "inbound pickle payload refused (akka.remote.allow-pickle "
                "is off)")
        return pickle.loads(data)


class StringSerializer(Serializer):
    identifier = 2

    def to_binary(self, obj: str) -> bytes:
        return obj.encode("utf-8")

    def from_binary(self, data: bytes, manifest: str = "") -> str:
        return data.decode("utf-8")


class BytesSerializer(Serializer):
    identifier = 3

    def to_binary(self, obj: bytes) -> bytes:
        return bytes(obj)

    def from_binary(self, data: bytes, manifest: str = "") -> bytes:
        return data


class JsonSerializer(Serializer):
    """Dict/list/primitive JSON (the reference's akka-serialization-jackson
    analogue for simple protocols)."""

    identifier = 4

    def to_binary(self, obj: Any) -> bytes:
        if is_dataclass(obj) and not isinstance(obj, type):
            obj = asdict(obj)
        return json.dumps(obj, separators=(",", ":")).encode("utf-8")

    def from_binary(self, data: bytes, manifest: str = "") -> Any:
        return json.loads(data.decode("utf-8"))


class TensorSerializer(Serializer):
    """numpy / jax arrays as raw buffers; manifest = dtype|shape."""

    identifier = 5
    include_manifest = True

    def manifest(self, obj: Any) -> str:
        arr = np.asarray(obj)
        return f"{arr.dtype.str}|{','.join(map(str, arr.shape))}"

    def to_binary(self, obj: Any) -> bytes:
        return np.ascontiguousarray(np.asarray(obj)).tobytes()

    def from_binary(self, data: bytes, manifest: str = "") -> np.ndarray:
        dtype_s, _, shape_s = manifest.partition("|")
        shape = tuple(int(x) for x in shape_s.split(",") if x)
        return np.frombuffer(data, dtype=np.dtype(dtype_s)).reshape(shape).copy()


class SerializationError(Exception):
    pass


class FieldSchemaSerializer(Serializer):
    """Fixed-schema object graphs (codec.py): tag-coded primitives and
    containers, raw tensor buffers, ActorRefs as resolved path strings, and
    allowlisted classes rebuilt via __new__ + setattr — no code execution
    on decode (the protobuf-internal-serializer analogue,
    remote/serialization/ + artery Codecs.scala layout discipline)."""

    identifier = 6

    def to_binary(self, obj: Any) -> bytes:
        from .codec import WireCodecError, dumps
        try:
            return dumps(obj)
        except WireCodecError as e:
            raise SerializationError(str(e)) from e

    def from_binary(self, data: bytes, manifest: str = "") -> Any:
        from .codec import WireCodecError, loads
        try:
            return loads(data)
        except WireCodecError as e:
            raise SerializationError(str(e)) from e
        except (struct.error, ValueError, TypeError, KeyError, EOFError,
                AttributeError) as e:
            # malformed frames must surface as serialization failures, not
            # leak implementation errors to the inbound path
            raise SerializationError(f"malformed wire frame: {e!r}") from e


# -- ActorRef transparency over the wire -------------------------------------
# (reference: Serialization.currentTransportInformation thread-local,
# Serialization.scala:93-136 — refs serialize as full-address path strings and
# resolve against the current system's provider on the receiving side)

_transport_info = threading.local()


class transport_information:
    """Context manager installing the provider used to (de)serialize ActorRefs
    embedded in message payloads."""

    def __init__(self, provider):
        self.provider = provider

    def __enter__(self):
        self._prev = getattr(_transport_info, "provider", None)
        _transport_info.provider = self.provider
        return self

    def __exit__(self, *exc):
        _transport_info.provider = self._prev


def serialized_ref_path(ref) -> str:
    """Full-address serialization path for a ref (local addresses get the
    provider's canonical host:port)."""
    provider = getattr(_transport_info, "provider", None)
    path = ref.path
    if provider is None:
        raise SerializationError(
            f"cannot serialize ActorRef {path}: no transport information set "
            "(refs only cross the wire inside remote-enabled systems)")
    local = getattr(provider, "local_address", None)
    if local is not None and path.address.has_local_scope:
        path = path.with_address(local)
    return path.to_serialization_format()


def resolve_ref(path: str):
    provider = getattr(_transport_info, "provider", None)
    if provider is None:
        raise SerializationError(
            f"cannot deserialize ActorRef {path}: no transport information set")
    return provider.resolve_actor_ref(path)


class Serialization:
    """Per-system registry (reference: Serialization.scala:138)."""

    def __init__(self, system=None, allow_pickle: bool = True):
        """allow_pickle=False is the wire posture (remote provider default):
        the object fallback becomes the fixed-schema codec, and pickle
        payloads are refused in both directions."""
        self.system = system
        self.allow_pickle = allow_pickle
        self._by_id: Dict[int, Serializer] = {}
        self._bindings: list[Tuple[type, Serializer]] = []
        self._cache: Dict[type, Serializer] = {}
        self._lock = threading.Lock()
        for s in (PickleSerializer(enabled=allow_pickle), StringSerializer(),
                  BytesSerializer(), JsonSerializer(), TensorSerializer(),
                  FieldSchemaSerializer()):
            self.register_serializer(s)
        self.add_binding(str, self._by_id[2])
        self.add_binding(bytes, self._by_id[3])
        self.add_binding(np.ndarray, self._by_id[5])
        try:  # jax.Array is not an np.ndarray; bind it to the tensor path too
            import jax
            self.add_binding(jax.Array, self._by_id[5])
        except Exception:  # noqa: BLE001 — jax optional for the host runtime
            pass
        # fallback: pickle when explicitly allowed, fixed-schema otherwise
        self.add_binding(object, self._by_id[1 if allow_pickle else 6])

    def register_serializer(self, serializer: Serializer) -> None:
        with self._lock:
            existing = self._by_id.get(serializer.identifier)
            if existing is not None and type(existing) is not type(serializer):
                raise SerializationError(
                    f"serializer id {serializer.identifier} already bound to "
                    f"{type(existing).__name__}")
            self._by_id[serializer.identifier] = serializer

    def add_binding(self, cls: type, serializer: Serializer) -> None:
        self.register_serializer(serializer)
        with self._lock:
            self._bindings.append((cls, serializer))
            # most specific class first (reference: Serialization.bindings sort)
            self._bindings.sort(key=lambda kv: -_depth(kv[0]))
            self._cache.clear()

    def find_serializer_for(self, obj: Any) -> Serializer:
        cls = type(obj)
        s = self._cache.get(cls)
        if s is not None:
            return s
        with self._lock:
            for bound_cls, ser in self._bindings:
                if isinstance(obj, bound_cls):
                    self._cache[cls] = ser
                    return ser
        raise SerializationError(f"no serializer for {cls.__name__}")

    def serializer_by_id(self, id_: int) -> Serializer:
        s = self._by_id.get(id_)
        if s is None:
            raise SerializationError(f"unknown serializer id {id_}")
        return s

    # -- round trips ---------------------------------------------------------
    def serialize(self, obj: Any) -> Tuple[int, str, bytes]:
        s = self.find_serializer_for(obj)
        return s.identifier, s.manifest(obj), s.to_binary(obj)

    def deserialize(self, serializer_id: int, manifest: str, data: bytes) -> Any:
        return self.serializer_by_id(serializer_id).from_binary(data, manifest)

    def verify_round_trip(self, obj: Any) -> Any:
        """The serialize-messages guard rail (reference:
        actor/dungeon/Dispatch.scala:162-204)."""
        sid, manifest, data = self.serialize(obj)
        return self.deserialize(sid, manifest, data)


def _depth(cls: type) -> int:
    return len(cls.__mro__)
