"""akka-tpu: a TPU-native actor framework with the capabilities of Akka 2.6.

Not a port: the hot path (tell → receive) runs as batched, jitted JAX steps on
TPU — actors are rows in SoA state tensors, message delivery is a segment-sum
scatter over recipient ids, behaviors are vmapped update functions — while a
host-side control plane keeps Akka's semantics for spawn/stop/supervision/
cluster membership. See SURVEY.md for the reference map.

Public surface (mirrors the reference's module split):
- akka_tpu.actor      — ActorSystem, ActorRef, Props, classic actors
- akka_tpu.typed      — Behavior/Behaviors typed API
- akka_tpu.dispatch   — dispatchers incl. the flagship `tpu-batched`
- akka_tpu.batched    — the SoA device runtime (BatchedSystem)
- akka_tpu.routing / pattern / event / serialization
- akka_tpu.remote / cluster / sharding / ddata / persistence / stream
- akka_tpu.testkit    — TestProbe, BehaviorTestKit, multi-node harness
"""

__version__ = "0.1.0"

from .config import Config, reference_config  # noqa: F401
from .actor.system import ActorSystem, ExtensionId, CoordinatedShutdown  # noqa: F401
from .actor.actor import Actor, Stash, FunctionActor  # noqa: F401
from .actor.props import Props  # noqa: F401
from .actor.deploy import Deploy, LocalScope, RemoteScope  # noqa: F401
from .actor.ref import ActorRef, Nobody  # noqa: F401
from .actor.path import ActorPath, Address  # noqa: F401
from .actor.messages import (  # noqa: F401
    PoisonPill, Kill, ReceiveTimeout, Terminated, Identify, ActorIdentity,
    DeadLetter, Status, UnhandledMessage)
from .actor.supervision import (  # noqa: F401
    OneForOneStrategy, AllForOneStrategy, Resume, Restart, Stop, Escalate,
    default_strategy, stopping_strategy)
from .pattern.ask import ask, ask_sync, pipe, AskTimeoutException  # noqa: F401
