"""Python wrappers over the native substrate: MPSC mailbox queue, wheel
timer, and the message stager.

Reference parity notes are in src/akka_native.cpp. The token registry trick:
the C queue carries uint64 tokens; the Python side keeps token -> object in
a dict (dict mutation is atomic under the GIL), so arbitrary messages ride
the lock-free queue without the C side touching refcounts.
"""

from __future__ import annotations

import ctypes
import itertools
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from . import lib as _libmod


class NativeMpscQueue:
    """Lock-free MPSC queue of Python objects (AbstractNodeQueue parity)."""

    def __init__(self):
        self._lib = _libmod.get()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        self._h = self._lib.aq_mpsc_create()
        self._closed = False            # consumer side shut (full close)
        self._closed_producers = False  # producer side shut (phase 1)
        self._tokens = itertools.count(1)
        self._registry: Dict[int, Any] = {}
        self._out = (ctypes.c_uint64 * 1)()

    def enqueue(self, obj: Any) -> bool:
        """Returns False when the queue is closed (actor stopped) and the
        message was NOT accepted — the caller routes it to dead letters
        (becomeClosed parity: late sends are redirected, never lost)."""
        if self._closed_producers:
            return False
        tok = next(self._tokens)
        self._registry[tok] = obj
        # safe vs concurrent close(): close only sets the closed flag (no
        # free, no drain — a drain would be a second consumer); memory is
        # freed in __del__, which cannot run while this frame holds a ref
        self._lib.aq_mpsc_enqueue(self._h, tok)
        if self._closed_producers:
            # close raced us. If our token is still registered, pull it back
            # and report rejection (caller dead-letters it). If it is gone,
            # either the consumer delivered it or the close-time registry
            # sweep (drain_registry) dead-lettered it — accepted either way.
            return self._registry.pop(tok, None) is None
        return True

    def dequeue(self) -> Optional[Any]:
        if self._closed:
            return None
        if self._lib.aq_mpsc_dequeue(self._h, self._out):
            obj = self._registry.pop(int(self._out[0]), None)
            if obj is not None:
                return obj
        return None

    def __len__(self) -> int:
        if self._closed:
            return 0
        return int(self._lib.aq_mpsc_count(self._h))

    def close_producers(self) -> None:
        """Phase 1 of shutdown: reject new enqueues; the consumer can still
        drain. Nothing is freed (producers may be mid-enqueue — ADVICE r1)."""
        if not self._closed_producers:
            self._closed_producers = True
            self._lib.aq_mpsc_close(self._h)

    def drain_registry(self) -> list:
        """Swap out the token registry and return the orphaned messages —
        tokens enqueued by racing producers that the consumer never drained.
        Call after close_producers + a full dequeue drain; the caller routes
        these to dead letters (exactly-once: a producer whose token survives
        here sees pop miss and reports 'accepted')."""
        old, self._registry = self._registry, {}
        return list(old.values())

    def close(self) -> None:
        """Full close: producers rejected, consumer reads nothing further.
        No free, no drain, and no registry clear here (clearing would race a
        producer's post-enqueue pop-back check into reporting 'accepted' for
        a message nobody swept); in-flight racers pop their own tokens, and
        whatever remains is reclaimed with the object in __del__."""
        self.close_producers()
        self._closed = True

    def __del__(self):  # true reclamation: no refs => no in-flight producers
        try:
            if self._h:
                self._lib.aq_mpsc_destroy(self._h)
                self._h = None
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


class NativeWheelTimer:
    """Hashed-wheel timer driven by a native tick thread; callbacks run on a
    single Python poller thread (LightArrayRevolverScheduler parity)."""

    def __init__(self, tick_duration: float = 0.001, wheel_size: int = 512):
        self._lib = _libmod.get()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        self._h = self._lib.aq_timer_create(int(tick_duration * 1e9),
                                            wheel_size)
        self._ids = itertools.count(1)
        self._callbacks: Dict[int, Tuple[Callable[[], None], bool]] = {}
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        self._poller = threading.Thread(target=self._run,
                                        name="akka-tpu-native-timer",
                                        daemon=True)
        self._poller.start()

    def schedule_once(self, delay: float, fn: Callable[[], None]) -> int:
        tid = next(self._ids)
        with self._lock:
            self._callbacks[tid] = (fn, False)
        self._lib.aq_timer_schedule(self._h, tid, int(max(delay, 0) * 1e9), 0)
        return tid

    def schedule_periodically(self, initial: float, interval: float,
                              fn: Callable[[], None]) -> int:
        tid = next(self._ids)
        with self._lock:
            self._callbacks[tid] = (fn, True)
        self._lib.aq_timer_schedule(self._h, tid, int(max(initial, 0) * 1e9),
                                    int(max(interval, 1e-4) * 1e9))
        return tid

    def cancel(self, tid: int) -> None:
        with self._lock:
            self._callbacks.pop(tid, None)
        self._lib.aq_timer_cancel(self._h, tid)

    def _run(self) -> None:
        buf = (ctypes.c_uint64 * 256)()
        while not self._stopped.is_set():
            n = self._lib.aq_timer_poll(self._h, buf, 256, 200)
            for i in range(n):
                with self._lock:
                    entry = self._callbacks.get(int(buf[i]))
                    if entry is not None and not entry[1]:
                        del self._callbacks[int(buf[i])]
                if entry is not None:
                    try:
                        entry[0]()
                    except Exception:  # noqa: BLE001 — timer cbs must not die
                        pass

    def shutdown(self) -> None:
        self._stopped.set()
        self._poller.join(timeout=2.0)
        if self._poller.is_alive():
            # a callback is blocking the poller: leak the native handle
            # instead of freeing memory it will touch (no use-after-free)
            return
        self._lib.aq_timer_destroy(self._h)


class NativeStager:
    """Preallocated staging buffer for batched-runtime tells: producers on
    any thread memcpy fixed-width rows in, the step loop drains one
    contiguous block (EnvelopeBufferPool parity)."""

    def __init__(self, capacity: int, payload_width: int, dtype=np.float32):
        self._lib = _libmod.get()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        self.capacity = capacity
        self.payload_width = payload_width
        self.dtype = np.dtype(dtype)
        self.row_bytes = payload_width * self.dtype.itemsize
        self._h = self._lib.aq_stager_create(capacity, self.row_bytes)
        # reusable drain buffers (zero allocation per drain)
        self._dst_out = np.empty(capacity, np.int32)
        self._payload_out = np.empty((capacity, payload_width), self.dtype)

    def stage(self, dsts: np.ndarray, payloads: np.ndarray) -> int:
        dsts = np.ascontiguousarray(dsts, np.int32)
        payloads = np.ascontiguousarray(payloads, self.dtype)
        k = dsts.shape[0]
        return int(self._lib.aq_stager_stage(
            self._h, k,
            dsts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            payloads.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))))

    def __len__(self) -> int:
        return int(self._lib.aq_stager_count(self._h))

    @property
    def dropped(self) -> int:
        return int(self._lib.aq_stager_dropped(self._h))

    def drain(self) -> Tuple[np.ndarray, np.ndarray]:
        n = int(self._lib.aq_stager_drain(
            self._h,
            self._dst_out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            self._payload_out.ctypes.data_as(
                ctypes.POINTER(ctypes.c_uint8))))
        return self._dst_out[:n], self._payload_out[:n]

    def close(self) -> None:
        if self._h:
            self._lib.aq_stager_destroy(self._h)
            self._h = None
