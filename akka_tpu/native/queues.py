"""Python wrappers over the native substrate: MPSC mailbox queue, wheel
timer, and the message stager.

Reference parity notes are in src/akka_native.cpp. The token registry trick:
the C queue carries uint64 tokens; the Python side keeps token -> object in
a dict (dict mutation is atomic under the GIL), so arbitrary messages ride
the lock-free queue without the C side touching refcounts.
"""

from __future__ import annotations

import ctypes
import itertools
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from . import lib as _libmod


class NativeMpscQueue:
    """Lock-free MPSC queue of Python objects (AbstractNodeQueue parity)."""

    def __init__(self):
        self._lib = _libmod.get()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        self._h = self._lib.aq_mpsc_create()
        self._closed = False
        self._tokens = itertools.count(1)
        self._registry: Dict[int, Any] = {}
        self._out = (ctypes.c_uint64 * 1)()

    def enqueue(self, obj: Any) -> None:
        if self._closed:
            return  # closed (actor stopped): drop, mirrors dead-letter path
        tok = next(self._tokens)
        self._registry[tok] = obj
        # safe vs concurrent close(): close only sets the closed flag (no
        # free, no drain — a drain would be a second consumer); memory is
        # freed in __del__, which cannot run while this frame holds a ref
        self._lib.aq_mpsc_enqueue(self._h, tok)
        if self._closed:
            self._registry.pop(tok, None)

    def dequeue(self) -> Optional[Any]:
        if self._closed:
            return None
        if self._lib.aq_mpsc_dequeue(self._h, self._out):
            obj = self._registry.pop(int(self._out[0]), None)
            if obj is not None:
                return obj
        return None

    def __len__(self) -> int:
        if self._closed:
            return 0
        return int(self._lib.aq_mpsc_count(self._h))

    def close(self) -> None:
        """Mark closed; late tells become safe no-ops. Nothing is freed or
        drained here: a drain would race the consumer's in-flight dequeue
        (two consumers on a single-consumer queue), and freeing would race
        producers mid-enqueue (ADVICE r1). Reclamation happens in __del__
        when no reference — hence no in-flight caller — remains."""
        if not self._closed:
            self._closed = True
            self._lib.aq_mpsc_close(self._h)
            self._registry.clear()

    def __del__(self):  # true reclamation: no refs => no in-flight producers
        try:
            if self._h:
                self._lib.aq_mpsc_destroy(self._h)
                self._h = None
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


class NativeWheelTimer:
    """Hashed-wheel timer driven by a native tick thread; callbacks run on a
    single Python poller thread (LightArrayRevolverScheduler parity)."""

    def __init__(self, tick_duration: float = 0.001, wheel_size: int = 512):
        self._lib = _libmod.get()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        self._h = self._lib.aq_timer_create(int(tick_duration * 1e9),
                                            wheel_size)
        self._ids = itertools.count(1)
        self._callbacks: Dict[int, Tuple[Callable[[], None], bool]] = {}
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        self._poller = threading.Thread(target=self._run,
                                        name="akka-tpu-native-timer",
                                        daemon=True)
        self._poller.start()

    def schedule_once(self, delay: float, fn: Callable[[], None]) -> int:
        tid = next(self._ids)
        with self._lock:
            self._callbacks[tid] = (fn, False)
        self._lib.aq_timer_schedule(self._h, tid, int(max(delay, 0) * 1e9), 0)
        return tid

    def schedule_periodically(self, initial: float, interval: float,
                              fn: Callable[[], None]) -> int:
        tid = next(self._ids)
        with self._lock:
            self._callbacks[tid] = (fn, True)
        self._lib.aq_timer_schedule(self._h, tid, int(max(initial, 0) * 1e9),
                                    int(max(interval, 1e-4) * 1e9))
        return tid

    def cancel(self, tid: int) -> None:
        with self._lock:
            self._callbacks.pop(tid, None)
        self._lib.aq_timer_cancel(self._h, tid)

    def _run(self) -> None:
        buf = (ctypes.c_uint64 * 256)()
        while not self._stopped.is_set():
            n = self._lib.aq_timer_poll(self._h, buf, 256, 200)
            for i in range(n):
                with self._lock:
                    entry = self._callbacks.get(int(buf[i]))
                    if entry is not None and not entry[1]:
                        del self._callbacks[int(buf[i])]
                if entry is not None:
                    try:
                        entry[0]()
                    except Exception:  # noqa: BLE001 — timer cbs must not die
                        pass

    def shutdown(self) -> None:
        self._stopped.set()
        self._poller.join(timeout=2.0)
        if self._poller.is_alive():
            # a callback is blocking the poller: leak the native handle
            # instead of freeing memory it will touch (no use-after-free)
            return
        self._lib.aq_timer_destroy(self._h)


class NativeStager:
    """Preallocated staging buffer for batched-runtime tells: producers on
    any thread memcpy fixed-width rows in, the step loop drains one
    contiguous block (EnvelopeBufferPool parity)."""

    def __init__(self, capacity: int, payload_width: int, dtype=np.float32):
        self._lib = _libmod.get()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        self.capacity = capacity
        self.payload_width = payload_width
        self.dtype = np.dtype(dtype)
        self.row_bytes = payload_width * self.dtype.itemsize
        self._h = self._lib.aq_stager_create(capacity, self.row_bytes)
        # reusable drain buffers (zero allocation per drain)
        self._dst_out = np.empty(capacity, np.int32)
        self._payload_out = np.empty((capacity, payload_width), self.dtype)

    def stage(self, dsts: np.ndarray, payloads: np.ndarray) -> int:
        dsts = np.ascontiguousarray(dsts, np.int32)
        payloads = np.ascontiguousarray(payloads, self.dtype)
        k = dsts.shape[0]
        return int(self._lib.aq_stager_stage(
            self._h, k,
            dsts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            payloads.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))))

    def __len__(self) -> int:
        return int(self._lib.aq_stager_count(self._h))

    @property
    def dropped(self) -> int:
        return int(self._lib.aq_stager_dropped(self._h))

    def drain(self) -> Tuple[np.ndarray, np.ndarray]:
        n = int(self._lib.aq_stager_drain(
            self._h,
            self._dst_out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            self._payload_out.ctypes.data_as(
                ctypes.POINTER(ctypes.c_uint8))))
        return self._dst_out[:n], self._payload_out[:n]

    def close(self) -> None:
        if self._h:
            self._lib.aq_stager_destroy(self._h)
            self._h = None
