"""Hook the native substrate into the runtime seams.

- NativeUnboundedMailbox: a MailboxType over the lock-free C++ MPSC queue,
  registered as "native-unbounded" in the Mailboxes registry (the
  dispatch/Mailboxes.scala:91 extension seam).
- NativeScheduler: the Scheduler interface backed by the C++ hashed-wheel
  timer (actor/LightArrayRevolverScheduler.scala parity), selected via
  `akka.scheduler.implementation = native`.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from ..actor.scheduler import Cancellable
from ..dispatch.mailbox import Envelope, MailboxType, MessageQueue
from . import lib as _libmod
from .queues import NativeMpscQueue, NativeWheelTimer


class NativeMessageQueue(MessageQueue):
    __slots__ = ("_q", "_dead_letters")

    def __init__(self):
        self._q = NativeMpscQueue()
        self._dead_letters: Optional[MessageQueue] = None

    def enqueue(self, receiver: Any, handle: Envelope) -> None:
        if not self._q.enqueue(handle):
            # closed (actor stopped): redirect to dead letters, mirroring
            # the reference's becomeClosed mailbox swap — late sends are
            # visible on the EventStream, never silently lost
            dl = self._dead_letters
            if dl is not None:
                dl.enqueue(receiver, handle)

    def dequeue(self) -> Optional[Envelope]:
        return self._q.dequeue()

    @property
    def number_of_messages(self) -> int:
        return len(self._q)

    def clean_up(self, owner: Any, dead_letters: MessageQueue) -> None:
        """On actor stop: install the dead-letter sink for late tells, shut
        the producer side, drain what's left to dead letters, then sweep
        messages orphaned by racing producers — every message is either
        delivered or dead-lettered, exactly once. Memory is reclaimed by
        NativeMpscQueue.__del__ once no producer can hold the handle."""
        self._dead_letters = dead_letters
        self._q.close_producers()
        super().clean_up(owner, dead_letters)  # drains visible nodes
        for obj in self._q.drain_registry():
            dead_letters.enqueue(owner, obj)
        self._q.close()


class NativeUnboundedMailbox(MailboxType):
    def create(self, owner, system) -> MessageQueue:
        return NativeMessageQueue()


def register_native_mailbox(mailboxes) -> bool:
    """Idempotently add the native mailbox type when the library builds."""
    if not _libmod.available():
        return False
    mailboxes.register("native-unbounded", NativeUnboundedMailbox())
    return True


class _NativeCancellable(Cancellable):
    __slots__ = ("_timer", "_tid")

    def __init__(self, timer: NativeWheelTimer, tid: int):
        super().__init__()
        self._timer = timer
        self._tid = tid

    def cancel(self) -> bool:
        out = super().cancel()
        if out:
            self._timer.cancel(self._tid)
        return out


class NativeScheduler:
    """Drop-in for akka_tpu.actor.scheduler.Scheduler backed by the C++
    wheel. Same public surface; shutdown stops the native tick thread."""

    def __init__(self, tick_duration: float = 0.001, ticks_per_wheel: int = 512,
                 name: str = "akka-tpu-native-scheduler"):
        self.tick_duration = tick_duration
        self._timer = NativeWheelTimer(tick_duration, ticks_per_wheel)

    # -- public API (mirrors Scheduler) --------------------------------------
    def schedule_once(self, delay: float, fn: Callable[[], None]) -> Cancellable:
        holder = {}

        def run():
            # the timer may fire before holder is populated; cancel() cannot
            # have been called by then, so a missing entry means "run"
            c = holder.get("c")
            if c is None or not c.is_cancelled:
                fn()
        holder["c"] = _NativeCancellable(
            self._timer, self._timer.schedule_once(delay, run))
        return holder["c"]

    def schedule_with_fixed_delay(self, initial_delay: float, delay: float,
                                  fn: Callable[[], None]) -> Cancellable:
        holder = {}

        def run():
            c = holder.get("c")
            if c is None or not c.is_cancelled:
                fn()
        holder["c"] = _NativeCancellable(
            self._timer, self._timer.schedule_periodically(initial_delay,
                                                           delay, run))
        return holder["c"]

    # the native wheel reschedules at fixed intervals; fixed-rate and
    # fixed-delay coincide for short callbacks
    schedule_at_fixed_rate = schedule_with_fixed_delay

    def schedule_tell_once(self, delay: float, receiver, message: Any,
                           sender=None) -> Cancellable:
        return self.schedule_once(delay,
                                  lambda: receiver.tell(message, sender))

    def schedule_tell_with_fixed_delay(self, initial_delay: float,
                                       delay: float, receiver, message: Any,
                                       sender=None) -> Cancellable:
        return self.schedule_with_fixed_delay(
            initial_delay, delay, lambda: receiver.tell(message, sender))

    def shutdown(self) -> None:
        self._timer.shutdown()
