// Native runtime substrate for akka-tpu.
//
// The reference's performance layer is JVM-intrinsic (sun.misc.Unsafe CAS ops,
// akka-actor/src/main/java/akka/dispatch/AbstractNodeQueue.java lock-free MPSC
// queues, akka-actor/src/main/scala/akka/actor/LightArrayRevolverScheduler.scala
// hashed-wheel timer, akka-remote envelope buffer pools). This library is the
// C++ equivalent (SURVEY.md §2.10 items 1, 2, 5):
//
//  1. aq_mpsc_*   — Vyukov non-intrusive MPSC queue: many producer threads,
//                   one consumer, no locks (AbstractNodeQueue parity).
//  2. aq_timer_*  — hashed-wheel timer on a dedicated tick thread; expired
//                   timer ids drain through a fired-queue the host polls
//                   (LightArrayRevolverScheduler parity).
//  3. aq_stager_* — preallocated message staging buffer: producers reserve
//                   slots with one atomic fetch_add and memcpy fixed-width
//                   payloads; the consumer drains a contiguous block for
//                   zero-copy device upload (EnvelopeBufferPool parity, host
//                   side of the batched runtime's inbox).
//
// Exposed as a plain C ABI for ctypes (no pybind11 in the image).

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

extern "C" {

// ======================== 1. MPSC queue ====================================

struct MpscNode {
    std::atomic<MpscNode*> next;
    uint64_t value;
};

struct MpscQueue {
    std::atomic<MpscNode*> head;  // producers push here
    MpscNode* tail;               // consumer pops here
    MpscNode stub;
    std::atomic<int64_t> size;
    std::atomic<bool> closed;     // late sends no-op (becomeClosed parity)
};

void* aq_mpsc_create() {
    auto* q = new MpscQueue();
    q->stub.next.store(nullptr, std::memory_order_relaxed);
    q->head.store(&q->stub, std::memory_order_relaxed);
    q->tail = &q->stub;
    q->size.store(0, std::memory_order_relaxed);
    q->closed.store(false, std::memory_order_relaxed);
    return q;
}

void aq_mpsc_enqueue(void* h, uint64_t v) {
    auto* q = static_cast<MpscQueue*>(h);
    if (q->closed.load(std::memory_order_acquire)) return;
    auto* n = new MpscNode();
    n->value = v;
    n->next.store(nullptr, std::memory_order_relaxed);
    MpscNode* prev = q->head.exchange(n, std::memory_order_acq_rel);
    prev->next.store(n, std::memory_order_release);
    q->size.fetch_add(1, std::memory_order_relaxed);
}

// returns 1 and sets *out on success, 0 when empty
int aq_mpsc_dequeue(void* h, uint64_t* out) {
    auto* q = static_cast<MpscQueue*>(h);
    MpscNode* tail = q->tail;
    MpscNode* next = tail->next.load(std::memory_order_acquire);
    if (next == nullptr) return 0;
    *out = next->value;
    q->tail = next;
    if (tail != &q->stub) delete tail;
    q->size.fetch_sub(1, std::memory_order_relaxed);
    return 1;
}

int64_t aq_mpsc_count(void* h) {
    return static_cast<MpscQueue*>(h)->size.load(std::memory_order_relaxed);
}

int64_t aq_mpsc_drain(void* h, uint64_t* out, int64_t max) {
    int64_t n = 0;
    while (n < max && aq_mpsc_dequeue(h, out + n)) n++;
    return n;
}

// Mark closed: late producers no-op. Flag-only on purpose — draining here
// would make close a second concurrent consumer racing the real consumer's
// dequeue (double-delete of q->tail on a Vyukov queue). Queued nodes and
// the struct are reclaimed in aq_mpsc_destroy, called only when no thread
// can hold the handle — mirrors the reference's becomeClosed mailbox swap
// routing late senders to dead letters.
void aq_mpsc_close(void* h) {
    static_cast<MpscQueue*>(h)->closed.store(true, std::memory_order_release);
}

void aq_mpsc_destroy(void* h) {
    auto* q = static_cast<MpscQueue*>(h);
    uint64_t scratch;
    while (aq_mpsc_dequeue(h, &scratch)) {}
    // dequeue defers deleting the node it leaves as tail; reclaim it
    if (q->tail != &q->stub) delete q->tail;
    delete q;
}

// ======================== 2. hashed-wheel timer ============================

struct TimerEntry {
    uint64_t id;
    uint64_t deadline_tick;   // absolute tick at which to fire
    uint64_t interval_ticks;  // 0 = one-shot
    bool cancelled;
};

struct WheelTimer {
    std::vector<std::vector<TimerEntry>> wheel;
    uint64_t wheel_mask;
    uint64_t tick_ns;
    uint64_t current_tick;
    std::mutex mu;                      // guards wheel + cancel set
    std::vector<uint64_t> fired;        // expired ids awaiting poll
    std::condition_variable fired_cv;
    std::atomic<bool> stop;
    std::thread ticker;

    void run() {
        auto next = std::chrono::steady_clock::now();
        while (!stop.load(std::memory_order_relaxed)) {
            next += std::chrono::nanoseconds(tick_ns);
            std::this_thread::sleep_until(next);
            std::unique_lock<std::mutex> lk(mu);
            current_tick++;
            auto& slot = wheel[current_tick & wheel_mask];
            bool any = false;
            // Reschedules are collected and appended AFTER the iteration:
            // pushing into the slot being walked would re-visit an entry in
            // the same pass (an exact-multiple interval lands back in this
            // slot), firing and re-appending forever. Absolute deadlines
            // (not revolution counts) make same-slot entries with a future
            // deadline simply skip until their tick arrives.
            std::vector<TimerEntry> resched;
            for (size_t i = 0; i < slot.size();) {
                TimerEntry& e = slot[i];
                if (e.cancelled) {
                    slot.erase(slot.begin() + i);
                    continue;
                }
                if (e.deadline_tick > current_tick) {
                    i++;
                    continue;
                }
                fired.push_back(e.id);
                any = true;
                if (e.interval_ticks > 0) {
                    TimerEntry re = e;
                    re.deadline_tick = current_tick + re.interval_ticks;
                    resched.push_back(re);
                }
                slot.erase(slot.begin() + i);
            }
            for (auto& re : resched)
                wheel[re.deadline_tick & wheel_mask].push_back(re);
            if (any) fired_cv.notify_all();
        }
        fired_cv.notify_all();
    }
};

void* aq_timer_create(uint64_t tick_ns, uint64_t wheel_size_pow2) {
    auto* t = new WheelTimer();
    uint64_t size = 1;
    while (size < wheel_size_pow2) size <<= 1;
    t->wheel.resize(size);
    t->wheel_mask = size - 1;
    t->tick_ns = tick_ns < 100000 ? 100000 : tick_ns;  // >= 0.1ms
    t->current_tick = 0;
    t->stop.store(false);
    t->ticker = std::thread([t] { t->run(); });
    return t;
}

void aq_timer_schedule(void* h, uint64_t id, uint64_t delay_ns,
                       uint64_t interval_ns) {
    auto* t = static_cast<WheelTimer*>(h);
    std::unique_lock<std::mutex> lk(t->mu);
    uint64_t delay_ticks = delay_ns / t->tick_ns;
    if (delay_ticks == 0) delay_ticks = 1;
    uint64_t target = t->current_tick + delay_ticks;
    TimerEntry e;
    e.id = id;
    e.deadline_tick = target;
    e.interval_ticks = interval_ns ? (interval_ns / t->tick_ns ? interval_ns / t->tick_ns : 1) : 0;
    e.cancelled = false;
    t->wheel[target & t->wheel_mask].push_back(e);
}

void aq_timer_cancel(void* h, uint64_t id) {
    auto* t = static_cast<WheelTimer*>(h);
    std::unique_lock<std::mutex> lk(t->mu);
    for (auto& slot : t->wheel)
        for (auto& e : slot)
            if (e.id == id) e.cancelled = true;
}

// blocking poll of expired ids; returns count written to out (<= max)
int64_t aq_timer_poll(void* h, uint64_t* out, int64_t max,
                      int64_t timeout_ms) {
    auto* t = static_cast<WheelTimer*>(h);
    std::unique_lock<std::mutex> lk(t->mu);
    if (t->fired.empty()) {
        t->fired_cv.wait_for(lk, std::chrono::milliseconds(timeout_ms));
    }
    int64_t n = 0;
    while (n < max && !t->fired.empty()) {
        out[n++] = t->fired.front();
        t->fired.erase(t->fired.begin());
    }
    return n;
}

void aq_timer_destroy(void* h) {
    auto* t = static_cast<WheelTimer*>(h);
    t->stop.store(true);
    if (t->ticker.joinable()) t->ticker.join();
    delete t;
}

// ======================== 3. message stager ================================

struct Stager {
    int64_t capacity;
    int64_t payload_bytes;
    std::atomic<int64_t> cursor;      // monotonic reservation counter
    std::atomic<int64_t> committed;   // slots fully written
    std::atomic<int64_t> pending;     // producers between reserve and commit
    std::atomic<bool> draining;       // consumer mid-drain (producers wait)
    std::atomic<int64_t> epoch;       // completed drains (full-vs-fence tiebreak)
    int32_t* dst;
    uint8_t* payload;
    std::atomic<int64_t> dropped;
};

void* aq_stager_create(int64_t capacity, int64_t payload_bytes) {
    auto* s = new Stager();
    s->capacity = capacity;
    s->payload_bytes = payload_bytes;
    s->cursor.store(0);
    s->committed.store(0);
    s->pending.store(0);
    s->draining.store(false);
    s->epoch.store(0);
    s->dst = new int32_t[capacity];
    s->payload = new uint8_t[capacity * payload_bytes];
    s->dropped.store(0);
    return s;
}

// thread-safe: reserve with one fetch_add, memcpy, then commit. All-or-
// nothing per batch. A batch colliding with an in-flight drain WAITS for
// the drain and retries — only a genuinely full buffer drops (bounded-
// mailbox overflow semantics); a concurrent flush must never lose tells.
int64_t aq_stager_stage(void* h, int64_t k, const int32_t* dsts,
                        const uint8_t* payloads) {
    auto* s = static_cast<Stager*>(h);
    for (int attempt = 0; attempt < 1 << 16; ++attempt) {
        if (s->draining.load(std::memory_order_acquire)) {
            std::this_thread::yield();
            continue;
        }
        int64_t seen_epoch = s->epoch.load(std::memory_order_acquire);
        s->pending.fetch_add(1, std::memory_order_acq_rel);
        int64_t start = s->cursor.fetch_add(k, std::memory_order_acq_rel);
        if (start + k <= s->capacity) {
            std::memcpy(s->dst + start, dsts, k * sizeof(int32_t));
            std::memcpy(s->payload + start * s->payload_bytes, payloads,
                        k * s->payload_bytes);
            s->committed.fetch_add(k, std::memory_order_acq_rel);
            s->pending.fetch_sub(1, std::memory_order_acq_rel);
            return k;
        }
        s->pending.fetch_sub(1, std::memory_order_acq_rel);
        // "full" is only believable if NO drain was in flight around the
        // failed reservation: a drain that completed between our decrement
        // and this check (draining back to false, epoch bumped) emptied the
        // buffer — retry instead of falsely dropping into an empty stager
        if (!s->draining.load(std::memory_order_acquire) &&
            s->epoch.load(std::memory_order_acquire) == seen_epoch) {
            // not a drain fence: the buffer is genuinely full
            s->dropped.fetch_add(k, std::memory_order_relaxed);
            return 0;
        }
        std::this_thread::yield();  // fenced by the drain: wait and retry
    }
    s->dropped.fetch_add(k, std::memory_order_relaxed);
    return 0;
}

int64_t aq_stager_count(void* h) {
    return static_cast<Stager*>(h)->committed.load(std::memory_order_acquire);
}

int64_t aq_stager_dropped(void* h) {
    return static_cast<Stager*>(h)->dropped.load(std::memory_order_relaxed);
}

// single-consumer drain: copies the staged block out and resets. Waits for
// in-flight producers (between reserve and commit) to finish; producers
// arriving during the drain see a beyond-capacity cursor and drop (the host
// inbox is bounded anyway — bounded-mailbox overflow semantics). committed
// is zeroed BEFORE the cursor so a post-reset stage can never be lost.
int64_t aq_stager_drain(void* h, int32_t* dst_out, uint8_t* payload_out) {
    auto* s = static_cast<Stager*>(h);
    // flag first (late producers park), then fence the cursor so producers
    // that already passed the flag check fail their reservation and retry
    s->draining.store(true, std::memory_order_release);
    s->cursor.fetch_add(s->capacity + 1, std::memory_order_acq_rel);
    while (s->pending.load(std::memory_order_acquire) != 0)
        std::this_thread::yield();
    int64_t n = s->committed.load(std::memory_order_acquire);
    std::memcpy(dst_out, s->dst, n * sizeof(int32_t));
    std::memcpy(payload_out, s->payload, n * s->payload_bytes);
    s->committed.store(0, std::memory_order_release);
    s->cursor.store(0, std::memory_order_release);
    s->epoch.fetch_add(1, std::memory_order_acq_rel);
    s->draining.store(false, std::memory_order_release);
    return n;
}

void aq_stager_destroy(void* h) {
    auto* s = static_cast<Stager*>(h);
    delete[] s->dst;
    delete[] s->payload;
    delete s;
}

}  // extern "C"
