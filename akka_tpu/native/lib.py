"""Build + ctypes bindings for the native runtime library.

Compiles akka_native.cpp with g++ on first use (cached as a .so next to the
package; rebuilt when the source changes). pybind11 is not in the image, so
the C ABI + ctypes is the binding layer. Everything degrades gracefully:
`available()` is False when no compiler is present and all consumers fall
back to pure-Python implementations.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src", "akka_native.cpp")
_BUILD_DIR = os.path.join(_HERE, "_build")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _so_path() -> str:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha1(f.read()).hexdigest()[:12]
    return os.path.join(_BUILD_DIR, f"libakka_native-{digest}.so")


def _build() -> Optional[str]:
    so = _so_path()
    if os.path.exists(so):
        return so
    os.makedirs(_BUILD_DIR, exist_ok=True)
    tmp = so + ".tmp"
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           "-o", tmp, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (subprocess.CalledProcessError, FileNotFoundError,
            subprocess.TimeoutExpired):
        return None
    os.replace(tmp, so)
    return so


def get() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        so = _build()
        if so is None:
            return None
        lib = ctypes.CDLL(so)
        # -- signatures --------------------------------------------------
        u64, i64, i32p, u64p, u8p, voidp = (
            ctypes.c_uint64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_void_p)
        lib.aq_mpsc_create.restype = voidp
        lib.aq_mpsc_enqueue.argtypes = [voidp, u64]
        lib.aq_mpsc_dequeue.argtypes = [voidp, u64p]
        lib.aq_mpsc_dequeue.restype = ctypes.c_int
        lib.aq_mpsc_count.argtypes = [voidp]
        lib.aq_mpsc_count.restype = i64
        lib.aq_mpsc_drain.argtypes = [voidp, u64p, i64]
        lib.aq_mpsc_drain.restype = i64
        lib.aq_mpsc_close.argtypes = [voidp]
        lib.aq_mpsc_destroy.argtypes = [voidp]

        lib.aq_timer_create.argtypes = [u64, u64]
        lib.aq_timer_create.restype = voidp
        lib.aq_timer_schedule.argtypes = [voidp, u64, u64, u64]
        lib.aq_timer_cancel.argtypes = [voidp, u64]
        lib.aq_timer_poll.argtypes = [voidp, u64p, i64, i64]
        lib.aq_timer_poll.restype = i64
        lib.aq_timer_destroy.argtypes = [voidp]

        lib.aq_stager_create.argtypes = [i64, i64]
        lib.aq_stager_create.restype = voidp
        lib.aq_stager_stage.argtypes = [voidp, i64, i32p, u8p]
        lib.aq_stager_stage.restype = i64
        lib.aq_stager_count.argtypes = [voidp]
        lib.aq_stager_count.restype = i64
        lib.aq_stager_dropped.argtypes = [voidp]
        lib.aq_stager_dropped.restype = i64
        lib.aq_stager_drain.argtypes = [voidp, i32p, u8p]
        lib.aq_stager_drain.restype = i64
        lib.aq_stager_destroy.argtypes = [voidp]
        _lib = lib
        return _lib


def available() -> bool:
    return get() is not None
