"""Native (C++) runtime substrate.

Where the reference leans on JVM intrinsics (sun.misc.Unsafe, lock-free
AbstractNodeQueue mailboxes, the LightArrayRevolverScheduler wheel, Artery
envelope buffer pools), akka-tpu uses a small C++ library bound via ctypes:
lock-free MPSC mailbox queues, a hashed-wheel timer with a native tick
thread, and a preallocated message stager feeding the batched device
runtime. Built on demand with g++; everything falls back to pure Python
when unavailable (`available()`).
"""

from .lib import available  # noqa: F401
from .integration import (NativeScheduler, NativeUnboundedMailbox,  # noqa: F401
                          register_native_mailbox)

__all__ = ["available", "NativeScheduler", "NativeUnboundedMailbox",
           "register_native_mailbox"]


def __getattr__(name):
    # NativeMpscQueue etc. require the built library; import lazily so
    # importing akka_tpu.native never fails without a compiler
    if name in ("NativeMpscQueue", "NativeWheelTimer", "NativeStager"):
        from . import queues
        return getattr(queues, name)
    raise AttributeError(name)
