"""Replicator: per-node actor replicating CRDTs with tunable consistency.

Reference parity: akka-distributed-data/src/main/scala/akka/cluster/ddata/
Replicator.scala — Get/Update/Subscribe/Delete with consistency levels
(ReadLocal/ReadFrom/ReadMajority/ReadAll and the Write* mirror, :430-495),
periodic gossip of Status digests + Gossip payloads, delta propagation
(:877,1072-1079 / DeltaPropagationSelector.scala), deleted-key tombstones,
and pruning of removed nodes' contributions (PruningState.scala, simplified
here to leader-driven collapse without the two-phase performed/obsoleted
handshake).

Wire protocol between replicators (one per node, same actor path):
- _Status(digests)        gossip tick: my {key -> digest}
- _Gossip(entries, reply) entries the peer lacked / had stale
- _DeltaPropagation({key -> delta}) cheap incremental updates
- _Read(key) / _ReadResult(envelope)      read-consistency fan-out
- _Write(key, envelope) / _WriteAck       write-consistency fan-out
"""

from __future__ import annotations

import hashlib
import pickle
import random
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from ..actor.actor import Actor
from ..actor.messages import Terminated as ActorTerminated
from ..actor.props import Props
from ..actor.ref import ActorRef
from ..actor.system import ActorSystem, ExtensionId
from ..cluster.cluster import Cluster
from ..cluster.events import MemberEvent, MemberRemoved, MemberUp
from ..cluster.member import MemberStatus
from .crdt import DeltaReplicatedData, RemovedNodePruning, ReplicatedData
from .durable import DurableStore


# -- keys -------------------------------------------------------------------

@dataclass(frozen=True)
class Key:
    """Typed key (reference: Key.scala; GCounterKey etc. are just ids here)."""
    id: str

    def __str__(self):
        return self.id


def unique_node_id(ua) -> str:
    """CRDT node id for a cluster member incarnation: "addr#uid"."""
    return f"{ua.address_str}#{ua.uid}"


# -- consistency levels (reference: Replicator.scala:430-495) ---------------

@dataclass(frozen=True)
class ReadLocal:
    timeout: float = 0.0


@dataclass(frozen=True)
class ReadFrom:
    n: int
    timeout: float = 5.0


@dataclass(frozen=True)
class ReadMajority:
    timeout: float = 5.0
    min_cap: int = 0


@dataclass(frozen=True)
class ReadAll:
    timeout: float = 5.0


@dataclass(frozen=True)
class WriteLocal:
    timeout: float = 0.0


@dataclass(frozen=True)
class WriteTo:
    n: int
    timeout: float = 5.0


@dataclass(frozen=True)
class WriteMajority:
    timeout: float = 5.0
    min_cap: int = 0


@dataclass(frozen=True)
class WriteAll:
    timeout: float = 5.0


# -- user API messages ------------------------------------------------------

@dataclass(frozen=True)
class Get:
    key: Key
    consistency: Any = ReadLocal()
    request: Any = None


@dataclass(frozen=True)
class GetSuccess:
    key: Key
    data: ReplicatedData
    request: Any = None

    def get(self, key: Key) -> ReplicatedData:
        return self.data


@dataclass(frozen=True)
class NotFound:
    key: Key
    request: Any = None


@dataclass(frozen=True)
class GetFailure:
    """Read consistency not met within timeout."""
    key: Key
    request: Any = None


@dataclass(frozen=True)
class GetDataDeleted:
    key: Key
    request: Any = None


@dataclass(frozen=True)
class Update:
    key: Key
    initial: Optional[ReplicatedData]
    consistency: Any
    modify: Callable[[ReplicatedData], ReplicatedData]
    request: Any = None


@dataclass(frozen=True)
class UpdateSuccess:
    key: Key
    request: Any = None


@dataclass(frozen=True)
class UpdateTimeout:
    key: Key
    request: Any = None


@dataclass(frozen=True)
class ModifyFailure:
    key: Key
    error: str
    request: Any = None


@dataclass(frozen=True)
class UpdateDataDeleted:
    key: Key
    request: Any = None


@dataclass(frozen=True)
class Delete:
    key: Key
    consistency: Any = WriteLocal()
    request: Any = None


@dataclass(frozen=True)
class DeleteSuccess:
    key: Key
    request: Any = None


@dataclass(frozen=True)
class ReplicationDeleteFailure:
    key: Key
    request: Any = None


@dataclass(frozen=True)
class DataDeleted:
    key: Key
    request: Any = None


@dataclass(frozen=True)
class Subscribe:
    key: Key
    subscriber: ActorRef


@dataclass(frozen=True)
class Unsubscribe:
    key: Key
    subscriber: ActorRef


@dataclass(frozen=True)
class Changed:
    key: Key
    data: ReplicatedData

    def get(self, key: Key) -> ReplicatedData:
        return self.data


@dataclass(frozen=True)
class Deleted:
    key: Key


@dataclass(frozen=True)
class GetKeyIds:
    pass


@dataclass(frozen=True)
class GetKeyIdsResult:
    key_ids: frozenset


@dataclass(frozen=True)
class GetReplicaCount:
    pass


@dataclass(frozen=True)
class ReplicaCount:
    n: int


# -- internal wire messages -------------------------------------------------

DELETED = "__deleted__"  # tombstone sentinel in the data map


@dataclass(frozen=True)
class _Status:
    digests: Dict[str, bytes]
    from_addr: str


@dataclass(frozen=True)
class _Gossip:
    entries: Dict[str, Any]   # key -> data-or-DELETED (pickled-safe CRDTs)
    want_keys: Tuple[str, ...]  # keys the sender lacks and wants back
    from_addr: str
    tombstones: Dict[str, Tuple[str, ...]] = field(default_factory=dict)
    # sender's per-key delta sequence at send time: a full state entry
    # covers every op up to this seq, so the receiver resyncs its
    # (origin, key) delta cursor and resumes op-based deltas after a gap
    # (reference: delta versions riding the gossiped DataEnvelope)
    delta_seq: Dict[str, int] = field(default_factory=dict)
    origin_uid: str = ""   # sender replicator incarnation (cursor scope)


@dataclass(frozen=True)
class _DeltaPropagation:
    """deltas: key -> (seq, delta). Op-based deltas are only safe under
    CAUSAL delivery — the per-(origin, key) sequence number lets receivers
    detect a gap (dropped tick, late join) and fall back to full-state
    gossip instead of applying an op whose causal context they miss
    (reference: DeltaPropagationSelector seqNr discipline; applying a
    gapped ORSet op poisons the vvector and deletes the missed elements
    cluster-wide)."""
    deltas: Dict[str, Any]
    from_addr: str
    origin_uid: str = ""   # sender replicator incarnation (cursor scope)


@dataclass(frozen=True)
class _Read:
    key: str
    req_id: str


@dataclass(frozen=True)
class _ReadResult:
    req_id: str
    data: Any  # data | DELETED | None


@dataclass(frozen=True)
class _Write:
    key: str
    data: Any  # data | DELETED
    req_id: str


@dataclass(frozen=True)
class _WriteAck:
    req_id: str


@dataclass(frozen=True)
class _Pruned:
    """Leader pruned `removed`'s contributions out of `key` (simplified
    PruningPerformed dissemination)."""
    key: str
    removed: Tuple[str, ...]
    data: Any
    from_addr: str


@dataclass(frozen=True)
class _GossipTick:
    pass


@dataclass(frozen=True)
class _NotifyTick:
    pass


@dataclass(frozen=True)
class _DeltaTick:
    pass


@dataclass(frozen=True)
class _PruneTick:
    pass


@dataclass(frozen=True)
class _ReqTimeout:
    req_id: str


@dataclass(frozen=True)
class ReplicatorSettings:
    """(reference: ReplicatorSettings.scala)"""
    role: Optional[str] = None
    gossip_interval: float = 2.0
    notify_subscribers_interval: float = 0.5
    delta_propagation_interval: float = 0.2
    pruning_interval: float = 30.0
    max_pruning_dissemination: float = 60.0
    durable_keys: Tuple[str, ...] = ()
    durable_store_dir: Optional[str] = None

    @staticmethod
    def from_config(cfg) -> "ReplicatorSettings":
        return ReplicatorSettings(
            role=cfg.get_string("role", "") or None,
            gossip_interval=cfg.get_duration("gossip-interval", "2s"),
            notify_subscribers_interval=cfg.get_duration(
                "notify-subscribers-interval", "0.5s"),
            delta_propagation_interval=cfg.get_duration(
                "delta-crdt.delta-propagation-interval", "0.2s"),
            pruning_interval=cfg.get_duration("pruning-interval", "30s"),
            durable_keys=tuple(cfg.get("durable.keys", []) or []),
            durable_store_dir=cfg.get_string("durable.lmdb.dir", "") or None)


class _PendingReq:
    """In-flight read/write consistency round."""

    __slots__ = ("kind", "key", "replyto", "request", "needed", "acks",
                 "acc", "local", "timer")

    def __init__(self, kind, key, replyto, request, needed, local):
        self.kind = kind          # "read" | "write" | "delete"
        self.key = key
        self.replyto = replyto
        self.request = request
        self.needed = needed      # remote acks still required
        self.acks = 0
        self.acc = local          # merged data (reads)
        self.local = local
        self.timer = None


class Replicator(Actor):
    """One per node (reference: Replicator.scala actor)."""

    def __init__(self, settings: Optional[ReplicatorSettings] = None):
        super().__init__()
        self.settings = settings or ReplicatorSettings()
        self.cluster = Cluster.get(self.context.system)
        self.self_addr = str(self.context.system.provider.default_address)
        # CRDT node id: "addr#uid" so a restarted node (same host:port, new
        # incarnation) is a distinct contributor and is never hit by the old
        # incarnation's pruning tombstone (reference: SelfUniqueAddress)
        self.self_unique = unique_node_id(self.cluster.self_unique_address)
        # key -> data | DELETED sentinel
        self.data: Dict[str, Any] = {}
        self.subscribers: Dict[str, Set[ActorRef]] = {}
        self.changed_keys: Set[str] = set()
        self.pending: Dict[str, _PendingReq] = {}
        self.deltas: Dict[str, Any] = {}  # key -> accumulated delta for peers
        self.delta_seq: Dict[str, int] = {}        # key -> my last sent seq
        # delta cursors key on the origin's INCARNATION, not its bare
        # address: a restarted origin's fresh seq stream (1, 2, ...) would
        # otherwise be swallowed as duplicates by the old cursor and its
        # first genuinely-applied op would smuggle the unseen events'
        # vvector in — precisely the poisoning the gap guard prevents
        self._delta_incarnation = uuid.uuid4().hex
        self._delta_seen: Dict[Tuple[str, str, str], int] = {}
        self._delta_gapped: set = set()   # (origin, origin_uid, key)
        self._origin_uid: Dict[str, str] = {}  # origin addr -> last uid
        # key -> {pruned node id -> prune time}; incoming merges are cleaned
        # against these so stale gossip can't resurrect a removed node's
        # entries (reference: PruningState tombstones); expired after
        # max_pruning_dissemination since uid-based ids can't recur
        self.pruned: Dict[str, Dict[str, float]] = {}
        # unique ids of members the cluster REMOVED (only these are ever
        # pruned — application-chosen logical CRDT node ids never are)
        self.removed_nodes: Set[str] = set()
        self._digest_cache: Dict[str, bytes] = {}
        # gossip-size observability: payload bytes per propagation tick and
        # the op-delta-vs-full-state ratio, step-stamped on the shared
        # ATT_STEP axis so the O(entry) claim is visible in the metrics
        # plane (docs/OBSERVABILITY.md)
        reg = getattr(self.context.system, "metrics_registry", None)
        self._metrics = reg
        self._h_gossip_bytes = reg.histogram(
            "ddata_gossip_payload_bytes",
            "bytes per outbound replication payload (delta tick or "
            "full-state gossip)") if reg is not None else None
        self._h_delta_vs_full = reg.histogram(
            "ddata_delta_vs_full",
            "per-key op-delta size as a fraction of the full-state size, "
            "observed per delta-propagation tick") if reg is not None else None
        self._cluster_listener = lambda e: self.self_ref.tell(e)
        self._tasks: List[Any] = []
        self.durable = None
        if self.settings.durable_keys:
            self.durable = DurableStore(
                self.settings.durable_store_dir
                or f"/tmp/akka-tpu-ddata-{self.context.system.name}-{self.self_addr.replace('/', '_').replace(':', '_')}")
            for k, v in self.durable.load_all().items():
                self.data[k] = v

    # -- lifecycle -----------------------------------------------------------
    def pre_start(self) -> None:
        sched = self.context.system.scheduler
        s = self.settings
        self._tasks = [
            sched.schedule_tell_with_fixed_delay(
                s.gossip_interval, s.gossip_interval, self.self_ref, _GossipTick()),
            sched.schedule_tell_with_fixed_delay(
                s.delta_propagation_interval, s.delta_propagation_interval,
                self.self_ref, _DeltaTick()),
            sched.schedule_tell_with_fixed_delay(
                s.pruning_interval, s.pruning_interval, self.self_ref, _PruneTick()),
            sched.schedule_tell_with_fixed_delay(
                s.notify_subscribers_interval, s.notify_subscribers_interval,
                self.self_ref, _NotifyTick()),
        ]
        self.cluster.subscribe(self._cluster_listener, MemberEvent,
                               initial_state=False)

    def post_stop(self) -> None:
        self.cluster.unsubscribe(self._cluster_listener)
        for t in self._tasks:
            t.cancel()

    # -- membership helpers --------------------------------------------------
    def _nodes(self) -> List[str]:
        """Other Up nodes carrying the configured role."""
        out = []
        for m in self.cluster.state.members:
            if m.status not in (MemberStatus.UP, MemberStatus.WEAKLY_UP):
                continue
            if self.settings.role and self.settings.role not in m.roles:
                continue
            a = str(m.address)
            if a != self.self_addr:
                out.append(a)
        return out

    def _replicator_at(self, addr: str) -> ActorRef:
        rel = self.context.self_ref.path.to_string_without_address()
        return self.context.system.provider.resolve_actor_ref(f"{addr}{rel}")

    def _required_acks(self, consistency, n_nodes_total: int) -> int:
        """Remote acks needed beyond the local write/read."""
        if isinstance(consistency, (ReadLocal, WriteLocal)):
            return 0
        if isinstance(consistency, ReadFrom):
            return max(0, min(consistency.n - 1, n_nodes_total - 1))
        if isinstance(consistency, WriteTo):
            return max(0, min(consistency.n - 1, n_nodes_total - 1))
        if isinstance(consistency, (ReadMajority, WriteMajority)):
            majority = n_nodes_total // 2 + 1
            cap = getattr(consistency, "min_cap", 0)
            return max(0, min(max(majority, cap), n_nodes_total) - 1)
        if isinstance(consistency, (ReadAll, WriteAll)):
            return n_nodes_total - 1
        raise ValueError(f"unknown consistency {consistency!r}")

    # -- digest/gossip helpers ----------------------------------------------
    @classmethod
    def _canon(cls, obj: Any) -> Any:
        """Canonicalize nested state so semantically equal replicas hash
        equal regardless of dict/set insertion order (merge(a,b) and
        merge(b,a) build dicts in different orders)."""
        if isinstance(obj, dict):
            return ("d",) + tuple(sorted(
                ((cls._canon(k), cls._canon(v)) for k, v in obj.items()),
                key=repr))
        if isinstance(obj, (set, frozenset)):
            return ("s",) + tuple(sorted((cls._canon(e) for e in obj), key=repr))
        if isinstance(obj, (list, tuple)):
            return ("l",) + tuple(cls._canon(e) for e in obj)
        if isinstance(obj, (str, int, float, bool, bytes, type(None))):
            return obj
        from ..actor.ref import ActorRef
        if isinstance(obj, ActorRef):
            # by serialized full-address path: the SAME logical ref is a
            # LocalActorRef on its home node and a RemoteActorRef on peers
            # — attribute-walking would never hash equal across them
            from ..serialization.codec import ref_wire_path
            return ("r", ref_wire_path(obj))
        # CRDTs / VersionVector: class name + attrs, skipping delta caches
        attrs = {}
        for slot in getattr(type(obj), "__slots__", ()) or ():
            if slot.startswith("_"):
                continue
            attrs[slot] = getattr(obj, slot, None)
        for k, v in getattr(obj, "__dict__", {}).items():
            if not k.startswith("_"):
                attrs[k] = v
        if attrs:
            return (type(obj).__name__,) + cls._canon(attrs)
        return repr(obj)

    @classmethod
    def _digest(cls, data: Any) -> bytes:
        # digest the canonical form with the FIXED wire codec, not pickle:
        # these bytes are compared across nodes, so the encoding must be
        # stable across Python versions (pickle's isn't)
        from ..serialization.codec import dumps as _wire_dumps
        return hashlib.sha1(_wire_dumps(cls._canon(data))).digest()

    def _digest_for(self, key: str) -> bytes:
        """Per-key digest, cached until the next _set_data (the reference
        Replicator caches digests the same way — steady-state gossip must
        not re-hash the whole data map). Digests are COMPARED across nodes,
        so embedded ActorRefs must hash by full-address path — install the
        transport context like any other wire encode."""
        d = self._digest_cache.get(key)
        if d is None:
            from ..serialization.serialization import transport_information
            provider = getattr(self.context.system, "provider", None)
            with transport_information(provider):
                d = self._digest_cache[key] = self._digest(self.data[key])
        return d

    def _set_data(self, key: str, value: Any, notify: bool = True) -> None:
        old = self.data.get(key)
        self.data[key] = value
        self._digest_cache.pop(key, None)
        if self.durable is not None and self._is_durable(key):
            self.durable.store(key, value)
        if notify and old is not value:
            self.changed_keys.add(key)  # flushed on _NotifyTick

    def _is_durable(self, key: str) -> bool:
        for pat in self.settings.durable_keys:
            if pat == key or (pat.endswith("*") and key.startswith(pat[:-1])):
                return True
        return False

    def _flush_changes(self) -> None:
        for key in list(self.changed_keys):
            subs = self.subscribers.get(key)
            cur = self.data.get(key)
            if subs and cur is not None:
                msg = Deleted(Key(key)) if cur == DELETED else Changed(Key(key), cur)
                for ref in list(subs):
                    ref.tell(msg, self.self_ref)
        self.changed_keys.clear()

    def _merge_in(self, key: str, incoming: Any) -> None:
        cur = self.data.get(key)
        if incoming == DELETED or cur == DELETED:
            merged = DELETED
        else:
            incoming = self._cleanup_pruned(key, incoming)
            if cur is None:
                merged = incoming
            else:
                merged = self._cleanup_pruned(key, cur).merge(incoming)
        if merged != cur:
            self._set_data(key, merged)
            if merged == DELETED:
                # remote delete (a _Write/_Gossip carried the tombstone):
                # drop the key's delta bookkeeping exactly as the local
                # Delete path does — dead keys must not pin cursors, and
                # a pending accumulated delta for them is never sent
                self.deltas.pop(key, None)
                self.delta_seq.pop(key, None)
                self._drop_delta_cursors(key=key)

    def _cleanup_pruned(self, key: str, value: Any) -> Any:
        """Drop tombstoned nodes' residual entries from stale incoming state
        so pruning can't be undone by old gossip."""
        removed = self.pruned.get(key)
        if removed and isinstance(value, RemovedNodePruning):
            for node in removed:
                value = value.prune_cleanup(node)
        return value

    # -- receive -------------------------------------------------------------
    def receive(self, message: Any) -> Any:  # noqa: C901
        if isinstance(message, Get):
            self._handle_get(message)
        elif isinstance(message, Update):
            self._handle_update(message)
        elif isinstance(message, Delete):
            self._handle_delete(message)
        elif isinstance(message, Subscribe):
            self.subscribers.setdefault(message.key.id, set()).add(message.subscriber)
            self.context.watch(message.subscriber)
            cur = self.data.get(message.key.id)
            if cur == DELETED:
                message.subscriber.tell(Deleted(message.key), self.self_ref)
            elif cur is not None:
                message.subscriber.tell(Changed(message.key, cur), self.self_ref)
        elif isinstance(message, Unsubscribe):
            self.subscribers.get(message.key.id, set()).discard(message.subscriber)
            if not any(message.subscriber in subs
                       for subs in self.subscribers.values()):
                self.context.unwatch(message.subscriber)
        elif isinstance(message, ActorTerminated):
            for subs in self.subscribers.values():
                subs.discard(message.actor)
        elif isinstance(message, GetKeyIds):
            ids = frozenset(k for k, v in self.data.items() if v != DELETED)
            self.sender.tell(GetKeyIdsResult(ids), self.self_ref)
        elif isinstance(message, GetReplicaCount):
            self.sender.tell(ReplicaCount(len(self._nodes()) + 1), self.self_ref)
        # -- internal ticks ---------------------------------------------------
        elif isinstance(message, _NotifyTick):
            self._flush_changes()
        elif isinstance(message, _GossipTick):
            self._gossip_tick()
        elif isinstance(message, _DeltaTick):
            self._delta_tick()
        elif isinstance(message, _PruneTick):
            self._prune_tick()
        elif isinstance(message, _ReqTimeout):
            self._req_timeout(message.req_id)
        # -- wire -------------------------------------------------------------
        elif isinstance(message, _Status):
            self._handle_status(message)
        elif isinstance(message, _Gossip):
            self._handle_gossip(message)
        elif isinstance(message, _DeltaPropagation):
            origin, uid = message.from_addr, message.origin_uid
            if self._origin_uid.get(origin) != uid:
                # new origin incarnation: its old cursors are dead weight
                # (and must never swallow the fresh stream as duplicates)
                self._drop_delta_cursors(origin=origin)
                self._origin_uid[origin] = uid
            for key, entry in message.deltas.items():
                seq, delta = entry
                ok_pair = (origin, uid, key)
                if ok_pair in self._delta_gapped:
                    continue  # full-state gossip owns this key from origin
                seen = self._delta_seen.get(ok_pair, 0)
                if seq <= seen:
                    continue  # duplicate/old tick
                if seq != seen + 1:
                    # GAP: applying an op whose causal context we miss
                    # would poison the vvector (delete the missed ops'
                    # elements everywhere). Drop, and let digest gossip
                    # carry this key until a full state resyncs the cursor
                    self._delta_gapped.add(ok_pair)
                    continue
                cur = self.data.get(key)
                if cur == DELETED:
                    continue  # no cursor bumps for dead keys
                if cur is None:
                    # first sight of the key via a delta: op-based deltas
                    # apply against their zero (ReplicatedDelta.zero);
                    # full-state deltas ARE data
                    zero = getattr(delta, "zero", None)
                    self._merge_in(key, zero().merge_delta(delta)
                                   if zero is not None else delta)
                elif isinstance(cur, DeltaReplicatedData):
                    merged = cur.merge_delta(delta)
                    if merged != cur:
                        self._set_data(key, merged)
                else:
                    self._merge_in(key, delta)
                self._delta_seen[ok_pair] = seq
        elif isinstance(message, _Read):
            self.sender.tell(_ReadResult(message.req_id,
                                         self.data.get(message.key)),
                             self.self_ref)
        elif isinstance(message, _ReadResult):
            self._handle_read_result(message)
        elif isinstance(message, _Write):
            self._merge_in(message.key, message.data)
            self.sender.tell(_WriteAck(message.req_id), self.self_ref)
        elif isinstance(message, _WriteAck):
            self._handle_write_ack(message)
        elif isinstance(message, _Pruned):
            _ts = self.pruned.setdefault(message.key, {})
            _now = time.time()
            for _n in message.removed:
                _ts.setdefault(_n, _now)
            cur = self.data.get(message.key)
            if (cur is not None and cur != DELETED
                    and isinstance(cur, RemovedNodePruning)):
                cleaned = cur
                for n in message.removed:
                    cleaned = cleaned.prune_cleanup(n)
                if cleaned != cur:
                    self._set_data(message.key, cleaned, notify=False)
            self._merge_in(message.key, message.data)
        elif isinstance(message, MemberRemoved):
            self.removed_nodes.add(unique_node_id(message.member.unique_address))
            gone = str(message.member.unique_address.address)
            self._drop_delta_cursors(origin=gone)
            self._origin_uid.pop(gone, None)
        elif isinstance(message, MemberEvent):
            pass
        else:
            return self.unhandled(message)

    # -- user ops ------------------------------------------------------------
    def _handle_get(self, msg: Get) -> None:
        key, replyto = msg.key.id, self.sender
        local = self.data.get(key)
        if isinstance(msg.consistency, ReadLocal) or not self._nodes():
            self._reply_get(msg.key, local, replyto, msg.request)
            return
        needed = self._required_acks(msg.consistency, len(self._nodes()) + 1)
        if needed == 0:
            self._reply_get(msg.key, local, replyto, msg.request)
            return
        req_id = uuid.uuid4().hex
        req = _PendingReq("read", msg.key, replyto, msg.request, needed, local)
        self.pending[req_id] = req
        self._start_timeout(req_id, msg.consistency.timeout)
        for addr in self._nodes():
            self._replicator_at(addr).tell(_Read(key, req_id), self.self_ref)

    def _reply_get(self, key: Key, value: Any, replyto: ActorRef, request) -> None:
        if value == DELETED:
            replyto.tell(GetDataDeleted(key, request), self.self_ref)
        elif value is None:
            replyto.tell(NotFound(key, request), self.self_ref)
        else:
            replyto.tell(GetSuccess(key, value, request), self.self_ref)

    def _handle_update(self, msg: Update) -> None:
        key, replyto = msg.key.id, self.sender
        cur = self.data.get(key)
        if cur == DELETED:
            replyto.tell(UpdateDataDeleted(msg.key, msg.request), self.self_ref)
            return
        try:
            base = cur if cur is not None else msg.initial
            if base is None:
                raise KeyError(f"no initial value for new key {key}")
            new = msg.modify(base)
        except Exception as e:  # noqa: BLE001 (reference: ModifyFailure)
            replyto.tell(ModifyFailure(msg.key, str(e), msg.request), self.self_ref)
            return
        # harvest + reset delta before storing (reference :1072-1079)
        if isinstance(new, DeltaReplicatedData) and new.delta is not None:
            d = new.delta
            acc = self.deltas.get(key)
            self.deltas[key] = d if acc is None else acc.merge(d)
            new = new.reset_delta()
        self._set_data(key, new)
        nodes = self._nodes()
        needed = self._required_acks(msg.consistency, len(nodes) + 1)
        if needed == 0:
            replyto.tell(UpdateSuccess(msg.key, msg.request), self.self_ref)
            return
        req_id = uuid.uuid4().hex
        req = _PendingReq("write", msg.key, replyto, msg.request, needed, new)
        self.pending[req_id] = req
        self._start_timeout(req_id, msg.consistency.timeout)
        for addr in nodes:
            self._replicator_at(addr).tell(_Write(key, new, req_id), self.self_ref)

    def _handle_delete(self, msg: Delete) -> None:
        key, replyto = msg.key.id, self.sender
        if self.data.get(key) == DELETED:
            replyto.tell(DataDeleted(msg.key, msg.request), self.self_ref)
            return
        self._set_data(key, DELETED)
        self.deltas.pop(key, None)
        self.delta_seq.pop(key, None)
        self._drop_delta_cursors(key=key)
        nodes = self._nodes()
        needed = self._required_acks(msg.consistency, len(nodes) + 1)
        if needed == 0:
            replyto.tell(DeleteSuccess(msg.key, msg.request), self.self_ref)
            return
        req_id = uuid.uuid4().hex
        req = _PendingReq("delete", msg.key, replyto, msg.request, needed, DELETED)
        self.pending[req_id] = req
        self._start_timeout(req_id, msg.consistency.timeout)
        for addr in nodes:
            self._replicator_at(addr).tell(_Write(key, DELETED, req_id), self.self_ref)

    def _start_timeout(self, req_id: str, timeout: float) -> None:
        self.pending[req_id].timer = \
            self.context.system.scheduler.schedule_tell_once(
                timeout, self.self_ref, _ReqTimeout(req_id))

    def _req_timeout(self, req_id: str) -> None:
        req = self.pending.pop(req_id, None)
        if req is None:
            return
        if req.kind == "read":
            # reply with best-effort merged data? reference: GetFailure
            req.replyto.tell(GetFailure(req.key, req.request), self.self_ref)
        elif req.kind == "write":
            req.replyto.tell(UpdateTimeout(req.key, req.request), self.self_ref)
        else:
            req.replyto.tell(ReplicationDeleteFailure(req.key, req.request),
                             self.self_ref)

    def _handle_read_result(self, msg: _ReadResult) -> None:
        req = self.pending.get(msg.req_id)
        if req is None:
            return
        if msg.data is not None:
            if msg.data == DELETED or req.acc == DELETED:
                req.acc = DELETED
            elif req.acc is None:
                req.acc = msg.data
            else:
                req.acc = req.acc.merge(msg.data)
        req.acks += 1
        if req.acks >= req.needed:
            self.pending.pop(msg.req_id, None)
            if req.timer:
                req.timer.cancel()
            if req.acc is not None and req.acc != req.local:
                self._merge_in(req.key.id, req.acc)  # read-repair
            self._reply_get(req.key, req.acc, req.replyto, req.request)

    def _handle_write_ack(self, msg: _WriteAck) -> None:
        req = self.pending.get(msg.req_id)
        if req is None:
            return
        req.acks += 1
        if req.acks >= req.needed:
            self.pending.pop(msg.req_id, None)
            if req.timer:
                req.timer.cancel()
            if req.kind == "delete":
                req.replyto.tell(DeleteSuccess(req.key, req.request), self.self_ref)
            else:
                req.replyto.tell(UpdateSuccess(req.key, req.request), self.self_ref)

    # -- gossip --------------------------------------------------------------
    def _gossip_tick(self) -> None:
        nodes = self._nodes()
        if not nodes or not self.data:
            return
        digests = {k: self._digest_for(k) for k in self.data}
        for addr in random.sample(nodes, min(2, len(nodes))):
            self._replicator_at(addr).tell(
                _Status(digests, self.self_addr), self.self_ref)

    def _handle_status(self, msg: _Status) -> None:
        # entries the peer lacks or differs on -> send ours
        to_send = {}
        for k, v in self.data.items():
            if msg.digests.get(k) != self._digest_for(k):
                to_send[k] = v
        # keys the peer has that we lack -> ask for exactly those back
        missing = tuple(k for k in msg.digests if k not in self.data)
        if to_send or missing:
            self._observe_gossip_bytes(to_send)
            self._replicator_at(msg.from_addr).tell(
                _Gossip(to_send, want_keys=missing, from_addr=self.self_addr,
                        tombstones=self._tombstones_wire(),
                        delta_seq=self._delta_seq_for(to_send),
                        origin_uid=self._delta_incarnation),
                self.self_ref)

    def _handle_gossip(self, msg: _Gossip) -> None:
        now = time.time()
        for k, removed in msg.tombstones.items():
            ts = self.pruned.setdefault(k, {})
            fresh = [n for n in removed if n not in ts]
            for n in removed:
                ts.setdefault(n, now)
            cur = self.data.get(k)
            if fresh and cur is not None and cur != DELETED:
                cleaned = self._cleanup_pruned(k, cur)
                if cleaned != cur:
                    self._set_data(k, cleaned, notify=False)
        for k, v in msg.entries.items():
            self._merge_in(k, v)
            if self.data.get(k) == DELETED:
                continue  # dead key: no cursor resync (see _merge_in prune)
            if k in msg.delta_seq and msg.origin_uid:
                # the full state covers every op of the sender up to this
                # seq: resync the delta cursor and resume op-based deltas
                # (duplicate re-application is safe — CRDT merges are
                # idempotent; only GAPS are dangerous)
                if self._origin_uid.get(msg.from_addr) != msg.origin_uid:
                    self._drop_delta_cursors(origin=msg.from_addr)
                    self._origin_uid[msg.from_addr] = msg.origin_uid
                pair = (msg.from_addr, msg.origin_uid, k)
                self._delta_seen[pair] = max(
                    self._delta_seen.get(pair, 0), msg.delta_seq[k])
                self._delta_gapped.discard(pair)
        if msg.want_keys:
            back = {k: self.data[k] for k in msg.want_keys if k in self.data}
            if back:
                self._observe_gossip_bytes(back)
                self._replicator_at(msg.from_addr).tell(
                    _Gossip(back, want_keys=(), from_addr=self.self_addr,
                            tombstones=self._tombstones_wire(),
                            delta_seq=self._delta_seq_for(back),
                            origin_uid=self._delta_incarnation),
                    self.self_ref)

    def _observe_gossip_bytes(self, entries: Dict[str, Any]) -> None:
        if self._h_gossip_bytes is None or not entries:
            return
        from ..serialization.codec import WireCodecError, dumps
        try:
            self._h_gossip_bytes.observe(float(len(dumps(entries))),
                                         step=self._metrics.step)
        except WireCodecError:
            pass

    def _delta_seq_for(self, entries: Dict[str, Any]) -> Dict[str, int]:
        return {k: self.delta_seq[k] for k in entries if k in self.delta_seq}

    def _drop_delta_cursors(self, origin: Optional[str] = None,
                            key: Optional[str] = None) -> None:
        """Prune delta bookkeeping: by origin (node removed / new
        incarnation) or by key (deleted) — the cursors must not grow with
        cluster/key churn."""
        def dead(pair) -> bool:
            return (origin is not None and pair[0] == origin) or \
                (key is not None and pair[2] == key)
        for pair in [p for p in self._delta_seen if dead(p)]:
            del self._delta_seen[pair]
        self._delta_gapped = {p for p in self._delta_gapped if not dead(p)}

    def _tombstones_wire(self) -> Dict[str, Tuple[str, ...]]:
        return {k: tuple(v) for k, v in self.pruned.items()}

    def _delta_tick(self) -> None:
        if not self.deltas:
            return
        nodes = self._nodes()
        if nodes:
            payload = {}
            for k, d in self.deltas.items():
                self.delta_seq[k] = self.delta_seq.get(k, 0) + 1
                payload[k] = (self.delta_seq[k], d)
            self._observe_delta_sizes(payload)
            for addr in nodes:
                self._replicator_at(addr).tell(
                    _DeltaPropagation(payload, self.self_addr,
                                      self._delta_incarnation), self.self_ref)
        self.deltas.clear()

    def _observe_delta_sizes(self, payload: Dict[str, Any]) -> None:
        """Per propagation tick: outbound payload bytes + each key's
        op-delta-size : full-state-size ratio (the O(entry) evidence)."""
        if self._h_gossip_bytes is None:
            return
        from ..serialization.codec import WireCodecError, dumps
        step = self._metrics.step
        try:
            self._h_gossip_bytes.observe(float(len(dumps(payload))), step=step)
            for k, (_seq, d) in payload.items():
                full = self.data.get(k)
                if full is None or full == DELETED:
                    continue
                full_n = len(dumps(full))
                if full_n:
                    self._h_delta_vs_full.observe(
                        len(dumps(d)) / full_n, step=step)
        except WireCodecError:
            pass  # unsized payloads must never break propagation

    # -- pruning (simplified leader-driven collapse) -------------------------
    def _prune_tick(self) -> None:
        self._expire_tombstones()
        state = self.cluster.state
        if state.leader is None or state.leader.address_str != self.self_addr:
            return
        if not self.removed_nodes:
            return
        now = time.time()
        for key, value in list(self.data.items()):
            if value == DELETED or not isinstance(value, RemovedNodePruning):
                continue
            # only ids of members the cluster actually removed are pruned —
            # never application-chosen logical CRDT node ids
            pruned_nodes = [n for n in self.removed_nodes
                            if value.needs_pruning_from(n)]
            if not pruned_nodes:
                continue
            for node in pruned_nodes:
                value = value.prune(node, self.self_unique)
            ts = self.pruned.setdefault(key, {})
            for node in pruned_nodes:
                ts[node] = now
            self._set_data(key, value)
            # disseminate: peers record the tombstone, clean their local
            # copy, and merge the collapsed state — stale gossip of the
            # removed node's entries is then filtered by _merge_in
            for addr in self._nodes():
                self._replicator_at(addr).tell(
                    _Pruned(key, tuple(pruned_nodes), value, self.self_addr),
                    self.self_ref)

    def _expire_tombstones(self) -> None:
        """Tombstones only need to outlive in-flight stale gossip; uid-based
        node ids cannot recur, so expiry after max_pruning_dissemination is
        safe and bounds tombstone growth (reference: PruningState obsoleting)."""
        deadline = time.time() - self.settings.max_pruning_dissemination
        for key in list(self.pruned):
            ts = self.pruned[key]
            for node in [n for n, t in ts.items() if t < deadline]:
                del ts[node]
            if not ts:
                del self.pruned[key]


# -- extension ---------------------------------------------------------------

class DistributedData(ExtensionId):
    """`DistributedData(system).replicator` (reference: DistributedData.scala)."""

    _instances: Dict[ActorSystem, "DistributedData"] = {}
    _lock = threading.Lock()

    def __init__(self, system: Optional[ActorSystem] = None):
        if system is not None:
            cfg = system.settings.config.get_config("akka.cluster.distributed-data")
            self.settings = ReplicatorSettings.from_config(cfg)
            # the id to pass as `node` to CRDT mutators (uid-qualified so a
            # restarted node is a fresh contributor, reference SelfUniqueAddress)
            self.self_unique_address = unique_node_id(
                Cluster.get(system).self_unique_address)
            self.replicator = system.system_actor_of(
                Props.create(Replicator, self.settings), "ddataReplicator")

    @staticmethod
    def get(system: ActorSystem) -> "DistributedData":
        with DistributedData._lock:
            inst = DistributedData._instances.get(system)
            if inst is None:
                inst = DistributedData._instances[system] = DistributedData(system)
                system.register_on_termination(
                    lambda: DistributedData._instances.pop(system, None))
            return inst
