"""Version vectors: causality tracking for CRDTs.

Reference parity: akka-distributed-data/src/main/scala/akka/cluster/ddata/
VersionVector.scala — node -> monotonically increasing counter; compare
yields Before / After / Same / Concurrent; `+` increments this node's entry;
merge is the pairwise max. The reference specialises One/ManyVersionVector
for allocation; here a single immutable dict-backed class suffices (the host
control plane is not the hot path — bulk CRDT merges ride the tensor kernels
in akka_tpu/ddata/tensor.py instead).
"""

from __future__ import annotations

import itertools
from enum import Enum
from typing import Dict, Iterable, Optional, Tuple


class Ordering(Enum):
    BEFORE = "Before"
    AFTER = "After"
    SAME = "Same"
    CONCURRENT = "Concurrent"


_counter = itertools.count(1)


class VersionVector:
    """Immutable version vector (reference: VersionVector.scala:73)."""

    __slots__ = ("versions",)

    def __init__(self, versions: Optional[Dict[str, int]] = None):
        object.__setattr__(self, "versions", dict(versions or {}))

    def __setattr__(self, *a):  # immutability guard
        raise AttributeError("VersionVector is immutable")

    def __getstate__(self):  # pickle despite the immutability guard
        return self.versions

    def __setstate__(self, state):
        object.__setattr__(self, "versions", state)

    @staticmethod
    def empty() -> "VersionVector":
        return _EMPTY

    @staticmethod
    def one(node: str, version: int) -> "VersionVector":
        return VersionVector({node: version})

    def is_empty(self) -> bool:
        return not self.versions

    def increment(self, node: str) -> "VersionVector":
        """`+`: bump `node`'s counter (reference uses a global monotonic
        timestamp to keep increments unique across merges; a per-node
        monotonic counter has the same causal properties)."""
        v = dict(self.versions)
        v[node] = max(v.get(node, 0), next(_counter))
        return VersionVector(v)

    def version_at(self, node: str) -> int:
        return self.versions.get(node, 0)

    def contains(self, node: str) -> bool:
        return node in self.versions

    def merge(self, other: "VersionVector") -> "VersionVector":
        v = dict(self.versions)
        for node, n in other.versions.items():
            if v.get(node, 0) < n:
                v[node] = n
        return VersionVector(v)

    def compare_to(self, other: "VersionVector") -> Ordering:
        lt = gt = False
        for node in set(self.versions) | set(other.versions):
            a, b = self.versions.get(node, 0), other.versions.get(node, 0)
            if a < b:
                lt = True
            elif a > b:
                gt = True
            if lt and gt:
                return Ordering.CONCURRENT
        if lt:
            return Ordering.BEFORE
        if gt:
            return Ordering.AFTER
        return Ordering.SAME

    def is_before(self, other: "VersionVector") -> bool:
        return self.compare_to(other) == Ordering.BEFORE

    def is_after(self, other: "VersionVector") -> bool:
        return self.compare_to(other) == Ordering.AFTER

    def is_same(self, other: "VersionVector") -> bool:
        return self.compare_to(other) == Ordering.SAME

    def is_concurrent(self, other: "VersionVector") -> bool:
        return self.compare_to(other) == Ordering.CONCURRENT

    def prune(self, removed: str, collapse_into: str) -> "VersionVector":
        """Move `removed`'s entry onto `collapse_into` (RemovedNodePruning)."""
        if removed not in self.versions:
            return self
        v = dict(self.versions)
        v.pop(removed)
        out = VersionVector(v)
        return out.increment(collapse_into)

    def needs_pruning_from(self, removed: str) -> bool:
        return removed in self.versions

    def nodes(self) -> Iterable[str]:
        return self.versions.keys()

    def __eq__(self, other):
        return isinstance(other, VersionVector) and self.versions == other.versions

    def __hash__(self):
        return hash(tuple(sorted(self.versions.items())))

    def __repr__(self):
        inner = ", ".join(f"{n} -> {v}" for n, v in sorted(self.versions.items()))
        return f"VersionVector({inner})"


_EMPTY = VersionVector()
