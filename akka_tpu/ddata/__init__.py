"""Replicated state (CRDTs): akka-distributed-data equivalent (SURVEY.md §2.7).

Host control plane: Replicator actor with Get/Update/Subscribe/Delete and
tunable consistency, gossip + delta propagation, durable keys. TPU data
plane: tensor CRDT banks whose merge is one elementwise op and whose
cluster-wide convergence is one mesh collective (akka_tpu/ddata/tensor.py).
"""

from .version_vector import Ordering, VersionVector  # noqa: F401
from .crdt import (DeltaReplicatedData, Flag, GCounter, GSet,  # noqa: F401
                   LWWMap, LWWRegister, ORMap, ORMultiMap, ORSet, PNCounter,
                   PNCounterMap, RemovedNodePruning, ReplicatedData)
from .durable import DurableStore  # noqa: F401
from .replicator import (Changed, DataDeleted, Delete, Deleted,  # noqa: F401
                         DeleteSuccess, DistributedData, Get, GetDataDeleted,
                         GetFailure, GetKeyIds, GetKeyIdsResult,
                         GetReplicaCount, GetSuccess, Key, ModifyFailure,
                         NotFound, ReadAll, ReadFrom, ReadLocal, ReadMajority,
                         ReplicaCount, ReplicationDeleteFailure, Replicator,
                         ReplicatorSettings, Subscribe, Unsubscribe, Update,
                         UpdateDataDeleted, UpdateSuccess, UpdateTimeout,
                         WriteAll, WriteLocal, WriteMajority, WriteTo)
from . import tensor  # noqa: F401

__all__ = [
    "VersionVector", "Ordering",
    "ReplicatedData", "DeltaReplicatedData", "RemovedNodePruning",
    "GCounter", "PNCounter", "GSet", "ORSet", "ORMap", "ORMultiMap",
    "PNCounterMap", "LWWMap", "LWWRegister", "Flag",
    "Replicator", "ReplicatorSettings", "DistributedData", "Key",
    "Get", "GetSuccess", "NotFound", "GetFailure", "GetDataDeleted",
    "Update", "UpdateSuccess", "UpdateTimeout", "ModifyFailure",
    "UpdateDataDeleted", "Delete", "DeleteSuccess", "DataDeleted",
    "ReplicationDeleteFailure", "Subscribe", "Unsubscribe", "Changed",
    "Deleted", "GetKeyIds", "GetKeyIdsResult", "GetReplicaCount",
    "ReplicaCount",
    "ReadLocal", "ReadFrom", "ReadMajority", "ReadAll",
    "WriteLocal", "WriteTo", "WriteMajority", "WriteAll",
    "DurableStore", "tensor",
]
