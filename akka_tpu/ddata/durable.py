"""Durable store for ddata keys that must survive node restart.

Reference parity: akka-distributed-data/src/main/scala/akka/cluster/ddata/
DurableStore.scala — the reference uses LMDB; here a write-behind pickle-per-
key directory (no LMDB in the image; the access pattern — whole-value
store/load keyed by string — is identical). File name is the hex SHA1 of the
key so arbitrary key ids are path-safe.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import tempfile
from typing import Any, Dict


class DurableStore:
    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.dir, hashlib.sha1(key.encode()).hexdigest() + ".ddata")

    def store(self, key: str, data: Any) -> None:
        # atomic replace so a crash mid-write never corrupts the entry
        fd, tmp = tempfile.mkstemp(dir=self.dir, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                pickle.dump((key, data), f, protocol=4)
            os.replace(tmp, self._path(key))
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def load_all(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for name in os.listdir(self.dir):
            if not name.endswith(".ddata"):
                continue
            try:
                with open(os.path.join(self.dir, name), "rb") as f:
                    key, data = pickle.load(f)
                out[key] = data
            except (OSError, pickle.PickleError, EOFError):
                continue
        return out

    def delete(self, key: str) -> None:
        try:
            os.unlink(self._path(key))
        except OSError:
            pass
