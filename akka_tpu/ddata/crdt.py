"""The CRDT library: state-based convergent replicated data types.

Reference parity (akka-distributed-data/src/main/scala/akka/cluster/ddata/):
GCounter.scala, PNCounter.scala, GSet.scala, ORSet.scala (add-wins via
per-element "dots" = version vectors), ORMap.scala, ORMultiMap.scala,
PNCounterMap.scala, LWWMap.scala, LWWRegister.scala, Flag.scala.

Contracts (reference: ReplicatedData.scala):
- `merge(other)` is commutative, associative, idempotent.
- DeltaReplicatedData additionally accumulates a `delta` between replicator
  ticks (`delta`, `reset_delta`, `merge_delta`) so gossip can ship small
  updates (delta-CRDT, Replicator.scala:98-99, DeltaPropagationSelector.scala).
- RemovedNodePruning lets the leader collapse a removed node's contributions
  into a surviving node (`needs_pruning_from`, `prune`).

Mutators take a `node` (the SelfUniqueAddress string) exactly like the
reference's implicit `SelfUniqueAddress`.

Tensor note: GCounter/PNCounter merge is elementwise max over per-node rows —
the psum-shaped bulk form lives in akka_tpu/ddata/tensor.py; these host types
are the unit of the Replicator control plane.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, FrozenSet, Generic, Optional, Tuple, TypeVar

from .version_vector import Ordering, VersionVector

A = TypeVar("A")


class ReplicatedData:
    """Base marker (reference: ReplicatedData.scala)."""

    def merge(self, other: "ReplicatedData") -> "ReplicatedData":
        raise NotImplementedError


class DeltaReplicatedData(ReplicatedData):
    @property
    def delta(self) -> Optional[ReplicatedData]:
        return None

    def reset_delta(self) -> "DeltaReplicatedData":
        return self

    def merge_delta(self, delta: ReplicatedData) -> "DeltaReplicatedData":
        return self.merge(delta)  # full-state deltas by default


class RemovedNodePruning:
    def modified_by_nodes(self) -> FrozenSet[str]:
        raise NotImplementedError

    def needs_pruning_from(self, removed: str) -> bool:
        return removed in self.modified_by_nodes()

    def prune(self, removed: str, collapse_into: str) -> "ReplicatedData":
        raise NotImplementedError

    def prune_cleanup(self, removed: str) -> "ReplicatedData":
        """Drop any residual entries for `removed` (post-prune gossip races)."""
        return self  # type: ignore[return-value]


# -- counters ---------------------------------------------------------------


class GCounter(DeltaReplicatedData, RemovedNodePruning):
    """Grow-only counter: node -> count, merge = pairwise max, value = sum
    (reference: GCounter.scala)."""

    __slots__ = ("state", "_delta")

    def __init__(self, state: Optional[Dict[str, int]] = None,
                 _delta: Optional["GCounter"] = None):
        self.state = dict(state or {})
        self._delta = _delta

    @staticmethod
    def empty() -> "GCounter":
        return GCounter()

    @property
    def value(self) -> int:
        return sum(self.state.values())

    def increment(self, node: str, n: int = 1) -> "GCounter":
        if n < 0:
            raise ValueError("GCounter can't decrement")
        if n == 0:
            return self
        new = dict(self.state)
        new[node] = new.get(node, 0) + n
        delta_state = {node: new[node]}
        d = GCounter(delta_state) if self._delta is None else \
            GCounter({**self._delta.state, **delta_state})
        return GCounter(new, d)

    def merge(self, other: "GCounter") -> "GCounter":
        new = dict(self.state)
        for k, v in other.state.items():
            if new.get(k, 0) < v:
                new[k] = v
        return GCounter(new, self._delta)

    @property
    def delta(self) -> Optional["GCounter"]:
        return self._delta

    def reset_delta(self) -> "GCounter":
        return GCounter(self.state)

    def merge_delta(self, delta: "GCounter") -> "GCounter":
        return self.merge(delta)

    def modified_by_nodes(self) -> FrozenSet[str]:
        return frozenset(self.state)

    def prune(self, removed: str, collapse_into: str) -> "GCounter":
        c = self.state.get(removed)
        if c is None:
            return self
        new = dict(self.state)
        del new[removed]
        new[collapse_into] = new.get(collapse_into, 0) + c
        return GCounter(new)

    def prune_cleanup(self, removed: str) -> "GCounter":
        if removed not in self.state:
            return self
        new = dict(self.state)
        del new[removed]
        return GCounter(new)

    def __eq__(self, other):
        return isinstance(other, GCounter) and self.state == other.state

    def __hash__(self):
        return hash(tuple(sorted(self.state.items())))

    def __repr__(self):
        return f"GCounter({self.value})"


class PNCounter(DeltaReplicatedData, RemovedNodePruning):
    """Increment+decrement counter = two GCounters (reference: PNCounter.scala)."""

    __slots__ = ("increments", "decrements")

    def __init__(self, increments: Optional[GCounter] = None,
                 decrements: Optional[GCounter] = None):
        self.increments = increments or GCounter()
        self.decrements = decrements or GCounter()

    @staticmethod
    def empty() -> "PNCounter":
        return PNCounter()

    @property
    def value(self) -> int:
        return self.increments.value - self.decrements.value

    def increment(self, node: str, n: int = 1) -> "PNCounter":
        if n < 0:
            return self.decrement(node, -n)
        return PNCounter(self.increments.increment(node, n), self.decrements)

    def decrement(self, node: str, n: int = 1) -> "PNCounter":
        if n < 0:
            return self.increment(node, -n)
        return PNCounter(self.increments, self.decrements.increment(node, n))

    def merge(self, other: "PNCounter") -> "PNCounter":
        return PNCounter(self.increments.merge(other.increments),
                         self.decrements.merge(other.decrements))

    @property
    def delta(self) -> Optional["PNCounter"]:
        di, dd = self.increments.delta, self.decrements.delta
        if di is None and dd is None:
            return None
        return PNCounter(di or GCounter(), dd or GCounter())

    def reset_delta(self) -> "PNCounter":
        return PNCounter(self.increments.reset_delta(),
                         self.decrements.reset_delta())

    def merge_delta(self, delta: "PNCounter") -> "PNCounter":
        return self.merge(delta)

    def modified_by_nodes(self) -> FrozenSet[str]:
        return self.increments.modified_by_nodes() | self.decrements.modified_by_nodes()

    def prune(self, removed: str, collapse_into: str) -> "PNCounter":
        return PNCounter(self.increments.prune(removed, collapse_into),
                         self.decrements.prune(removed, collapse_into))

    def prune_cleanup(self, removed: str) -> "PNCounter":
        return PNCounter(self.increments.prune_cleanup(removed),
                         self.decrements.prune_cleanup(removed))

    def __eq__(self, other):
        return (isinstance(other, PNCounter)
                and self.increments == other.increments
                and self.decrements == other.decrements)

    def __hash__(self):
        return hash((self.increments, self.decrements))

    def __repr__(self):
        return f"PNCounter({self.value})"


# -- sets -------------------------------------------------------------------


class GSet(DeltaReplicatedData, Generic[A]):
    """Grow-only set; merge = union (reference: GSet.scala)."""

    __slots__ = ("elements", "_delta")

    def __init__(self, elements: Optional[FrozenSet[A]] = None,
                 _delta: Optional["GSet[A]"] = None):
        self.elements: FrozenSet[A] = frozenset(elements or ())
        self._delta = _delta

    @staticmethod
    def empty() -> "GSet":
        return GSet()

    def contains(self, e: A) -> bool:
        return e in self.elements

    def __contains__(self, e: A) -> bool:
        return e in self.elements

    def add(self, e: A) -> "GSet[A]":
        d = GSet(frozenset({e}) | (self._delta.elements if self._delta else frozenset()))
        return GSet(self.elements | {e}, d)

    def merge(self, other: "GSet[A]") -> "GSet[A]":
        return GSet(self.elements | other.elements, self._delta)

    @property
    def delta(self) -> Optional["GSet[A]"]:
        return self._delta

    def reset_delta(self) -> "GSet[A]":
        return GSet(self.elements)

    def merge_delta(self, delta: "GSet[A]") -> "GSet[A]":
        return self.merge(delta)

    def __eq__(self, other):
        return isinstance(other, GSet) and self.elements == other.elements

    def __hash__(self):
        return hash(self.elements)

    def __repr__(self):
        return f"GSet({set(self.elements)!r})"


class ORSetDeltaOp:
    """Op-based ORSet delta algebra (reference: ORSet.scala:55-110
    AddDeltaOp/RemoveDeltaOp/FullStateDeltaOp/DeltaGroup): an update ships
    only the touched element + its dot, not the whole set. Ops merge into
    groups between propagation ticks; consecutive same-node adds coalesce."""

    __slots__ = ()

    def zero(self) -> "ORSet":
        """Empty full state to apply a delta against on a replica that has
        never seen the key (reference: ReplicatedDelta.zero)."""
        return ORSet()

    def merge(self, that: "ORSetDeltaOp") -> "ORSetDeltaOp":
        if isinstance(that, ORSetDeltaGroup):
            return ORSetDeltaGroup((self,) + that.ops)
        return ORSetDeltaGroup((self, that))

    def __eq__(self, other):
        return type(self) is type(other) and \
            self.underlying == other.underlying  # type: ignore[attr-defined]

    def __hash__(self):
        return hash((type(self).__name__,
                     self.underlying))  # type: ignore[attr-defined]


class ORSetAddDeltaOp(ORSetDeltaOp):
    """underlying: ONLY the added element(s) with their fresh dots; its
    vvector is just those dots (tiny on the wire)."""

    __slots__ = ("underlying",)

    def __init__(self, underlying: "ORSet"):
        self.underlying = underlying

    def merge(self, that: ORSetDeltaOp) -> ORSetDeltaOp:
        if isinstance(that, ORSetAddDeltaOp):
            # consecutive adds from the SAME node coalesce into one op
            new_map = dict(self.underlying.element_map)
            new_map.update(that.underlying.element_map)
            return ORSetAddDeltaOp(ORSet(
                new_map,
                self.underlying.vvector.merge(that.underlying.vvector)))
        return super().merge(that)


class ORSetRemoveDeltaOp(ORSetDeltaOp):
    """underlying: exactly ONE removed element with the remover's dot; its
    vvector is the remover's FULL causal context (the remove only wins over
    adds it observed)."""

    __slots__ = ("underlying",)

    def __init__(self, underlying: "ORSet"):
        if len(underlying.element_map) != 1:
            raise ValueError(
                f"RemoveDeltaOp must contain one removed element, "
                f"got {len(underlying.element_map)}")
        self.underlying = underlying


class ORSetFullStateDeltaOp(ORSetDeltaOp):
    """Fallback op carrying full state (clear(), and mixed histories)."""

    __slots__ = ("underlying",)

    def __init__(self, underlying: "ORSet"):
        self.underlying = underlying


class ORSetDeltaGroup(ORSetDeltaOp):
    """Ordered batch of atomic ops between propagation ticks."""

    __slots__ = ("ops",)

    def __init__(self, ops):
        self.ops = tuple(ops)

    def merge(self, that: ORSetDeltaOp) -> ORSetDeltaOp:
        if isinstance(that, ORSetAddDeltaOp) and self.ops and \
                isinstance(self.ops[-1], ORSetAddDeltaOp):
            return ORSetDeltaGroup(
                self.ops[:-1] + (self.ops[-1].merge(that),))
        if isinstance(that, ORSetDeltaGroup):
            return ORSetDeltaGroup(self.ops + that.ops)
        return ORSetDeltaGroup(self.ops + (that,))

    def __eq__(self, other):
        return isinstance(other, ORSetDeltaGroup) and self.ops == other.ops

    def __hash__(self):
        return hash(self.ops)


class ORSet(DeltaReplicatedData, RemovedNodePruning, Generic[A]):
    """Observed-remove set, add-wins on concurrent add/remove.

    Reference: ORSet.scala — element -> "dot" (a VersionVector naming the
    add events observed for that element) plus a set-level version vector
    `vvector` that records every event the whole set has seen. Merge keeps
    an element present on one side iff its dot is NOT dominated by the other
    side's vvector (i.e. the other side saw the add and deleted it).
    Deltas are OP-BASED (r5; previously full-state): add ships only the
    element + fresh dot, remove ships the element + the remover's causal
    context, clear ships full state — the AddDeltaOp/RemoveDeltaOp/
    FullStateDeltaOp/DeltaGroup algebra of ORSet.scala:55-110,334-410.
    """

    __slots__ = ("element_map", "vvector", "_delta")

    def __init__(self, element_map: Optional[Dict[A, VersionVector]] = None,
                 vvector: Optional[VersionVector] = None,
                 _delta: Optional["ORSet[A]"] = None):
        self.element_map: Dict[A, VersionVector] = dict(element_map or {})
        self.vvector = vvector or VersionVector.empty()
        self._delta = _delta

    @staticmethod
    def empty() -> "ORSet":
        return ORSet()

    @property
    def elements(self) -> FrozenSet[A]:
        return frozenset(self.element_map)

    def contains(self, e: A) -> bool:
        return e in self.element_map

    def __contains__(self, e: A) -> bool:
        return e in self.element_map

    def _push_delta(self, op: ORSetDeltaOp) -> ORSetDeltaOp:
        return op if self._delta is None else self._delta.merge(op)

    def add(self, node: str, e: A) -> "ORSet[A]":
        vv = self.vvector.increment(node)
        dot = VersionVector.one(node, vv.version_at(node))
        new = dict(self.element_map)
        new[e] = dot  # fresh dot replaces observed history for e (ORSet.scala add)
        op = ORSetAddDeltaOp(ORSet({e: dot}, dot))
        return ORSet(new, vv, _delta=self._push_delta(op))

    def remove(self, node: str, e: A) -> "ORSet[A]":
        new = dict(self.element_map)
        new.pop(e, None)
        # the op carries the remover's FULL causal context so the remove
        # wins exactly over the adds it observed (ORSet.scala:382)
        delta_dot = VersionVector.one(node, self.vvector.version_at(node))
        op = ORSetRemoveDeltaOp(ORSet({e: delta_dot}, self.vvector))
        return ORSet(new, self.vvector, _delta=self._push_delta(op))

    def clear(self) -> "ORSet[A]":
        op = ORSetFullStateDeltaOp(ORSet({}, self.vvector))
        return ORSet({}, self.vvector, _delta=self._push_delta(op))

    @staticmethod
    def _merge_dots(d1: VersionVector, d2: VersionVector) -> VersionVector:
        return d1.merge(d2)

    def merge(self, other: "ORSet[A]") -> "ORSet[A]":
        return self._dry_merge(other, add_delta=False)

    def _dry_merge(self, other: "ORSet[A]", add_delta: bool) -> "ORSet[A]":
        """Full merge; with add_delta=True, THIS side's unique elements are
        kept unconditionally — an AddDeltaOp's tiny vvector records only
        the new dots, so checking our elements against it would wrongly
        delete everything it has not seen (ORSet.scala:434-453 dryMerge)."""
        merged: Dict[A, VersionVector] = {}
        for e in set(self.element_map) | set(other.element_map):
            mine, theirs = self.element_map.get(e), other.element_map.get(e)
            if mine is not None and theirs is not None:
                merged[e] = self._merge_dots(mine, theirs)
            elif mine is not None:
                # present only here: keep iff other hasn't observed (and
                # hence removed) every event in the dot
                if add_delta or not self._dominated(mine, other.vvector):
                    merged[e] = mine
            else:
                if not self._dominated(theirs, self.vvector):  # type: ignore[arg-type]
                    merged[e] = theirs  # type: ignore[assignment]
        return ORSet(merged, self.vvector.merge(other.vvector), self._delta)

    @staticmethod
    def _dominated(dot: VersionVector, vv: VersionVector) -> bool:
        return all(vv.version_at(n) >= dot.version_at(n) for n in dot.nodes())

    @property
    def delta(self) -> Optional["ORSet[A]"]:
        return self._delta

    def reset_delta(self) -> "ORSet[A]":
        return ORSet(self.element_map, self.vvector)

    def merge_delta(self, delta) -> "ORSet[A]":
        """Apply an op-based delta (ORSet.scala:455-469 mergeDelta); a
        plain ORSet (pre-r5 full-state delta) still full-merges."""
        if isinstance(delta, ORSetAddDeltaOp):
            return self._dry_merge(delta.underlying, add_delta=True)
        if isinstance(delta, ORSetRemoveDeltaOp):
            return self._merge_remove_delta(delta)
        if isinstance(delta, ORSetFullStateDeltaOp):
            return self._dry_merge(delta.underlying, add_delta=False)
        if isinstance(delta, ORSetDeltaGroup):
            acc = self
            for op in delta.ops:
                if isinstance(op, ORSetDeltaGroup):
                    raise ValueError("ORSet DeltaGroup must not be nested")
                acc = acc.merge_delta(op)
            return acc
        return self.merge(delta)

    def _merge_remove_delta(self, delta: ORSetRemoveDeltaOp) -> "ORSet[A]":
        """(reference: ORSet.scala:471-501 mergeRemoveDelta) — drop the
        element iff the remover's causal context covers every add event in
        OUR dot for it; always merge the remover's dot into the vvector so
        the removal event itself is recorded."""
        that = delta.underlying
        (elem, that_dot), = that.element_map.items()
        new = dict(self.element_map)
        mine = new.get(elem)
        # drop iff OUR dot is dominated by the remover's causal context —
        # the canonical domination predicate (a node of ours absent from
        # the context makes it false, i.e. a concurrent unseen add wins)
        if mine is not None and self._dominated(mine, that.vvector):
            del new[elem]
        return ORSet(new, self.vvector.merge(that_dot), self._delta)

    def modified_by_nodes(self) -> FrozenSet[str]:
        return frozenset(self.vvector.nodes())

    def prune(self, removed: str, collapse_into: str) -> "ORSet[A]":
        new: Dict[A, VersionVector] = {}
        for e, dot in self.element_map.items():
            new[e] = dot.prune(removed, collapse_into) if dot.contains(removed) else dot
        return ORSet(new, self.vvector.prune(removed, collapse_into))

    def prune_cleanup(self, removed: str) -> "ORSet[A]":
        """Drop `removed` from the vvector and every dot (stale replicas
        gossiping after the prune). Elements whose only add events came from
        `removed` are dropped too — the pruned copy carries them re-dotted
        under the collapse target, so the merge restores them."""
        if removed not in self.vvector.nodes() and not any(
                dot.contains(removed) for dot in self.element_map.values()):
            return self
        new: Dict[A, VersionVector] = {}
        for e, dot in self.element_map.items():
            if dot.contains(removed):
                cleaned = VersionVector({n: v for n, v in dot.versions.items()
                                         if n != removed})
                if not cleaned.is_empty():
                    new[e] = cleaned
            else:
                new[e] = dot
        vv = VersionVector({n: v for n, v in self.vvector.versions.items()
                            if n != removed})
        return ORSet(new, vv)

    def __eq__(self, other):
        return (isinstance(other, ORSet)
                and self.element_map == other.element_map
                and self.vvector == other.vvector)

    def __hash__(self):
        return hash((frozenset(self.element_map.items()), self.vvector))

    def __repr__(self):
        return f"ORSet({set(self.element_map)!r})"


# -- registers & flag -------------------------------------------------------


class Flag(ReplicatedData):
    """Boolean that can only go False -> True (reference: Flag.scala)."""

    __slots__ = ("enabled",)

    def __init__(self, enabled: bool = False):
        self.enabled = enabled

    @staticmethod
    def empty() -> "Flag":
        return Flag(False)

    def switch_on(self) -> "Flag":
        return Flag(True)

    def merge(self, other: "Flag") -> "Flag":
        return Flag(self.enabled or other.enabled)

    def __eq__(self, other):
        return isinstance(other, Flag) and self.enabled == other.enabled

    def __hash__(self):
        return hash(self.enabled)

    def __repr__(self):
        return f"Flag({self.enabled})"


class LWWRegister(ReplicatedData, Generic[A]):
    """Last-writer-wins register (reference: LWWRegister.scala — timestamp
    with node-id tiebreak; pluggable clock for e.g. monotonically increasing
    version semantics)."""

    __slots__ = ("node", "value", "timestamp")

    DefaultClock: Callable[[int, Any], int] = staticmethod(
        lambda current, _value: max(int(time.time() * 1e6), current + 1))

    def __init__(self, node: str, value: A, timestamp: int):
        self.node = node
        self.value = value
        self.timestamp = timestamp

    @staticmethod
    def create(node: str, value: A,
               clock: Optional[Callable[[int, Any], int]] = None) -> "LWWRegister[A]":
        clock = clock or LWWRegister.DefaultClock
        return LWWRegister(node, value, clock(0, value))

    def with_value(self, node: str, value: A,
                   clock: Optional[Callable[[int, Any], int]] = None) -> "LWWRegister[A]":
        clock = clock or LWWRegister.DefaultClock
        return LWWRegister(node, value, clock(self.timestamp, value))

    def merge(self, other: "LWWRegister[A]") -> "LWWRegister[A]":
        if other.timestamp > self.timestamp:
            return other
        if other.timestamp == self.timestamp and other.node < self.node:
            return other
        return self

    def __eq__(self, other):
        return (isinstance(other, LWWRegister) and self.node == other.node
                and self.value == other.value and self.timestamp == other.timestamp)

    def __hash__(self):
        return hash((self.node, self.timestamp))

    def __repr__(self):
        return f"LWWRegister({self.value!r} @ {self.timestamp} by {self.node})"


# -- maps -------------------------------------------------------------------


class ORMapDeltaOp:
    """Op-based ORMap delta algebra (reference: ORMap.scala:30-110
    PutDeltaOp/UpdateDeltaOp/RemoveDeltaOp/RemoveKeyDeltaOp/DeltaGroup):
    a 1-entry change ships one key op + one entry, not the whole map.

    Every op carries a `zero_tag` — the TOP-LEVEL map class (ORMap or a
    derived wrapper) — so a replica that has never seen the key can
    reconstruct the right type from nothing: `op.zero().merge_delta(op)`
    (reference: ZeroTag.scala, the replicator's first-sight path)."""

    __slots__ = ()

    def zero(self) -> "ReplicatedData":
        return self.zero_tag.empty()  # type: ignore[attr-defined]

    def merge(self, that: "ORMapDeltaOp") -> "ORMapDeltaOp":
        if isinstance(that, ORMapDeltaGroup):
            return ORMapDeltaGroup((self,) + that.ops)
        return ORMapDeltaGroup((self, that))


class ORMapPutDeltaOp(ORMapDeltaOp):
    """Destructive entry write: ships the key's ORSet add op + the FULL
    value (put replaces; only `updated` ships value deltas)."""

    __slots__ = ("key_op", "key", "value", "zero_tag")

    def __init__(self, key_op: ORSetDeltaOp, key, value: ReplicatedData,
                 zero_tag: type):
        self.key_op = key_op
        self.key = key
        self.value = value
        self.zero_tag = zero_tag

    def merge(self, that: ORMapDeltaOp) -> ORMapDeltaOp:
        if isinstance(that, ORMapPutDeltaOp) and that.key == self.key:
            # a later put of the SAME key supersedes within the tick
            return ORMapPutDeltaOp(self.key_op.merge(that.key_op),
                                   self.key, that.value, self.zero_tag)
        return super().merge(that)

    def __eq__(self, other):
        return (isinstance(other, ORMapPutDeltaOp)
                and self.key_op == other.key_op and self.key == other.key
                and self.value == other.value
                and self.zero_tag is other.zero_tag)

    def __hash__(self):
        return hash(("put", self.key_op, self.key))


class ORMapUpdateDeltaOp(ORMapDeltaOp):
    """In-place entry update: ships the key's ORSet add op + the value's
    own DELTA per key (a counter increment rides as {node: count}, an
    ORSet binding as one AddDeltaOp — O(entry), never O(map)). Falls back
    to the full value for non-delta value types; `merge_delta` tells the
    two apart by type. Consecutive updates between propagation ticks
    coalesce: key ops merge, per-key value deltas merge."""

    __slots__ = ("key_op", "values", "zero_tag")

    def __init__(self, key_op: ORSetDeltaOp, values: Dict[Any, Any],
                 zero_tag: type):
        self.key_op = key_op
        self.values = dict(values)
        self.zero_tag = zero_tag

    def merge(self, that: ORMapDeltaOp) -> ORMapDeltaOp:
        if isinstance(that, ORMapUpdateDeltaOp) \
                and that.zero_tag is self.zero_tag:
            vals = dict(self.values)
            for k, d in that.values.items():
                cur = vals.get(k)
                vals[k] = d if cur is None else cur.merge(d)
            return ORMapUpdateDeltaOp(self.key_op.merge(that.key_op),
                                      vals, self.zero_tag)
        return super().merge(that)

    def __eq__(self, other):
        return (isinstance(other, ORMapUpdateDeltaOp)
                and self.key_op == other.key_op
                and self.values == other.values
                and self.zero_tag is other.zero_tag)

    def __hash__(self):
        return hash(("update", self.key_op, frozenset(self.values)))


class ORMapRemoveDeltaOp(ORMapDeltaOp):
    """Key removal dropping the value: ships the key's ORSet remove op
    (one element + the remover's causal context). The entry disappears
    with the key; value types that need their causal context preserved
    across a remove (ORMultiMap) use RemoveKeyDeltaOp instead."""

    __slots__ = ("key_op", "key", "zero_tag")

    def __init__(self, key_op: ORSetDeltaOp, key, zero_tag: type):
        self.key_op = key_op
        self.key = key
        self.zero_tag = zero_tag

    def __eq__(self, other):
        return (isinstance(other, ORMapRemoveDeltaOp)
                and self.key_op == other.key_op and self.key == other.key
                and self.zero_tag is other.zero_tag)

    def __hash__(self):
        return hash(("remove", self.key_op, self.key))


class ORMapRemoveKeyDeltaOp(ORMapDeltaOp):
    """Key removal RETAINING the value as a tombstone (reference:
    ORMap.scala RemoveKeyDeltaOp): the cleared value keeps its causal
    context so a concurrent binding update converges instead of
    resurrecting removed elements — the ORMultiMap remove path clears
    the set (a value delta) then removes the key with this op."""

    __slots__ = ("key_op", "key", "zero_tag")

    def __init__(self, key_op: ORSetDeltaOp, key, zero_tag: type):
        self.key_op = key_op
        self.key = key
        self.zero_tag = zero_tag

    def __eq__(self, other):
        return (isinstance(other, ORMapRemoveKeyDeltaOp)
                and self.key_op == other.key_op and self.key == other.key
                and self.zero_tag is other.zero_tag)

    def __hash__(self):
        return hash(("remove_key", self.key_op, self.key))


class ORMapDeltaGroup(ORMapDeltaOp):
    """Ordered batch of atomic ops between propagation ticks; an incoming
    op first tries to coalesce with the trailing op."""

    __slots__ = ("ops",)

    def __init__(self, ops):
        self.ops = tuple(ops)

    @property
    def zero_tag(self) -> type:
        return self.ops[0].zero_tag  # type: ignore[attr-defined]

    def merge(self, that: ORMapDeltaOp) -> ORMapDeltaOp:
        if isinstance(that, ORMapDeltaGroup):
            return ORMapDeltaGroup(self.ops + that.ops)
        if self.ops:
            tail = self.ops[-1].merge(that)
            if not isinstance(tail, ORMapDeltaGroup):
                return ORMapDeltaGroup(self.ops[:-1] + (tail,))
        return ORMapDeltaGroup(self.ops + (that,))

    def __eq__(self, other):
        return isinstance(other, ORMapDeltaGroup) and self.ops == other.ops

    def __hash__(self):
        return hash(self.ops)


class ORMap(DeltaReplicatedData, RemovedNodePruning, Generic[A]):
    """Observed-remove map: ORSet of keys + per-key ReplicatedData values
    merged recursively (reference: ORMap.scala).

    Deltas are OP-BASED (previously full-state snapshots): put/updated/
    remove emit Put/Update/Remove/RemoveKey ops carrying one key op and
    one entry (or just the entry's own delta), the DeltaGroup algebra of
    ORMap.scala:30-110 with zero-tag reconstruction for replicas that
    have never seen the key and the causal guard on update application
    (a value delta only applies if its key survived the key-set merge).

    Known reference anomaly, kept for parity: a remove() concurrent with
    an updated() of the SAME key can transiently differ between the op
    path (the update's value delta resurrects the entry from zero) and
    the full-merge path; full-state gossip reconciles. Value types whose
    causal context must survive a remove use remove_key() tombstones —
    ORMultiMap does (clear-then-remove_key, merge retaining deleted
    values); PNCounterMap/LWWMap accept the documented anomaly."""

    __slots__ = ("keys", "entries", "_delta")

    def __init__(self, keys: Optional[ORSet] = None,
                 entries: Optional[Dict[Any, ReplicatedData]] = None,
                 _delta: Optional["ORMap"] = None):
        self.keys = keys or ORSet()
        self.entries: Dict[Any, ReplicatedData] = dict(entries or {})
        self._delta = _delta

    @staticmethod
    def empty() -> "ORMap":
        return ORMap()

    def get(self, key) -> Optional[ReplicatedData]:
        return self.entries.get(key)

    def contains(self, key) -> bool:
        return key in self.entries

    def __contains__(self, key) -> bool:
        return key in self.entries

    def _push_delta(self, op: ORMapDeltaOp) -> ORMapDeltaOp:
        return op if self._delta is None else self._delta.merge(op)

    def _key_add_op(self, node: str, key) -> Tuple[ORSet, ORSetDeltaOp]:
        """One key-set add as (new reset keys, the ORSet op it emitted)."""
        nk = self.keys.reset_delta().add(node, key)
        return nk.reset_delta(), nk.delta  # type: ignore[return-value]

    def put(self, node: str, key, value: ReplicatedData,
            _tag: Optional[type] = None) -> "ORMap":
        new_keys, key_op = self._key_add_op(node, key)
        entries = dict(self.entries)
        entries[key] = value
        op = ORMapPutDeltaOp(key_op, key, value, _tag or ORMap)
        return ORMap(new_keys, entries, _delta=self._push_delta(op))

    def updated(self, node: str, key, initial: ReplicatedData,
                modify: Callable[[ReplicatedData], ReplicatedData],
                _tag: Optional[type] = None) -> "ORMap":
        tag = _tag or ORMap
        new_keys, key_op = self._key_add_op(node, key)
        cur = self.entries.get(key, initial)
        entries = dict(self.entries)
        if isinstance(cur, DeltaReplicatedData):
            # ship the value's OWN delta (reference: valueDeltas branch of
            # ORMap.updated) — a counter increment gossips {node: count}
            new_val = modify(cur.reset_delta())
            vd = new_val.delta \
                if isinstance(new_val, DeltaReplicatedData) else None
            if vd is not None:
                op: ORMapDeltaOp = ORMapUpdateDeltaOp(key_op, {key: vd}, tag)
                entries[key] = new_val.reset_delta()
            else:  # modify produced no delta: ship the full value
                op = ORMapPutDeltaOp(key_op, key, new_val, tag)
                entries[key] = new_val
        else:
            new_val = modify(cur)
            op = ORMapUpdateDeltaOp(key_op, {key: new_val}, tag)
            entries[key] = new_val
        return ORMap(new_keys, entries, _delta=self._push_delta(op))

    def remove(self, node: str, key, _tag: Optional[type] = None) -> "ORMap":
        nk = self.keys.reset_delta().remove(node, key)
        entries = dict(self.entries)
        entries.pop(key, None)
        op = ORMapRemoveDeltaOp(nk.delta, key,  # type: ignore[arg-type]
                                _tag or ORMap)
        return ORMap(nk.reset_delta(), entries, _delta=self._push_delta(op))

    def remove_key(self, node: str, key,
                   _tag: Optional[type] = None) -> "ORMap":
        """Remove the key but KEEP its value as a tombstone (reference:
        ORMap.removeKey) — the ORMultiMap clear-then-remove path, so the
        value's causal context survives for concurrent binding updates."""
        nk = self.keys.reset_delta().remove(node, key)
        op = ORMapRemoveKeyDeltaOp(nk.delta, key,  # type: ignore[arg-type]
                                   _tag or ORMap)
        return ORMap(nk.reset_delta(), self.entries,
                     _delta=self._push_delta(op))

    def merge(self, other: "ORMap") -> "ORMap":
        return self._merge(other, retain_deleted=False)

    def merge_retaining_deleted_values(self, other: "ORMap") -> "ORMap":
        """(reference: ORMap.mergeRetainingDeletedValues) — tombstone
        entries whose keys left the key set survive the merge; the
        ORMultiMap merge path."""
        return self._merge(other, retain_deleted=True)

    def _merge(self, other: "ORMap", retain_deleted: bool) -> "ORMap":
        merged_keys = self.keys.merge(other.keys)
        keep = set(merged_keys.element_map)
        if retain_deleted:
            keep |= set(self.entries) | set(other.entries)
        entries: Dict[Any, ReplicatedData] = {}
        for key in keep:
            mine, theirs = self.entries.get(key), other.entries.get(key)
            if mine is not None and theirs is not None:
                entries[key] = mine.merge(theirs)
            elif mine is not None:
                entries[key] = mine
            elif theirs is not None:
                entries[key] = theirs
        return ORMap(merged_keys, entries, self._delta)

    @property
    def delta(self) -> Optional[ORMapDeltaOp]:
        return self._delta

    def reset_delta(self) -> "ORMap":
        return ORMap(self.keys.reset_delta(), self.entries)

    def merge_delta(self, delta) -> "ORMap":
        """Apply an op-based delta (reference: ORMap.mergeDelta /
        dryMergeDelta); a plain ORMap (legacy full-state delta) still
        full-merges."""
        if isinstance(delta, ORMapDeltaOp):
            return self._dry_merge_delta(delta, retain_deleted=False)
        return self.merge(delta)

    def merge_delta_retaining_deleted_values(self, delta) -> "ORMap":
        if isinstance(delta, ORMapDeltaOp):
            return self._dry_merge_delta(delta, retain_deleted=True)
        return self.merge_retaining_deleted_values(delta)

    def _dry_merge_delta(self, delta: ORMapDeltaOp,
                         retain_deleted: bool) -> "ORMap":
        """The op fold (reference: ORMap.dryMergeDelta): ops build a
        side-map of values which then FULL-MERGES with the local entries
        per key — so concurrent puts converge commutatively (register
        merge picks the winner) instead of diverging by application
        order. Update values apply under the causal guard: a value delta
        lands only if its key survived the key-set merge (an add our
        vvector already observed-and-removed stays removed)."""
        ops = delta.ops if isinstance(delta, ORMapDeltaGroup) else (delta,)
        merged_keys = self.keys
        merged_values: Dict[Any, Any] = {}
        tombstoned: Dict[Any, ReplicatedData] = {}
        for op in ops:
            if isinstance(op, ORMapDeltaGroup):
                raise ValueError("ORMap DeltaGroup must not be nested")
            if isinstance(op, ORMapPutDeltaOp):
                merged_keys = merged_keys.merge_delta(op.key_op)
                merged_values[op.key] = op.value
            elif isinstance(op, ORMapRemoveDeltaOp):
                merged_values.pop(op.key, None)
                merged_keys = merged_keys.merge_delta(op.key_op)
            elif isinstance(op, ORMapRemoveKeyDeltaOp):
                if op.key in self.entries:
                    tombstoned[op.key] = self.entries[op.key]
                merged_keys = merged_keys.merge_delta(op.key_op)
            elif isinstance(op, ORMapUpdateDeltaOp):
                merged_keys = merged_keys.merge_delta(op.key_op)
                for k, vd in op.values.items():
                    if k not in merged_keys.element_map:
                        # causal guard: the key's add was already observed
                        # AND removed here — the stale value delta must
                        # not resurrect it
                        continue
                    cur = merged_values.get(k)
                    if cur is None:
                        # seed from the local entry (reference parity): the
                        # value delta applies ONTO what this replica holds,
                        # not onto a zero-reconstruction whose vvector would
                        # dominate-and-drop the local elements on merge
                        cur = tombstoned.get(k, self.entries.get(k))
                    if cur is not None:
                        merged_values[k] = (
                            cur.merge_delta(vd)
                            if isinstance(cur, DeltaReplicatedData)
                            else cur.merge(vd))
                    else:
                        # zero-tag value reconstruction: an op-style value
                        # delta (ORSetDeltaOp) rebuilds against its zero;
                        # counter deltas ARE valid state (absolute counts)
                        z = getattr(vd, "zero", None)
                        merged_values[k] = \
                            z().merge_delta(vd) if z is not None else vd
            else:
                raise ValueError(f"unknown ORMap delta op {op!r}")
        keep = set(merged_keys.element_map)
        if retain_deleted:
            keep |= set(self.entries) | set(tombstoned) | set(merged_values)
        entries: Dict[Any, ReplicatedData] = {}
        for key in keep:
            mine = self.entries.get(key)
            theirs = merged_values.get(key)
            if mine is not None and theirs is not None:
                entries[key] = mine.merge(theirs)
            elif mine is not None:
                entries[key] = mine
            elif theirs is not None:
                entries[key] = theirs
        return ORMap(merged_keys, entries, self._delta)

    def modified_by_nodes(self) -> FrozenSet[str]:
        out = set(self.keys.modified_by_nodes())
        for v in self.entries.values():
            if isinstance(v, RemovedNodePruning):
                out |= v.modified_by_nodes()
        return frozenset(out)

    def prune(self, removed: str, collapse_into: str) -> "ORMap":
        entries = {
            k: (v.prune(removed, collapse_into)
                if isinstance(v, RemovedNodePruning) and v.needs_pruning_from(removed)
                else v)
            for k, v in self.entries.items()}
        return ORMap(self.keys.prune(removed, collapse_into), entries)

    def prune_cleanup(self, removed: str) -> "ORMap":
        entries = {
            k: (v.prune_cleanup(removed) if isinstance(v, RemovedNodePruning) else v)
            for k, v in self.entries.items()}
        return ORMap(self.keys.prune_cleanup(removed), entries)

    def __eq__(self, other):
        return (isinstance(other, ORMap) and self.keys == other.keys
                and self.entries == other.entries)

    def __hash__(self):
        return hash((self.keys, frozenset(self.entries)))

    def __repr__(self):
        return f"ORMap({dict(self.entries)!r})"


class ORMultiMap(DeltaReplicatedData, Generic[A]):
    """key -> ORSet of values (reference: ORMultiMap.scala, the
    withValueDeltas variant): binding changes ship as the value set's OWN
    op deltas inside ORMap UpdateDeltaOps, and key removal is
    clear-then-remove_key so the emptied set survives as a tombstone
    carrying its causal context — a concurrent add_binding then converges
    (removed elements stay removed, the new binding lands) instead of
    resurrecting the whole set. Tombstones are invisible through
    get/entries/contains (filtered to live keys) and survive merges via
    merge_retaining_deleted_values."""

    __slots__ = ("underlying",)

    def __init__(self, underlying: Optional[ORMap] = None):
        self.underlying = underlying or ORMap()

    @staticmethod
    def empty() -> "ORMultiMap":
        return ORMultiMap()

    def _live(self, key) -> bool:
        return key in self.underlying.keys.element_map

    def get(self, key) -> FrozenSet:
        if not self._live(key):
            return frozenset()
        s = self.underlying.get(key)
        return s.elements if isinstance(s, ORSet) else frozenset()

    def contains(self, key) -> bool:
        return self._live(key) and key in self.underlying

    @property
    def entries(self) -> Dict[Any, FrozenSet]:
        return {k: v.elements for k, v in self.underlying.entries.items()
                if isinstance(v, ORSet) and self._live(k)}

    def add_binding(self, node: str, key, value) -> "ORMultiMap":
        return ORMultiMap(self.underlying.updated(
            node, key, ORSet(), lambda s: s.add(node, value),
            _tag=ORMultiMap))

    def remove_binding(self, node: str, key, value) -> "ORMultiMap":
        if value not in self.get(key):
            return self
        u = self.underlying.updated(
            node, key, ORSet(), lambda s: s.remove(node, value),
            _tag=ORMultiMap)
        got = u.get(key)
        if isinstance(got, ORSet) and not got.element_map:
            u = u.remove_key(node, key, _tag=ORMultiMap)
        return ORMultiMap(u)

    def replace_binding(self, node: str, key, old, new) -> "ORMultiMap":
        if old == new:  # guard: add-then-remove of the same element would
            return self  # observe the fresh dot and delete the binding
        return self.add_binding(node, key, new).remove_binding(node, key, old)

    def put(self, node: str, key, values) -> "ORMultiMap":
        vals = list(values)

        def replace(s: ORSet) -> ORSet:
            out = s.clear()  # clear observes the old dots (value delta)
            for v in vals:
                out = out.add(node, v)
            return out
        return ORMultiMap(self.underlying.updated(
            node, key, ORSet(), replace, _tag=ORMultiMap))

    def remove(self, node: str, key) -> "ORMultiMap":
        u = self.underlying.updated(
            node, key, ORSet(), lambda s: s.clear(), _tag=ORMultiMap)
        return ORMultiMap(u.remove_key(node, key, _tag=ORMultiMap))

    def merge(self, other: "ORMultiMap") -> "ORMultiMap":
        return ORMultiMap(self.underlying.merge_retaining_deleted_values(
            other.underlying))

    @property
    def delta(self) -> Optional[ORMapDeltaOp]:
        return self.underlying.delta

    def reset_delta(self) -> "ORMultiMap":
        return ORMultiMap(self.underlying.reset_delta())

    def merge_delta(self, delta) -> "ORMultiMap":
        if isinstance(delta, ORMultiMap):
            return self.merge(delta)
        return ORMultiMap(
            self.underlying.merge_delta_retaining_deleted_values(delta))

    def __eq__(self, other):
        return isinstance(other, ORMultiMap) and self.underlying == other.underlying

    def __hash__(self):
        return hash(self.underlying)

    def __repr__(self):
        return f"ORMultiMap({self.entries!r})"


class PNCounterMap(DeltaReplicatedData):
    """key -> PNCounter (reference: PNCounterMap.scala). Increments ship
    as the counter's own delta ({node: absolute count}) inside an ORMap
    UpdateDeltaOp — O(entry) gossip; the reference's documented
    remove-vs-concurrent-update anomaly applies (see ORMap docstring)."""

    __slots__ = ("underlying",)

    def __init__(self, underlying: Optional[ORMap] = None):
        self.underlying = underlying or ORMap()

    @staticmethod
    def empty() -> "PNCounterMap":
        return PNCounterMap()

    def get(self, key) -> Optional[int]:
        c = self.underlying.get(key)
        return c.value if isinstance(c, PNCounter) else None

    @property
    def entries(self) -> Dict[Any, int]:
        return {k: v.value for k, v in self.underlying.entries.items()
                if isinstance(v, PNCounter)}

    def increment(self, node: str, key, n: int = 1) -> "PNCounterMap":
        return PNCounterMap(self.underlying.updated(
            node, key, PNCounter(), lambda c: c.increment(node, n),
            _tag=PNCounterMap))

    def decrement(self, node: str, key, n: int = 1) -> "PNCounterMap":
        return PNCounterMap(self.underlying.updated(
            node, key, PNCounter(), lambda c: c.decrement(node, n),
            _tag=PNCounterMap))

    def remove(self, node: str, key) -> "PNCounterMap":
        return PNCounterMap(self.underlying.remove(node, key,
                                                   _tag=PNCounterMap))

    def merge(self, other: "PNCounterMap") -> "PNCounterMap":
        return PNCounterMap(self.underlying.merge(other.underlying))

    @property
    def delta(self) -> Optional[ORMapDeltaOp]:
        return self.underlying.delta

    def reset_delta(self) -> "PNCounterMap":
        return PNCounterMap(self.underlying.reset_delta())

    def merge_delta(self, delta) -> "PNCounterMap":
        if isinstance(delta, PNCounterMap):
            return self.merge(delta)
        return PNCounterMap(self.underlying.merge_delta(delta))

    def __eq__(self, other):
        return isinstance(other, PNCounterMap) and self.underlying == other.underlying

    def __hash__(self):
        return hash(self.underlying)

    def __repr__(self):
        return f"PNCounterMap({self.entries!r})"


class LWWMap(DeltaReplicatedData, Generic[A]):
    """key -> LWWRegister (reference: LWWMap.scala). A put ships one
    PutDeltaOp carrying one register; the dry-merge's final full-merge
    per key keeps concurrent puts commutative (timestamp winner)."""

    __slots__ = ("underlying",)

    def __init__(self, underlying: Optional[ORMap] = None):
        self.underlying = underlying or ORMap()

    @staticmethod
    def empty() -> "LWWMap":
        return LWWMap()

    def get(self, key):
        r = self.underlying.get(key)
        return r.value if isinstance(r, LWWRegister) else None

    def contains(self, key) -> bool:
        return key in self.underlying

    @property
    def entries(self) -> Dict[Any, Any]:
        return {k: v.value for k, v in self.underlying.entries.items()
                if isinstance(v, LWWRegister)}

    def put(self, node: str, key, value,
            clock: Optional[Callable[[int, Any], int]] = None) -> "LWWMap":
        cur = self.underlying.get(key)
        reg = (cur.with_value(node, value, clock) if isinstance(cur, LWWRegister)
               else LWWRegister.create(node, value, clock))
        return LWWMap(self.underlying.put(node, key, reg, _tag=LWWMap))

    def remove(self, node: str, key) -> "LWWMap":
        return LWWMap(self.underlying.remove(node, key, _tag=LWWMap))

    def merge(self, other: "LWWMap") -> "LWWMap":
        return LWWMap(self.underlying.merge(other.underlying))

    @property
    def delta(self) -> Optional[ORMapDeltaOp]:
        return self.underlying.delta

    def reset_delta(self) -> "LWWMap":
        return LWWMap(self.underlying.reset_delta())

    def merge_delta(self, delta) -> "LWWMap":
        if isinstance(delta, LWWMap):
            return self.merge(delta)
        return LWWMap(self.underlying.merge_delta(delta))

    def __eq__(self, other):
        return isinstance(other, LWWMap) and self.underlying == other.underlying

    def __hash__(self):
        return hash(self.underlying)

    def __repr__(self):
        return f"LWWMap({self.entries!r})"
