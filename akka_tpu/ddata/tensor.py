"""Tensor-native bulk CRDT kernels: the TPU-first data plane for ddata.

SURVEY.md §7 step 8: "G/PN counters and OR-sets have natural tensor
encodings (per-node counter rows; merge = elementwise max/sum — literally
psum-shaped)". The host Replicator (replicator.py) is the control plane for
arbitrary keys; when an application has MANY counters/flags/sets (e.g. one
per entity), it should hold them as a *bank*: a single device array with one
row per key and one column per cluster node. Merging two replicas of a bank
is then one fused elementwise op on the MXU-adjacent VPU, and converging all
replicas across a mesh axis is a single XLA collective (`lax.pmax` — the
max-reduction sibling of psum) instead of N² host gossip rounds.

Layouts (n_keys rows is the vmap/shard axis; n_nodes is small and fixed):
- GCounterBank:  uint32[n_keys, n_nodes]        merge = max, value = row sum
- PNCounterBank: uint32[n_keys, 2, n_nodes]     [:,0]=incs [:,1]=decs
- GSetBank:      bool[n_keys, n_elems]          merge = or, fixed universe
- FlagBank:      bool[n_keys]                   merge = or

No reference-file analogue exists for this module — it is the TPU-native
replacement for akka-distributed-data's per-object JVM merges
(ddata/GCounter.scala merge loop) at bank granularity.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    _shard_map = jax.shard_map
except AttributeError:  # jax < 0.5 ships it under experimental
    from jax.experimental.shard_map import shard_map as _shard_map


# -- single-replica pairwise merges (jitted, fuse into one kernel) ----------

@jax.jit
def gcounter_merge(a: jax.Array, b: jax.Array) -> jax.Array:
    """Pairwise max over per-node rows (GCounter.scala merge semantics)."""
    return jnp.maximum(a, b)


@jax.jit
def gcounter_value(bank: jax.Array) -> jax.Array:
    """Per-key counter value: sum over the node axis."""
    return jnp.sum(bank, axis=-1)


@jax.jit
def pncounter_merge(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.maximum(a, b)


@jax.jit
def pncounter_value(bank: jax.Array) -> jax.Array:
    s = jnp.sum(bank, axis=-1)  # [n_keys, 2]
    return s[..., 0].astype(jnp.int64 if jax.config.jax_enable_x64
                            else jnp.int32) - s[..., 1]


@jax.jit
def gset_merge(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.logical_or(a, b)


flag_merge = gset_merge


def gcounter_increment(bank: jax.Array, node_slot: int,
                       key_ids: jax.Array, amounts: jax.Array) -> jax.Array:
    """Batched local increment: bump this node's column for each key in
    `key_ids` by `amounts`. Duplicate key_ids accumulate (scatter-add)."""
    return bank.at[key_ids, node_slot].add(amounts.astype(bank.dtype))


# -- mesh-wide convergence: one collective instead of gossip ----------------

def converge_over_mesh(bank: jax.Array, mesh: Mesh, axis: str = "replica",
                       op: str = "max") -> jax.Array:
    """All-replica merge of a replicated bank over a mesh axis.

    Each device along `axis` holds its own replica of the full bank (the
    ddata model: every node has a copy). One `lax.pmax` (or `pmax`-of-or for
    boolean banks) converges every replica to the join of all — the
    ICI-collective equivalent of WriteAll+ReadAll consistency.
    """
    reduce = {"max": jax.lax.pmax, "or": lambda x, ax: jax.lax.pmax(
        x.astype(jnp.uint8), ax).astype(jnp.bool_)}[op]

    @functools.partial(
        _shard_map, mesh=mesh,
        in_specs=P(axis),   # stacked replicas: leading axis = replica id
        out_specs=P(axis))
    def _converge(local):
        merged = reduce(local, axis)
        return merged

    return _converge(bank)


def replicate_bank(bank: jax.Array, mesh: Mesh, axis: str = "replica") -> jax.Array:
    """Stack one replica of `bank` per device along `axis` (test/bootstrap
    helper: real deployments start each node with its own local bank)."""
    n = mesh.shape[axis]
    stacked = jnp.broadcast_to(bank[None], (n,) + bank.shape)
    return jax.device_put(stacked, NamedSharding(mesh, P(axis)))
