"""Process-stable hashing.

Python's builtin hash() is salted per process (PYTHONHASHSEED), so it must
never be used for cross-node placement decisions (shard ids, ring positions).
These helpers give every node the same answer for the same key — the moral
equivalent of the reference's MurmurHash (routing/MurmurHash.scala) used by
consistent-hashing routers.
"""

from __future__ import annotations

import hashlib
from typing import Any


def stable_hash(key: Any) -> int:
    """64-bit stable hash of repr(key)."""
    h = hashlib.md5(repr(key).encode()).digest()
    return int.from_bytes(h[:8], "little")


def stable_hash_str(s: str) -> int:
    h = hashlib.md5(s.encode()).digest()
    return int.from_bytes(h[:8], "little")
