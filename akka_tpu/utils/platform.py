"""Backend-platform forcing shared by every entry point that must honor an
explicit JAX_PLATFORMS=cpu request.

An ambient sitecustomize may register a tunneled TPU platform that wins over
the env var, and a wedged tunnel HANGS (not errors) at first backend init —
so the cpu request must be applied through the live config BEFORE any
backend touch. Exact-token match: a priority list like "tpu,cpu" ('prefer
TPU, fall back') is NOT a cpu-only request and is left alone."""

from __future__ import annotations

import os


def force_requested_platform() -> str | None:
    """Apply JAX_PLATFORMS via jax.config when it names cpu FIRST.
    Returns the forced platform name, or None if nothing was forced.
    Safe to call multiple times; must run before the first backend init."""
    plats = [p.strip() for p in
             os.environ.get("JAX_PLATFORMS", "").split(",") if p.strip()]
    if plats and plats[0] == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
        return "cpu"
    return None
