"""BackoffSupervisor + retry + gracefulStop.

Reference parity: akka-actor/src/main/scala/akka/pattern/BackoffSupervisor.scala
(exponential backoff respawn of a child on failure or stop),
pattern/RetrySupport.scala (retry), AskSupport.gracefulStop.
"""

from __future__ import annotations

import random
import threading
from concurrent.futures import Future
from typing import Any, Callable, Optional

from ..actor.actor import Actor
from ..actor.messages import PoisonPill, Terminated
from ..actor.props import Props
from ..actor.ref import ActorRef
from ..actor.supervision import OneForOneStrategy, Stop, default_decider


def backoff_delay(restart_count: int, min_backoff: float, max_backoff: float,
                  random_factor: float = 0.0) -> float:
    """Exponential backoff delay (BackoffSupervisor.scala calculateDelay):
    min_backoff * 2^restart_count capped at max_backoff, plus optional
    random jitter. Shared by BackoffSupervisor and the batched runtime's
    checkpoint-failure pacing (random_factor=0 there: deterministic)."""
    delay = min(min_backoff * (2 ** restart_count), max_backoff)
    if random_factor:
        delay *= 1.0 + random.random() * random_factor
    return delay


class GetCurrentChild:
    pass


class CurrentChild:
    def __init__(self, ref: Optional[ActorRef]):
        self.ref = ref


class GetRestartCount:
    pass


class RestartCount:
    def __init__(self, count: int):
        self.count = count


class _StartChild:
    pass


class BackoffSupervisor(Actor):
    """Spawns `child_props` as a child; when the child stops (on-stop mode) or
    fails (on-failure mode via supervision Stop), re-spawns it after an
    exponentially growing delay."""

    def __init__(self, child_props: Props, child_name: str, min_backoff: float,
                 max_backoff: float, random_factor: float = 0.2,
                 mode: str = "on-stop"):
        super().__init__()
        self.child_props = child_props
        self.child_name = child_name
        self.min_backoff = min_backoff
        self.max_backoff = max_backoff
        self.random_factor = random_factor
        self.mode = mode
        self.child: Optional[ActorRef] = None
        self.restart_count = 0
        self._forward_buffer: list = []

    @staticmethod
    def props(child_props: Props, child_name: str, min_backoff: float,
              max_backoff: float, random_factor: float = 0.2,
              mode: str = "on-stop") -> Props:
        return Props.create(BackoffSupervisor, child_props, child_name,
                            min_backoff, max_backoff, random_factor, mode)

    @property
    def supervisor_strategy(self):
        # child failures become stops, which trigger the backoff respawn
        return OneForOneStrategy(decider=lambda e: Stop if isinstance(e, Exception)
                                 else default_decider(e))

    def pre_start(self) -> None:
        self._start_child()

    def _start_child(self) -> None:
        self.child = self.context.actor_of(self.child_props, self.child_name)
        self.context.watch(self.child)
        for msg, sender in self._forward_buffer:
            self.child.tell(msg, sender)
        self._forward_buffer.clear()

    def receive(self, message: Any):
        if isinstance(message, Terminated) and self.child is not None \
                and message.actor == self.child:
            self.child = None
            delay = backoff_delay(self.restart_count, self.min_backoff,
                                  self.max_backoff, self.random_factor)
            self.restart_count += 1
            self.context.system.scheduler.schedule_tell_once(
                delay, self.self_ref, _StartChild(), self.self_ref)
        elif isinstance(message, _StartChild):
            self._start_child()
        elif isinstance(message, GetCurrentChild):
            self.sender.tell(CurrentChild(self.child), self.self_ref)
        elif isinstance(message, GetRestartCount):
            self.sender.tell(RestartCount(self.restart_count), self.self_ref)
        else:
            if self.child is not None:
                self.child.forward(message, self.context)
            else:
                self._forward_buffer.append((message, self.sender))
        return None


def retry(attempt: Callable[[], Future], attempts: int, delay: float,
          scheduler, backoff: float = 1.0) -> Future:
    """Retry an async op with (optionally growing) delay between attempts
    (reference: pattern/RetrySupport.scala)."""
    out: Future = Future()

    def try_once(remaining: int, current_delay: float):
        try:
            fut = attempt()
        except Exception as e:  # noqa: BLE001
            _handle_failure(e, remaining, current_delay)
            return

        def _done(f: Future):
            exc = f.exception()
            if exc is None:
                if not out.done():
                    out.set_result(f.result())
            else:
                _handle_failure(exc, remaining, current_delay)

        fut.add_done_callback(_done)

    def _handle_failure(exc, remaining, current_delay):
        if remaining <= 1:
            if not out.done():
                out.set_exception(exc)
        else:
            scheduler.schedule_once(
                current_delay,
                lambda: try_once(remaining - 1, current_delay * backoff))

    try_once(attempts, delay)
    return out


def graceful_stop(target: ActorRef, timeout: float, system,
                  stop_message: Any = PoisonPill) -> Future:
    """Stop an actor and complete when its termination is observed
    (reference: pattern/GracefulStopSupport.scala)."""
    fut: Future = Future()

    def handler(msg, sender):
        if isinstance(msg, Terminated) and not fut.done():
            fut.set_result(True)

    probe = system.provider.create_function_ref(handler)
    probe.watch(target)
    target.tell(stop_message, probe)

    def _timeout():
        if not fut.done():
            fut.set_exception(TimeoutError(
                f"{target} did not terminate within {timeout}s"))
        system.provider.stop_function_ref(probe)

    system.scheduler.schedule_once(timeout, _timeout)
    return fut
