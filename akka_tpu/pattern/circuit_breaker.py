"""Circuit breaker: closed -> open -> half-open state machine.

Reference parity: akka-actor/src/main/scala/akka/pattern/CircuitBreaker.scala
(:136 state machine, :416 transitions) — maxFailures within callTimeout trips
open; after resetTimeout one probe call (half-open) decides close vs re-open;
exponential backoff on repeated open.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, List, Optional


class CircuitBreakerOpenException(Exception):
    def __init__(self, remaining: float):
        super().__init__(f"circuit breaker is open; retry after {remaining:.2f}s")
        self.remaining = remaining


class CircuitBreaker:
    def __init__(self, scheduler, max_failures: int, call_timeout: float,
                 reset_timeout: float, exponential_backoff_factor: float = 1.0,
                 max_reset_timeout: float = float("inf")):
        self.scheduler = scheduler
        self.max_failures = max_failures
        self.call_timeout = call_timeout
        self.reset_timeout = reset_timeout
        self.backoff_factor = max(exponential_backoff_factor, 1.0)
        self.max_reset_timeout = max_reset_timeout
        self._state = "closed"
        self._failures = 0
        self._opened_at = 0.0
        self._current_reset = reset_timeout
        # half-open admits exactly ONE probe (reference :416 attemptReset —
        # the transition swaps in a single-permit gate): the first caller
        # claims this flag under the lock; every other caller fails fast
        # with CircuitBreakerOpenException until the probe reports. A probe
        # that raises re-opens atomically in fail(), which also restarts
        # the reset timer (_trip_open re-stamps _opened_at).
        self._probe_in_flight = False
        self._lock = threading.RLock()
        self._on_open: List[Callable[[], None]] = []
        self._on_close: List[Callable[[], None]] = []
        self._on_half_open: List[Callable[[], None]] = []

    # -- listeners -----------------------------------------------------------
    def on_open(self, cb): self._on_open.append(cb); return self
    def on_close(self, cb): self._on_close.append(cb); return self
    def on_half_open(self, cb): self._on_half_open.append(cb); return self

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    @property
    def current_failure_count(self) -> int:
        return self._failures

    def _maybe_half_open(self) -> None:
        if self._state == "open" and time.monotonic() - self._opened_at >= self._current_reset:
            self._state = "half-open"
            for cb in self._on_half_open:
                cb()

    def _trip_open(self) -> None:
        self._state = "open"
        self._opened_at = time.monotonic()
        self._probe_in_flight = False
        for cb in self._on_open:
            cb()

    def _close(self) -> None:
        self._state = "closed"
        self._failures = 0
        self._current_reset = self.reset_timeout
        self._probe_in_flight = False
        for cb in self._on_close:
            cb()

    def _admit(self) -> None:
        """Gate one call attempt (caller holds the lock): open -> fail
        fast; half-open -> admit only the single probe, racing callers
        fail fast until it reports via succeed()/fail()."""
        self._maybe_half_open()
        if self._state == "open" or (self._state == "half-open"
                                     and self._probe_in_flight):
            remaining = self._current_reset - (time.monotonic() - self._opened_at)
            raise CircuitBreakerOpenException(max(remaining, 0.0))
        if self._state == "half-open":
            self._probe_in_flight = True

    # -- call protection -----------------------------------------------------
    def with_sync_circuit_breaker(self, body: Callable[[], Any]) -> Any:
        with self._lock:
            self._admit()
        start = time.monotonic()
        try:
            result = body()
        except Exception:
            self.fail()
            raise
        if time.monotonic() - start > self.call_timeout:
            self.fail()
        else:
            self.succeed()
        return result

    call = with_sync_circuit_breaker

    def with_circuit_breaker(self, body: Callable[[], Future]) -> Future:
        out: Future = Future()
        with self._lock:
            try:
                self._admit()
            except CircuitBreakerOpenException as e:
                out.set_exception(e)
                return out
        start = time.monotonic()
        try:
            fut = body()
        except Exception as e:  # noqa: BLE001
            self.fail()
            out.set_exception(e)
            return out

        def _done(f: Future):
            exc = f.exception()
            if exc is not None or time.monotonic() - start > self.call_timeout:
                self.fail()
            else:
                self.succeed()
            if exc is not None:
                out.set_exception(exc)
            else:
                out.set_result(f.result())

        fut.add_done_callback(_done)
        return out

    # -- manual outcome reporting (reference: succeed()/fail() on CB) --------
    def succeed(self) -> None:
        with self._lock:
            if self._state == "half-open":
                self._close()
            else:
                self._failures = 0

    def fail(self) -> None:
        with self._lock:
            if self._state == "half-open":
                # atomic re-open: backoff the reset and restart its timer
                # (_trip_open re-stamps _opened_at) in the same critical
                # section that releases the probe permit
                self._current_reset = min(self._current_reset * self.backoff_factor,
                                          self.max_reset_timeout)
                self._trip_open()
                return
            self._failures += 1
            if self._failures >= self.max_failures and self._state == "closed":
                self._trip_open()
