"""Ask pattern: request-response as a Future via a temporary promise ref.

Reference parity: akka-actor/src/main/scala/akka/pattern/AskSupport.scala —
`ask` (:84) creates a PromiseActorRef (:476) registered under /temp, which
completes a future on the first reply and fails with AskTimeoutException
after the timeout.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future
from typing import Any, Optional

from ..actor.messages import Status
from ..actor.path import ActorPath
from ..actor.ref import ActorRef, InternalActorRef, MinimalActorRef
from ..dispatch import sysmsg


class AskTimeoutException(Exception):
    pass


class PromiseActorRef(MinimalActorRef):
    """(reference: pattern/AskSupport.scala:476)"""

    def __init__(self, path: ActorPath, provider, future: Future, timeout_task=None,
                 on_complete=None):
        super().__init__(path, provider)
        self.future = future
        self._timeout_task = timeout_task
        self._on_complete = on_complete
        self._done = threading.Event()
        self._done_lock = threading.Lock()
        self._watched_by: set = set()

    def _try_complete(self) -> bool:
        """Atomically claim completion — racing replies/timeouts lose cleanly."""
        with self._done_lock:
            if self._done.is_set():
                return False
            self._done.set()
            return True

    def tell(self, message: Any, sender: Optional[ActorRef] = None) -> None:
        if not self._try_complete():
            return
        if self._timeout_task is not None:
            self._timeout_task.cancel()
        if isinstance(message, Status.Failure):
            self.future.set_exception(message.cause)
        elif isinstance(message, Status.Success):
            self.future.set_result(message.status)
        else:
            self.future.set_result(message)
        self._cleanup()

    def send_system_message(self, message: sysmsg.SystemMessage) -> None:
        if isinstance(message, sysmsg.Watch):
            self._watched_by.add(message.watcher)
        elif isinstance(message, sysmsg.Unwatch):
            self._watched_by.discard(message.watcher)
        elif isinstance(message, sysmsg.DeathWatchNotification):
            from ..actor.messages import Terminated
            self.tell(Terminated(message.actor, message.existence_confirmed,
                                 message.address_terminated))

    def _cleanup(self) -> None:
        if self.provider is not None:
            self.provider.unregister_temp_actor(self.path)
        for w in list(self._watched_by):
            w.send_system_message(sysmsg.DeathWatchNotification(self, existence_confirmed=True))
        self._watched_by.clear()
        if self._on_complete is not None:
            self._on_complete(self)

    def stop(self) -> None:
        self.tell(Status.Failure(AskTimeoutException("promise ref stopped")))

    @property
    def is_terminated(self) -> bool:
        return self._done.is_set()


def ask(target: ActorRef, message: Any, timeout: float = 5.0, system=None) -> Future:
    """Send `message` to `target` with a promise ref as sender; returns a
    concurrent.futures.Future of the first reply. `message` may also be a
    callable ref -> message for typed-style ask."""
    import sys
    bridge = sys.modules.get("akka_tpu.batched.bridge")
    if bridge is not None:
        # only consult the device path if the batched runtime is actually
        # loaded — host-only systems never pay the jax import here
        if isinstance(target, bridge.DeviceActorRef):
            # device actors complete asks via promise rows read back after
            # a step (the PromiseActorRef analogue lives in HBM)
            if callable(message) and not isinstance(message, type):
                raise TypeError(
                    "callable (typed-style) ask messages are not supported "
                    "for device actors; encode the reply-to via the codec")
            return target.ask(message, timeout)
        if isinstance(target, bridge.DeviceBlockRef):
            raise TypeError(
                "ask() on a DeviceBlockRef is ambiguous (which row would "
                "reply?); ask a single actor via block[i]")
    if system is None:
        system = getattr(target, "_system", None) or getattr(getattr(target, "cell", None), "system", None)
    if system is None:
        raise ValueError("ask: cannot determine actor system; pass system=")
    provider = system.provider
    fut: Future = Future()
    path = provider.temp_path()
    ref = PromiseActorRef(path, provider, fut)
    task = system.scheduler.schedule_once(
        timeout, lambda: _timeout(ref, fut, target, message, timeout))
    ref._timeout_task = task
    provider.register_temp_actor(ref, path)
    msg = message(ref) if callable(message) and not isinstance(message, type) else message
    target.tell(msg, ref)
    return fut


def _timeout(ref: PromiseActorRef, fut: Future, target, message, timeout: float) -> None:
    if ref._try_complete():
        ref._cleanup()
        fut.set_exception(AskTimeoutException(
            f"Ask timed out on [{target}] after [{timeout}s]. "
            f"Message of type [{type(message).__name__}]."))


def ask_sync(target: ActorRef, message: Any, timeout: float = 5.0, system=None) -> Any:
    """Blocking ask."""
    return ask(target, message, timeout, system).result(timeout + 1.0)


def pipe(future: Future, recipient: ActorRef, sender: Optional[ActorRef] = None) -> None:
    """Pipe a future's outcome to an actor (reference: pattern/PipeToSupport.scala)."""

    def _done(f: Future) -> None:
        exc = f.exception()
        if exc is not None:
            recipient.tell(Status.Failure(exc), sender)
        else:
            recipient.tell(f.result(), sender)

    future.add_done_callback(_done)
