"""Reachability table: per-observer unreachable records, merged via gossip.

Reference parity: akka-cluster/src/main/scala/akka/cluster/Reachability.scala —
rows of (observer, subject, status, version); a subject is unreachable if ANY
observer currently marks it unreachable; merge keeps the freshest row per
(observer, subject).
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum
from typing import Dict, FrozenSet, Iterable, Set, Tuple

from .member import UniqueAddress


class ReachabilityStatus(Enum):
    REACHABLE = "Reachable"
    UNREACHABLE = "Unreachable"
    TERMINATED = "Terminated"


@dataclass(frozen=True)
class Record:
    observer: UniqueAddress
    subject: UniqueAddress
    status: ReachabilityStatus
    version: int


class Reachability:
    __slots__ = ("records",)

    def __init__(self, records: Iterable[Record] = ()):
        # keep only the freshest record per (observer, subject)
        table: Dict[Tuple[UniqueAddress, UniqueAddress], Record] = {}
        for r in records:
            key = (r.observer, r.subject)
            cur = table.get(key)
            if cur is None or r.version > cur.version:
                table[key] = r
        self.records = table

    def _next_version(self, observer: UniqueAddress) -> int:
        return 1 + max((r.version for (o, _), r in self.records.items()
                        if o == observer), default=0)

    def unreachable(self, observer: UniqueAddress,
                    subject: UniqueAddress) -> "Reachability":
        rec = Record(observer, subject, ReachabilityStatus.UNREACHABLE,
                     self._next_version(observer))
        return Reachability(list(self.records.values()) + [rec])

    def reachable(self, observer: UniqueAddress,
                  subject: UniqueAddress) -> "Reachability":
        rec = Record(observer, subject, ReachabilityStatus.REACHABLE,
                     self._next_version(observer))
        return Reachability(list(self.records.values()) + [rec])

    def terminated(self, observer: UniqueAddress,
                   subject: UniqueAddress) -> "Reachability":
        rec = Record(observer, subject, ReachabilityStatus.TERMINATED,
                     self._next_version(observer))
        return Reachability(list(self.records.values()) + [rec])

    def merge(self, other: "Reachability") -> "Reachability":
        return Reachability(list(self.records.values()) +
                            list(other.records.values()))

    def remove(self, nodes: Iterable[UniqueAddress]) -> "Reachability":
        gone = set(nodes)
        return Reachability(r for r in self.records.values()
                            if r.observer not in gone and r.subject not in gone)

    def is_reachable(self, subject: UniqueAddress) -> bool:
        return subject not in self.all_unreachable

    def is_reachable_by(self, observer: UniqueAddress,
                        subject: UniqueAddress) -> bool:
        r = self.records.get((observer, subject))
        return r is None or r.status is ReachabilityStatus.REACHABLE

    @property
    def all_unreachable(self) -> FrozenSet[UniqueAddress]:
        return frozenset(r.subject for r in self.records.values()
                         if r.status is not ReachabilityStatus.REACHABLE)

    def all_unreachable_from(self, observer: UniqueAddress) -> FrozenSet[UniqueAddress]:
        return frozenset(r.subject for (o, _), r in self.records.items()
                         if o == observer
                         and r.status is not ReachabilityStatus.REACHABLE)

    @property
    def is_all_reachable(self) -> bool:
        return not self.all_unreachable

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Reachability) and self.records == other.records

    def __repr__(self) -> str:
        bad = [f"{r.observer.address_str}!{r.subject.address_str}"
               for r in self.records.values()
               if r.status is not ReachabilityStatus.REACHABLE]
        return f"Reachability(unreachable=[{', '.join(bad)}])"
