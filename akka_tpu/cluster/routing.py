"""Cluster-aware routers: routees follow cluster membership.

Reference parity: akka-cluster/src/main/scala/akka/cluster/routing/ —
ClusterRouterPool / ClusterRouterGroup wrap a local Pool/Group with
ClusterRouterPoolSettings / ClusterRouterGroupSettings (totalInstances,
maxInstancesPerNode, routeesPaths, allowLocalRoutees, useRoles;
ClusterRouterConfigBase.scala), and ClusterRouterActor subscribes to
MemberEvent/ReachabilityEvent to add/remove routees as nodes come and go
(ClusterRouterActor in ClusterRouterConfig.scala: addRoutees on MemberUp,
removeMember on MemberRemoved, unregister on UnreachableMember).

TPU-first shape: pool routees are deployed onto members through the remote
daemon (remote/deploy.py — the recipe travels, not a closure); group routees
are remote-path selections. The routing decision itself stays the local
RoutingLogic — an index choice, no extra hop.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Optional, Tuple

from ..actor.deploy import Deploy, RemoteScope
from ..actor.path import Address
from ..actor.props import Props
from ..routing.router import (ActorRefRoutee, ActorSelectionRoutee, Routee,
                              Router, RouterConfig)
from ..routing.routed_cell import RouterActor
from .events import (CurrentClusterState, MemberEvent, MemberRemoved,
                     MemberUp, MemberWeaklyUp, ReachabilityEvent,
                     ReachableMember, UnreachableMember)
from .member import Member, MemberStatus


@dataclass(frozen=True)
class ClusterRouterPoolSettings:
    """(reference: ClusterRouterPoolSettings in ClusterRouterConfig.scala)"""
    total_instances: int
    max_instances_per_node: int = 1
    allow_local_routees: bool = True
    use_roles: frozenset = frozenset()

    def __post_init__(self):
        # reference throws IllegalArgumentException for both
        if self.total_instances <= 0:
            raise ValueError("total_instances of cluster router must be > 0")
        if self.max_instances_per_node <= 0:
            raise ValueError(
                "max_instances_per_node of cluster router must be > 0")


@dataclass(frozen=True)
class ClusterRouterGroupSettings:
    """(reference: ClusterRouterGroupSettings)"""
    total_instances: int
    routees_paths: Tuple[str, ...] = ()
    allow_local_routees: bool = True
    use_roles: frozenset = frozenset()

    def __post_init__(self):
        if self.total_instances <= 0:
            raise ValueError("total_instances of cluster router must be > 0")


@dataclass(frozen=True)
class ClusterRouterConfig(RouterConfig):
    """Wraps a local RouterConfig; routees managed by ClusterRouterActor."""
    local: Optional[RouterConfig] = None
    cluster_settings: Any = None

    # RoutedActorCell consults this to pick the managing actor
    router_actor_class = None  # set below (forward ref)

    def create_router(self, system) -> Router:
        return self.local.create_router(system)

    @property
    def is_group(self) -> bool:
        return isinstance(self.cluster_settings, ClusterRouterGroupSettings)


def ClusterRouterPool(pool: RouterConfig,
                      settings: ClusterRouterPoolSettings) -> ClusterRouterConfig:
    if pool.is_group:
        raise ValueError("ClusterRouterPool needs a Pool config")
    return ClusterRouterConfig(
        logic_factory=pool.logic_factory,
        supervisor_strategy=pool.supervisor_strategy,
        local=pool, cluster_settings=settings)


def ClusterRouterGroup(group: RouterConfig,
                       settings: ClusterRouterGroupSettings) -> ClusterRouterConfig:
    paths = settings.routees_paths or group.paths
    if not paths:
        raise ValueError("ClusterRouterGroup needs routees_paths")
    settings = replace(settings, routees_paths=tuple(paths))
    return ClusterRouterConfig(
        logic_factory=group.logic_factory,
        local=group, cluster_settings=settings)


from ..routing.router import RouterManagementMessage


@dataclass(frozen=True)
class _ClusterEvent(RouterManagementMessage):
    """Wrapper so membership events reach the managing actor's mailbox instead
    of being routed to routees (RoutedActorCell.send_message routes everything
    that is not a management message)."""
    event: Any


class ClusterRouterActor(RouterActor):
    """Manages routees against live membership (reference:
    ClusterRouterActor: cluster.subscribe in preStart, addMember/removeMember
    on events, fully-filled check on each change)."""

    def __init__(self, router_config: ClusterRouterConfig):
        super().__init__(router_config)
        self.settings = router_config.cluster_settings
        # node address string -> routees we created/selected there
        self.node_routees: Dict[str, List[Routee]] = {}
        self.cluster = None
        self._sub = None

    # -- membership plumbing -------------------------------------------------
    def pre_start(self) -> None:
        from .cluster import Cluster
        self.cluster = Cluster.get(self.context.system)
        me = self.self_ref

        def forward(event):
            me.tell(_ClusterEvent(event))

        self._sub = forward
        self.cluster.subscribe(forward, MemberEvent, ReachabilityEvent,
                               initial_state=True)

    def post_stop(self) -> None:
        if self.cluster is not None and self._sub is not None:
            self.cluster.unsubscribe(self._sub)

    # -- eligibility ---------------------------------------------------------
    def _eligible(self, member: Member) -> bool:
        if member.status not in (MemberStatus.UP, MemberStatus.WEAKLY_UP):
            return False
        # never (re)deploy onto a node currently marked unreachable — the
        # reference's availableNodes excludes them; without this, the
        # backfill after _remove_node would put routees straight back.
        # Compare by unique_address: an event-snapshot Member can differ
        # from the gossip snapshot in status/up_number (ADVICE r3)
        if member.unique_address in {m.unique_address
                                     for m in self.cluster.state.unreachable}:
            return False
        roles = frozenset(self.settings.use_roles)
        if roles and not roles.issubset(member.roles):
            return False
        is_self = (member.unique_address == self.cluster.self_unique_address)
        if is_self and not self.settings.allow_local_routees:
            return False
        return True

    def _member_addr(self, member: Member) -> str:
        return member.unique_address.address_str

    # -- routee management ---------------------------------------------------
    def _capacity_left(self) -> int:
        total = sum(len(v) for v in self.node_routees.values())
        return max(self.settings.total_instances - total, 0)

    def _node_limit(self) -> int:
        if self.router_config.is_group:
            return len(self.settings.routees_paths)
        return self.settings.max_instances_per_node

    def _add_one(self, member: Member) -> bool:
        """Deploy exactly one routee onto `member`'s node. False when the
        node is already at its per-node limit or total capacity is hit."""
        addr = self._member_addr(member)
        existing = self.node_routees.get(addr, [])
        if len(existing) >= self._node_limit() or self._capacity_left() <= 0:
            return False
        cell = self._rcell
        if self.router_config.is_group:
            path = self.settings.routees_paths[len(existing)]
            # full address form even for self: the provider resolves our
            # own address back to local refs (provider.resolve_actor_ref)
            r: Routee = ActorSelectionRoutee(f"{addr}{path}",
                                             self.context.system)
        else:
            is_self = (member.unique_address == self.cluster.self_unique_address)
            props = cell.routee_props
            if not is_self:
                props = props.with_deploy(Deploy(scope=RemoteScope(addr)))
            child = cell.actor_of(props)
            self.context.watch(child)
            r = ActorRefRoutee(child)
        self.node_routees.setdefault(addr, []).append(r)
        cell.router.add_routee(r)
        return True

    def _add_member(self, member: Member) -> None:
        """A node became usable: resume filling (the reference's addMember
        registers the node then deploys via selectDeploymentTarget)."""
        if self._eligible(member):
            self._fill()

    def _remove_node(self, addr: str) -> None:
        routees = self.node_routees.pop(addr, None)
        if not routees:
            return
        cell = self._rcell
        for r in routees:
            cell.router.remove_routee(r)
            ref = getattr(r, "ref", None)
            if ref is not None:
                self.context.unwatch(ref)
                ref.stop()
        # backfill onto remaining nodes (fully-filled check parity)
        self._fill()

    def _fill(self, members=None) -> None:
        """Allocate one routee at a time onto the currently LEAST-LOADED
        eligible node (ties broken by address for determinism) until total
        capacity or every node's per-node limit is reached — the reference's
        ClusterRouterPoolActor.selectDeploymentTarget order, which spreads
        routees one-per-node instead of packing the lexicographically
        smallest addresses first."""
        if members is None:
            members = self.cluster.state.members
        eligible = [m for m in members if self._eligible(m)]
        while self._capacity_left() > 0 and eligible:
            target = min(eligible, key=lambda m: (
                len(self.node_routees.get(self._member_addr(m), ())),
                self._member_addr(m)))
            if not self._add_one(target):
                eligible.remove(target)  # node at per-node limit

    # -- receive -------------------------------------------------------------
    def receive(self, message: Any):
        if isinstance(message, _ClusterEvent):
            message = message.event
        if isinstance(message, CurrentClusterState):
            self._fill(message.members)
            return None
        if isinstance(message, (MemberUp, MemberWeaklyUp)):
            self._add_member(message.member)
            return None
        if isinstance(message, MemberRemoved):
            self._remove_node(self._member_addr(message.member))
            return None
        if isinstance(message, UnreachableMember):
            self._remove_node(self._member_addr(message.member))
            return None
        if isinstance(message, ReachableMember):
            self._add_member(message.member)
            return None
        if isinstance(message, MemberEvent):
            # other transitions (Left/Exited/Downed): drop the node early
            if message.member.status not in (MemberStatus.UP,
                                             MemberStatus.WEAKLY_UP):
                self._remove_node(self._member_addr(message.member))
            return None
        from ..actor.messages import Terminated
        if isinstance(message, Terminated):
            changed = False
            for addr, routees in list(self.node_routees.items()):
                kept = [r for r in routees
                        if getattr(r, "ref", None) != message.actor]
                if len(kept) != len(routees):
                    changed = True
                    if kept:
                        self.node_routees[addr] = kept
                    else:
                        self.node_routees.pop(addr, None)
            result = super().receive(message)
            if changed and not self._rcell.is_terminating:
                self._fill()  # keep the pool fully filled (reference parity)
            return result
        return super().receive(message)


ClusterRouterConfig.router_actor_class = ClusterRouterActor
