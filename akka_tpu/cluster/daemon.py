"""ClusterCoreDaemon: the membership state machine actor.

Reference parity: akka-cluster/src/main/scala/akka/cluster/ClusterDaemon.scala
(:312) — `joining` (:735), `leaving` (:834), `receiveGossip` (:994),
`gossipTick` (:1116), `leaderActions` (:1166), `leaderActionsOnConvergence`
(:1245), `reapUnreachableMembers` (:1413); heartbeating per
cluster/ClusterHeartbeat.scala (ring neighbors feeding phi-accrual).

The control plane runs on the host (it's low-rate); the data plane stays on
device (akka_tpu/batched). One daemon actor per node at /system/cluster.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, replace
from typing import Any, Dict, FrozenSet, Optional

from ..actor.actor import Actor
from ..actor.path import Address
from ..remote.failure_detector import FailureDetectorRegistry
from .events import (CurrentClusterState, LeaderChanged, MemberDowned,
                     MemberEvent, MemberExited, MemberJoined, MemberLeft,
                     MemberRemoved, MemberUp, MemberWeaklyUp, ReachableMember,
                     UnreachableMember)
from .gossip import Gossip
from .member import Member, MemberStatus, UniqueAddress


# -- inter-node protocol (picklable; reference: ClusterMessage hierarchy) ----

@dataclass(frozen=True)
class Join:
    node: UniqueAddress
    roles: FrozenSet[str] = frozenset()
    # digest of the joiner's cluster-critical config; the first contact
    # node refuses mismatches (reference: JoinConfigCompatChecker.scala:18)
    config_digest: str = ""


@dataclass(frozen=True)
class JoinRefused:
    """Join denied — incompatible configuration (the reference replies
    IncompatibleConfig and the joiner logs + gives up)."""
    from_node: UniqueAddress
    reason: str


@dataclass(frozen=True)
class Welcome:
    from_node: UniqueAddress
    gossip: Gossip


@dataclass(frozen=True)
class GossipEnvelope:
    from_node: UniqueAddress
    gossip: Gossip


@dataclass(frozen=True)
class ClusterHeartbeat:
    from_node: UniqueAddress


@dataclass(frozen=True)
class ClusterHeartbeatRsp:
    from_node: UniqueAddress


@dataclass(frozen=True)
class LeaveCmd:
    address_str: str


@dataclass(frozen=True)
class DownCmd:
    address_str: str


@dataclass(frozen=True)
class JoinTo:
    """Local command: send Join to this address (seed or explicit join)."""
    address_str: str


@dataclass(frozen=True)
class JoinSeedNodes:
    """Local command: join the first reachable seed, retrying and rotating
    through the list (reference: cluster/SeedNodeProcess.scala)."""
    seeds: tuple


class _JoinRetryTick:
    pass


class _GossipTick:
    pass


class _LeaderActionsTick:
    pass


class _ReapTick:
    pass


class _HeartbeatTick:
    pass


class ClusterCoreDaemon(Actor):
    def __init__(self, cluster):
        super().__init__()
        self.cluster = cluster
        self.self_node: UniqueAddress = cluster.self_unique_address
        self.roles: FrozenSet[str] = cluster.self_roles
        # multi-DC: leader actions / heartbeat ring / reaping are PER-DC
        # (CrossDcClusterHeartbeat.scala:39; one DC per TPU slice/pod)
        self.dc: str = getattr(cluster, "self_data_center", "default")
        self._cross_dc = getattr(cluster, "cross_dc_settings",
                                 {"monitoring_members": 2,
                                  "interval_factor": 3})
        self._hb_tick_count = 0
        self.gossip = Gossip()
        self.fd = FailureDetectorRegistry(cluster.fd_factory)
        self._tasks = []
        self._published: Dict[UniqueAddress, MemberStatus] = {}
        self._published_unreachable: FrozenSet[UniqueAddress] = frozenset()
        self._published_leader: Optional[UniqueAddress] = None
        self._removed = False

    # -- lifecycle ------------------------------------------------------------
    def pre_start(self) -> None:
        s = self.context.system.scheduler
        cfg = self.cluster.settings
        self._tasks = [
            s.schedule_tell_with_fixed_delay(cfg["gossip_interval"],
                                             cfg["gossip_interval"],
                                             self.self_ref, _GossipTick()),
            s.schedule_tell_with_fixed_delay(cfg["leader_actions_interval"],
                                             cfg["leader_actions_interval"],
                                             self.self_ref, _LeaderActionsTick()),
            s.schedule_tell_with_fixed_delay(cfg["reaper_interval"],
                                             cfg["reaper_interval"],
                                             self.self_ref, _ReapTick()),
            s.schedule_tell_with_fixed_delay(cfg["heartbeat_interval"],
                                             cfg["heartbeat_interval"],
                                             self.self_ref, _HeartbeatTick()),
        ]

    def post_stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        self._stop_join_retry()

    # -- receive --------------------------------------------------------------
    def receive(self, message: Any):
        if isinstance(message, _GossipTick):
            self._gossip_tick()
        elif isinstance(message, _LeaderActionsTick):
            self._leader_actions()
        elif isinstance(message, _ReapTick):
            self._reap_unreachable()
        elif isinstance(message, _HeartbeatTick):
            self._heartbeat_tick()
        elif isinstance(message, Join):
            self._joining(message.node, message.roles,
                          getattr(message, "config_digest", ""))
        elif isinstance(message, JoinRefused):
            self._join_refused(message)
        elif isinstance(message, Welcome):
            self._welcome(message)
        elif isinstance(message, GossipEnvelope):
            self._receive_gossip(message)
        elif isinstance(message, ClusterHeartbeat):
            self._send_to(message.from_node, ClusterHeartbeatRsp(self.self_node))
        elif isinstance(message, ClusterHeartbeatRsp):
            self.fd.heartbeat(message.from_node.address_str)
        elif isinstance(message, JoinTo):
            self._start_join((message.address_str,))
        elif isinstance(message, JoinSeedNodes):
            self._start_join(tuple(message.seeds))
        elif isinstance(message, _JoinRetryTick):
            self._join_retry()
        elif isinstance(message, LeaveCmd):
            self._leaving(message.address_str)
        elif isinstance(message, DownCmd):
            self._downing(message.address_str)
        elif message == "get-state":
            self.sender.tell(self._current_state(), self.self_ref)
        else:
            return NotImplemented
        return None

    # -- join (reference: ClusterDaemon.joining :735; retry semantics per
    # SeedNodeProcess — a single Join may be dropped or arrive before the
    # target has self-joined, so resend until welcomed) -----------------------
    def _start_join(self, seeds: tuple) -> None:
        self._join_seeds = seeds
        self._join_idx = 0
        if getattr(self, "_join_retry_task", None) is None:
            interval = self.cluster.settings.get(
                "retry_unsuccessful_join_after", 0.25)
            self._join_retry_task = \
                self.context.system.scheduler.schedule_tell_with_fixed_delay(
                    interval, interval, self.self_ref, _JoinRetryTick())
        self._join_to(seeds[0])

    def _join_retry(self) -> None:
        if self.gossip.has_member(self.self_node):
            self._stop_join_retry()
            return
        seeds = getattr(self, "_join_seeds", ())
        if not seeds:
            self._stop_join_retry()
            return
        self._join_idx = (self._join_idx + 1) % len(seeds)
        self._join_to(seeds[self._join_idx])

    def _stop_join_retry(self) -> None:
        task = getattr(self, "_join_retry_task", None)
        if task is not None:
            task.cancel()
        self._join_retry_task = None
        self._join_seeds = ()

    def _join_to(self, address_str: str) -> None:
        if address_str == self.self_node.address_str:
            # join self: become the first member of a new cluster
            if not self.gossip.has_member(self.self_node):
                m = Member(self.self_node, MemberStatus.JOINING, self.roles)
                self.gossip = (self.gossip.with_member(m)
                               .bump(self.self_node)
                               .seen_by(self.self_node))
                self._publish_changes()
            self._stop_join_retry()
        else:
            self._send_to_addr(address_str,
                               Join(self.self_node, self.roles,
                                    self.cluster.config_digest))

    def _joining(self, node: UniqueAddress, roles: FrozenSet[str],
                 config_digest: str = "") -> None:
        if not self.gossip.has_member(self.self_node):
            return  # not yet a member ourselves; joiner will retry
        # configuration compatibility check at first contact (reference:
        # JoinConfigCompatChecker.scala:18 + ClusterDaemon joining's
        # validateJoin): a node with incompatible cluster-critical config
        # is refused with a logged reason, never admitted
        if (self.cluster.enforce_config_compat and config_digest
                and config_digest != self.cluster.config_digest):
            reason = (f"incompatible cluster configuration from {node}: "
                      f"digest {config_digest[:12]} != "
                      f"{self.cluster.config_digest[:12]} over "
                      f"{self.cluster.config_compat_paths}")
            self._log_warning(reason)
            self._send_to(node, JoinRefused(self.self_node, reason))
            return
        existing = self.gossip.member(node)
        if existing is not None and existing.status is not MemberStatus.REMOVED:
            self._send_to(node, Welcome(self.self_node, self.gossip))
            return
        # restarted incarnation of same address: remove the old member first
        for m in list(self.gossip.members):
            if m.address_str == node.address_str and m.unique_address != node:
                self.gossip = self.gossip.without_member(m)
        m = Member(node, MemberStatus.JOINING, roles)
        self.gossip = (self.gossip.with_member(m)
                       .bump(self.self_node)
                       .only_seen_by(self.self_node))
        self._publish_changes()
        self._send_to(node, Welcome(self.self_node, self.gossip))

    def _join_refused(self, msg: JoinRefused) -> None:
        """The contact node rejected our config: log loudly and STOP
        retrying (an operator must fix the config; hammering the seed with
        doomed joins helps nobody)."""
        self._log_warning(
            f"join refused by {msg.from_node}: {msg.reason}")
        self._stop_join_retry()
        self.cluster.join_refused_reason = msg.reason

    def _log_warning(self, text: str) -> None:
        from ..event.logging import Warning as _Warning
        self.context.system.event_stream.publish(
            _Warning(str(self.self_ref.path), "ClusterCoreDaemon", text))

    def _welcome(self, w: Welcome) -> None:
        if not w.gossip.has_member(self.self_node):
            return
        self.gossip = w.gossip.seen_by(self.self_node)
        self._publish_changes()
        self._gossip_to(w.from_node)

    # -- gossip (reference: receiveGossip :994, gossipTick :1116) --------------
    def _gossip_tick(self) -> None:
        peers = [m.unique_address for m in self.gossip.members
                 if m.unique_address != self.self_node
                 and m.status not in (MemberStatus.REMOVED,)
                 and self.gossip.reachability.is_reachable(m.unique_address)]
        if not peers:
            return
        # prefer peers that haven't seen our version (faster convergence;
        # reference: gossip target selection probabilities)
        unseen = [p for p in peers if p not in self.gossip.seen]
        target = random.choice(unseen if unseen else peers)
        self._gossip_to(target)

    def _gossip_to(self, node: UniqueAddress) -> None:
        self.gossip = self.gossip.seen_by(self.self_node)
        self._send_to(node, GossipEnvelope(self.self_node, self.gossip))

    def _receive_gossip(self, env: GossipEnvelope) -> None:
        if self._removed:
            return
        remote = env.gossip
        if env.from_node in self.gossip.tombstones:
            return  # stale gossip from a removed incarnation
        if self.self_node in remote.tombstones:
            self._self_removed()
            return
        if not remote.has_member(self.self_node):
            # we were removed from the cluster's view
            me = self.gossip.member(self.self_node)
            if me is not None and me.status in (MemberStatus.EXITING,
                                                MemberStatus.DOWN,
                                                MemberStatus.LEAVING):
                self._self_removed()
            return
        cmp = self.gossip.version.compare(remote.version)
        if cmp.value == "Same":
            self.gossip = replace(
                self.gossip,
                seen=self.gossip.seen | remote.seen | {self.self_node})
        elif cmp.value == "Before":
            self.gossip = remote.seen_by(self.self_node)
        elif cmp.value == "After":
            self._gossip_to(env.from_node)  # we know more; push back
            return
        else:  # concurrent
            self.gossip = self.gossip.merge(remote).seen_by(self.self_node)
        self._publish_changes()
        # reply if sender hasn't seen what we now have
        if env.from_node not in self.gossip.seen:
            self._gossip_to(env.from_node)
        me = self.gossip.member(self.self_node)
        if me is not None and me.status is MemberStatus.REMOVED:
            self._self_removed()

    # -- leader actions (reference: leaderActions :1166, :1245) ----------------
    def _leader_actions(self) -> None:
        if self._removed or not self.gossip.members:
            return
        # per-DC leadership: each data center's (lowest-address) leader
        # promotes/removes ITS OWN members only (MembershipState.leaderOf)
        leader = self.gossip.leader(self.self_node, dc=self.dc)
        if leader != self.self_node:
            return
        changed = False
        removed_nodes = []
        if self.gossip.convergence(self.self_node, dc=self.dc):
            up_number = self.gossip.youngest_up_number
            for m in list(self.gossip.members):
                if m.data_center != self.dc:
                    continue
                if m.status in (MemberStatus.JOINING, MemberStatus.WEAKLY_UP):
                    up_number += 1
                    self.gossip = self.gossip.with_member(
                        m.copy_with(MemberStatus.UP, up_number=up_number))
                    changed = True
                elif m.status is MemberStatus.LEAVING:
                    self.gossip = self.gossip.with_member(
                        m.copy_with(MemberStatus.EXITING))
                    changed = True
                elif m.status in (MemberStatus.EXITING, MemberStatus.DOWN):
                    self.gossip = self.gossip.without_member(m)
                    self._publish_removed(m)
                    removed_nodes.append(m.unique_address)
                    changed = True
        elif self.cluster.settings["allow_weakly_up"]:
            # no convergence (unreachable nodes): still let joiners in weakly
            unreachable = self.gossip.reachability.all_unreachable
            for m in list(self.gossip.members):
                if (m.data_center == self.dc
                        and m.status is MemberStatus.JOINING
                        and m.unique_address not in unreachable):
                    self.gossip = self.gossip.with_member(
                        m.copy_with(MemberStatus.WEAKLY_UP))
                    changed = True
            # leader can always remove Down members it observes as unreachable?
            # reference requires convergence-among-reachable; approximate:
            reachable_seen = {n for n in self.gossip.seen if n not in unreachable}
            reachable_members = {m.unique_address for m in self.gossip.members
                                 if m.unique_address not in unreachable
                                 and m.data_center == self.dc
                                 and m.status in (MemberStatus.UP, MemberStatus.LEAVING)}
            if reachable_members <= reachable_seen:
                for m in list(self.gossip.members):
                    if m.status is MemberStatus.DOWN \
                            and m.data_center == self.dc:
                        self.gossip = self.gossip.without_member(m)
                        self._publish_removed(m)
                        removed_nodes.append(m.unique_address)
                        changed = True
        if changed:
            self.gossip = (self.gossip.bump(self.self_node)
                           .only_seen_by(self.self_node))
            self._publish_changes()
            # final notice so removed nodes learn their fate (reference:
            # ExitingCompleted hand-off; they are no longer gossip targets)
            for node in removed_nodes:
                if node != self.self_node:
                    self._send_to(node, GossipEnvelope(self.self_node, self.gossip))

    # -- heartbeats + reaping (reference: ClusterHeartbeat.scala — ring is
    # PER-DC; CrossDcClusterHeartbeat.scala:39 — the oldest members of each
    # DC also monitor the oldest members of the other DCs at a lower rate) --
    def _alive_members(self) -> list:
        return [m for m in self.gossip.members
                if m.status in (MemberStatus.JOINING, MemberStatus.WEAKLY_UP,
                                MemberStatus.UP, MemberStatus.LEAVING)]

    def _neighbors(self) -> list:
        alive = [m.unique_address for m in self._alive_members()
                 if m.unique_address != self.self_node
                 and m.data_center == self.dc]
        if not alive:
            return []
        from ..utils.hashing import stable_hash
        ring = sorted(alive + [self.self_node],
                      key=lambda n: stable_hash((n.address_str, n.uid)))
        i = ring.index(self.self_node)
        k = self.cluster.settings["monitored_by_nr_of_members"]
        out = []
        for step in range(1, len(ring)):
            if len(out) >= k:
                break
            out.append(ring[(i + step) % len(ring)])
        return out

    def _cross_dc_targets(self) -> list:
        """Other-DC nodes THIS node monitors: only when self is among the
        `cross-dc-connections` OLDEST members of its DC, and then the same
        number of oldest members of every other DC
        (CrossDcHeartbeatSender.activeReceivers semantics)."""
        k = self._cross_dc["monitoring_members"]
        by_dc: Dict[str, list] = {}
        for m in self._alive_members():
            by_dc.setdefault(m.data_center, []).append(m)
        mine = sorted(by_dc.get(self.dc, ()),
                      key=lambda m: (m.up_number, m.unique_address))
        if self.self_node not in [m.unique_address for m in mine[:k]]:
            return []
        out = []
        for dc, members in by_dc.items():
            if dc == self.dc:
                continue
            oldest = sorted(members,
                            key=lambda m: (m.up_number, m.unique_address))[:k]
            out.extend(m.unique_address for m in oldest)
        return out

    def _heartbeat_tick(self) -> None:
        self._hb_tick_count += 1
        targets = list(self._neighbors())
        if self._hb_tick_count % self._cross_dc["interval_factor"] == 0:
            # cross-DC heartbeats ride DCN at a lower rate than the
            # intra-DC (ICI-local) ring
            targets += self._cross_dc_targets()
        for n in targets:
            self._send_to(n, ClusterHeartbeat(self.self_node))
            if not self.fd.is_monitoring(n.address_str):
                # arm the detector at first send: a neighbor that NEVER
                # responds must still become unreachable (the phi estimator
                # bootstraps from first-heartbeat-estimate)
                self.fd.heartbeat(n.address_str)

    def _reap_unreachable(self) -> None:
        if self._removed:
            return
        changed = False
        monitored = set(self._neighbors()) | set(self._cross_dc_targets())
        currently_unreachable = self.gossip.reachability.all_unreachable_from(
            self.self_node)
        for n in monitored:
            addr = n.address_str
            if not self.fd.is_monitoring(addr):
                continue
            if not self.fd.is_available(addr) and n not in currently_unreachable:
                self.gossip = replace(
                    self.gossip, seen=frozenset({self.self_node}),
                    reachability=self.gossip.reachability.unreachable(
                        self.self_node, n)).bump(self.self_node)
                changed = True
        for n in currently_unreachable:
            addr = n.address_str
            if self.fd.is_monitoring(addr) and self.fd.is_available(addr):
                self.gossip = replace(
                    self.gossip, seen=frozenset({self.self_node}),
                    reachability=self.gossip.reachability.reachable(
                        self.self_node, n)).bump(self.self_node)
                changed = True
        if changed:
            self._publish_changes()

    # -- leave / down (reference: leaving :834, downing) -----------------------
    def _leaving(self, address_str: str) -> None:
        for m in self.gossip.members:
            if m.address_str == address_str and m.status in (
                    MemberStatus.JOINING, MemberStatus.WEAKLY_UP, MemberStatus.UP):
                self.gossip = (self.gossip.with_member(m.copy_with(MemberStatus.LEAVING))
                               .bump(self.self_node)
                               .only_seen_by(self.self_node))
                self._publish_changes()
                return

    def _downing(self, address_str: str) -> None:
        for m in self.gossip.members:
            if m.address_str == address_str and m.status not in (
                    MemberStatus.DOWN, MemberStatus.REMOVED):
                self.gossip = (self.gossip.with_member(m.copy_with(MemberStatus.DOWN))
                               .bump(self.self_node)
                               .only_seen_by(self.self_node))
                self._publish_changes()  # publishes the MemberDowned event
                if m.unique_address == self.self_node:
                    self._self_removed()
                return

    def _self_removed(self) -> None:
        if self._removed:
            return
        self._removed = True
        me = self.gossip.member(self.self_node)
        prev = me.status if me is not None else MemberStatus.REMOVED
        self.context.system.event_stream.publish(MemberRemoved(
            Member(self.self_node, MemberStatus.REMOVED, self.roles), prev))
        self.cluster._on_self_removed()

    # -- event publication -----------------------------------------------------
    def _current_state(self) -> CurrentClusterState:
        unreachable = frozenset(
            m for m in self.gossip.members
            if m.unique_address in self.gossip.reachability.all_unreachable)
        return CurrentClusterState(
            members=self.gossip.members, unreachable=unreachable,
            leader=self.gossip.leader(self.self_node, dc=self.dc),
            seen_by=self.gossip.seen)

    def _publish_removed(self, m: Member) -> None:
        self.context.system.event_stream.publish(
            MemberRemoved(Member(m.unique_address, MemberStatus.REMOVED, m.roles),
                          m.status))
        self._published.pop(m.unique_address, None)

    def _publish_changes(self) -> None:
        es = self.context.system.event_stream
        self.cluster._latest_state = self._current_state()
        for m in self.gossip.members:
            prev = self._published.get(m.unique_address)
            if prev == m.status:
                continue
            self._published[m.unique_address] = m.status
            if m.status is MemberStatus.JOINING:
                es.publish(MemberJoined(m))
            elif m.status is MemberStatus.WEAKLY_UP:
                es.publish(MemberWeaklyUp(m))
            elif m.status is MemberStatus.UP:
                es.publish(MemberUp(m))
            elif m.status is MemberStatus.LEAVING:
                es.publish(MemberLeft(m))
            elif m.status is MemberStatus.EXITING:
                es.publish(MemberExited(m))
            elif m.status is MemberStatus.DOWN:
                es.publish(MemberDowned(m))
        # removed members no longer in gossip
        current = {m.unique_address for m in self.gossip.members}
        for node in list(self._published):
            if node not in current:
                status = self._published.pop(node)
                es.publish(MemberRemoved(
                    Member(node, MemberStatus.REMOVED), status))
        # reachability diffs
        unreachable = frozenset(n for n in self.gossip.reachability.all_unreachable
                                if self.gossip.has_member(n))
        for n in unreachable - self._published_unreachable:
            m = self.gossip.member(n)
            if m is not None:
                es.publish(UnreachableMember(m))
        for n in self._published_unreachable - unreachable:
            m = self.gossip.member(n)
            if m is not None:
                es.publish(ReachableMember(m))
        self._published_unreachable = unreachable
        # leader
        leader = self.gossip.leader(self.self_node, dc=self.dc)
        if leader != self._published_leader:
            self._published_leader = leader
            es.publish(LeaderChanged(leader))

    # -- wire helpers ----------------------------------------------------------
    def _send_to(self, node: UniqueAddress, message: Any) -> None:
        self._send_to_addr(node.address_str, message)

    def _send_to_addr(self, address_str: str, message: Any) -> None:
        provider = self.context.system.provider
        ref = provider.resolve_actor_ref(f"{address_str}/system/cluster")
        ref.tell(message, self.self_ref)
