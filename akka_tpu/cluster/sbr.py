"""Split-brain resolver: downing strategies applied after a stable period.

Reference parity: akka-cluster/src/main/scala/akka/cluster/sbr/
SplitBrainResolver.scala (:96 actor, :134 stable-after logic, :536 strategy
selection) and sbr/DowningStrategy.scala — keep-majority, static-quorum,
keep-oldest, down-all. A side that decides it lost downs ITSELF (both sides
decide independently and deterministically, so exactly one survives).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, FrozenSet, List, Optional, Set

from ..actor.actor import Actor
from .events import (ClusterDomainEvent, MemberEvent, ReachabilityEvent,
                     ReachableMember, UnreachableMember)
from .member import Member, MemberStatus, UniqueAddress

_CONSIDERED = {MemberStatus.UP, MemberStatus.LEAVING, MemberStatus.EXITING}


@dataclass
class Decision:
    down_nodes: List[UniqueAddress]
    # True = not decided yet; the resolver must keep the deadline armed and
    # re-invoke on the next tick (lease-majority's minority delay)
    retry: bool = False


class DowningStrategy:
    """`decide` sees members (considered statuses only), the unreachable set,
    and this node; returns the nodes THIS side should down."""

    def decide(self, members: List[Member], unreachable: Set[UniqueAddress],
               self_node: UniqueAddress) -> Decision:
        raise NotImplementedError

    @staticmethod
    def _sides(members, unreachable):
        reachable = [m for m in members if m.unique_address not in unreachable]
        lost = [m for m in members if m.unique_address in unreachable]
        return reachable, lost

    @staticmethod
    def _down_side(side) -> Decision:
        return Decision([m.unique_address for m in side])


class KeepMajority(DowningStrategy):
    """(reference: DowningStrategy.KeepMajority — ties broken by lowest
    address, which is deterministic on both sides)"""

    def decide(self, members, unreachable, self_node):
        reachable, lost = self._sides(members, unreachable)
        if not lost:
            return Decision([])
        if len(reachable) > len(lost):
            return self._down_side(lost)
        if len(reachable) < len(lost):
            return self._down_side(reachable)  # we lost; down our own side
        # tie: the side holding the lowest address survives
        lowest = min(m.unique_address for m in members)
        if any(m.unique_address == lowest for m in reachable):
            return self._down_side(lost)
        return self._down_side(reachable)


class StaticQuorum(DowningStrategy):
    def __init__(self, quorum_size: int):
        self.quorum_size = quorum_size

    def decide(self, members, unreachable, self_node):
        reachable, lost = self._sides(members, unreachable)
        if not lost:
            return Decision([])
        if len(reachable) >= self.quorum_size:
            return self._down_side(lost)
        return self._down_side(reachable)


class KeepOldest(DowningStrategy):
    def __init__(self, down_if_alone: bool = True):
        self.down_if_alone = down_if_alone

    def decide(self, members, unreachable, self_node):
        reachable, lost = self._sides(members, unreachable)
        if not lost or not members:
            return Decision([])
        oldest = min(members, key=lambda m: (m.up_number, m.unique_address))
        oldest_is_here = any(m.unique_address == oldest.unique_address
                             for m in reachable)
        if oldest_is_here:
            if self.down_if_alone and len(reachable) == 1 and len(lost) >= 1:
                return self._down_side(reachable)  # oldest alone: sacrifice it
            return self._down_side(lost)
        return self._down_side(reachable)


class DownAll(DowningStrategy):
    def decide(self, members, unreachable, self_node):
        return Decision([m.unique_address for m in members])


class LeaseMajority(DowningStrategy):
    """The side that ACQUIRES the lease survives (reference:
    SplitBrainResolver.scala:45-55 acquire/release plumbing +
    DowningStrategy.LeaseMajority): only each side's lowest-address
    reachable node races for the lease — on success it downs the other
    side, on failure it downs its OWN side; the rest of its side follows
    the downing through gossip. The MINORITY side delays its acquire
    attempt (the reference's acquire-lease-delay-for-minority) so a
    symmetric partition deterministically favors the majority instead of
    a coin-flip race. Works across real processes with the `file` lease
    backend."""

    def __init__(self, lease_factory, acquire_delay_for_minority: float = 2.0):
        # factory: () -> Lease — deferred so the owner name can carry the
        # node address and the lease is only created when SBR fires
        self._lease_factory = lease_factory
        self._lease = None
        self.acquire_delay_for_minority = acquire_delay_for_minority
        self._deferred_until: Optional[float] = None

    def decide(self, members, unreachable, self_node):
        reachable, lost = self._sides(members, unreachable)
        if not lost or not reachable:
            return Decision([])
        decider = min(m.unique_address for m in reachable)
        if self_node != decider:
            return Decision([])  # our side's decider acts; downs gossip in
        is_minority = len(reachable) < len(lost) or (
            len(reachable) == len(lost)
            and min(m.unique_address for m in members) not in
            {m.unique_address for m in reachable})
        if is_minority:
            now = time.monotonic()
            if self._deferred_until is None:
                self._deferred_until = now + self.acquire_delay_for_minority
            if now < self._deferred_until:
                return Decision([], retry=True)  # majority gets a head start
        self._deferred_until = None
        if self._lease is None:
            self._lease = self._lease_factory()
        if self._lease.acquire():
            return self._down_side(lost)
        return self._down_side(reachable)

    def reset(self) -> None:
        """Partition healed without a decision: clear the episode state so
        the NEXT partition's minority delay starts fresh (a stale expired
        _deferred_until would skip the delay entirely)."""
        self._deferred_until = None

    def release(self) -> None:
        if self._lease is not None:
            self._lease.release()


def strategy_from_config(cfg, system=None, self_owner: str = ""
                         ) -> DowningStrategy:
    """(reference: SplitBrainResolver.scala:536 strategy selection)"""
    name = cfg.get_string("active-strategy", "keep-majority")
    if name == "keep-majority":
        return KeepMajority()
    if name == "static-quorum":
        return StaticQuorum(cfg.get_int("static-quorum.quorum-size", 1))
    if name == "keep-oldest":
        return KeepOldest(cfg.get_bool("keep-oldest.down-if-alone", True))
    if name == "down-all":
        return DownAll()
    if name == "lease-majority":
        if system is None:
            raise ValueError("lease-majority needs the actor system")
        lease_name = cfg.get_string(
            "lease-majority.lease-name",
            f"{system.name}-akka-sbr")

        def factory():
            from ..cluster_tools.lease import LeaseProvider
            return LeaseProvider.get(system).get_lease(
                lease_name, "akka.cluster.split-brain-resolver.lease-majority",
                self_owner)
        return LeaseMajority(factory, cfg.get_duration(
            "lease-majority.acquire-lease-delay-for-minority", 2.0))
    raise ValueError(f"unknown split-brain-resolver strategy {name!r}")


class SplitBrainResolver(Actor):
    """Subscribes to reachability events; after `stable_after` seconds of an
    unchanged unreachable set, applies the strategy and downs the losers."""

    class _Tick:
        pass

    def __init__(self, cluster, strategy: DowningStrategy, stable_after: float,
                 tick_interval: float = 0.25):
        super().__init__()
        self.cluster = cluster
        self.strategy = strategy
        self.stable_after = stable_after
        self.tick_interval = tick_interval
        self._unreachable: Set[UniqueAddress] = set()
        self._deadline: Optional[float] = None
        self._task = None
        # when a lease-backed strategy acquires, release it AFTER a safety
        # margin (reference: SplitBrainResolver.scala:45-55 releases the
        # lease once the resolution settles; releasing immediately would
        # let the doomed side acquire and down the survivors, holding it
        # forever poisons the NEXT partition's decision)
        self._release_at: Optional[float] = None

    def pre_start(self) -> None:
        self._sub = lambda e: self.self_ref.tell(e)
        self.context.system.event_stream.subscribe(self._sub, ReachabilityEvent)
        self._task = self.context.system.scheduler.schedule_tell_with_fixed_delay(
            self.tick_interval, self.tick_interval, self.self_ref, self._Tick())

    def post_stop(self) -> None:
        self.context.system.event_stream.unsubscribe(self._sub)
        if self._task is not None:
            self._task.cancel()

    def _reset_strategy(self) -> None:
        """Any reachability change restarts the stability window — stateful
        strategies (lease-majority's minority acquire delay) must restart
        their episode state WITH it, or a flap mid-delay would let the
        delay expire unobserved and reinstate the symmetric lease race."""
        reset = getattr(self.strategy, "reset", None)
        if reset is not None:
            reset()

    def receive(self, message: Any):
        if isinstance(message, UnreachableMember):
            # SBR is PER-DC (the reference's SBR only acts within its own
            # data center; cross-DC unreachability — e.g. a DCN partition
            # between slices — must NOT down an independently-healthy DC)
            my_dc = getattr(self.cluster, "self_data_center", "default")
            if message.member.data_center != my_dc:
                return None
            self._unreachable.add(message.member.unique_address)
            self._deadline = time.monotonic() + self.stable_after
            self._reset_strategy()
        elif isinstance(message, ReachableMember):
            self._unreachable.discard(message.member.unique_address)
            self._deadline = (time.monotonic() + self.stable_after
                              if self._unreachable else None)
            self._reset_strategy()
        elif isinstance(message, self._Tick):
            if (self._deadline is not None and self._unreachable
                    and time.monotonic() >= self._deadline):
                self._act()
            if self._release_at is not None \
                    and time.monotonic() >= self._release_at:
                self._release_at = None
                release = getattr(self.strategy, "release", None)
                if release is not None:
                    release()
        else:
            return NotImplemented
        return None

    def _act(self) -> None:
        state = self.cluster.state
        my_dc = getattr(self.cluster, "self_data_center", "default")
        members = [m for m in state.members if m.status in _CONSIDERED
                   and m.data_center == my_dc]
        if not members:
            self._deadline = None
            return
        decision = self.strategy.decide(
            members, set(self._unreachable), self.cluster.self_unique_address)
        if decision.retry:
            # not decided yet (minority acquire delay): re-check next tick
            self._deadline = time.monotonic() + self.tick_interval
            return
        for node in decision.down_nodes:
            self.cluster.down(node.address_str)
        if decision.down_nodes and hasattr(self.strategy, "release"):
            # hold the lease past the losing side's own decision window,
            # then free it for future partitions
            self._release_at = time.monotonic() + 2 * self.stable_after + 2.0
        self._deadline = None
        self._unreachable -= set(decision.down_nodes)
