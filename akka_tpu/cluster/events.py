"""Cluster domain events published on the event stream.

Reference parity: akka-cluster/src/main/scala/akka/cluster/ClusterEvent.scala —
MemberJoined/MemberWeaklyUp/MemberUp/MemberLeft/MemberExited/MemberRemoved/
MemberDowned, UnreachableMember/ReachableMember, LeaderChanged,
CurrentClusterState snapshot for subscribe-with-initial-state.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import FrozenSet, Optional, Tuple

from .member import Member, MemberStatus, UniqueAddress


class ClusterDomainEvent:
    pass


@dataclass(frozen=True)
class MemberEvent(ClusterDomainEvent):
    member: Member


@dataclass(frozen=True)
class MemberJoined(MemberEvent):
    pass


@dataclass(frozen=True)
class MemberWeaklyUp(MemberEvent):
    pass


@dataclass(frozen=True)
class MemberUp(MemberEvent):
    pass


@dataclass(frozen=True)
class MemberLeft(MemberEvent):
    pass


@dataclass(frozen=True)
class MemberExited(MemberEvent):
    pass


@dataclass(frozen=True)
class MemberDowned(MemberEvent):
    pass


@dataclass(frozen=True)
class MemberRemoved(MemberEvent):
    previous_status: MemberStatus = MemberStatus.REMOVED


@dataclass(frozen=True)
class ReachabilityEvent(ClusterDomainEvent):
    member: Member


@dataclass(frozen=True)
class UnreachableMember(ReachabilityEvent):
    pass


@dataclass(frozen=True)
class ReachableMember(ReachabilityEvent):
    pass


@dataclass(frozen=True)
class LeaderChanged(ClusterDomainEvent):
    leader: Optional[UniqueAddress]


@dataclass(frozen=True)
class CurrentClusterState(ClusterDomainEvent):
    """Snapshot sent on subscribe (reference: ClusterEvent.CurrentClusterState)."""
    members: Tuple[Member, ...] = ()
    unreachable: FrozenSet[Member] = frozenset()
    leader: Optional[UniqueAddress] = None
    seen_by: FrozenSet[UniqueAddress] = frozenset()

    @property
    def up_members(self) -> Tuple[Member, ...]:
        return tuple(m for m in self.members if m.status is MemberStatus.UP)
