"""Gossip state: members + seen-set + reachability, versioned by vector clock.

Reference parity: akka-cluster/src/main/scala/akka/cluster/Gossip.scala
(members sorted set, overview.seen, overview.reachability, version) and
MembershipState.convergence (cluster/MembershipState.scala:56): convergence
when every Up/Leaving member has seen this gossip version and no members are
unreachable (unreachable Down/Exiting members don't block).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import FrozenSet, Iterable, Optional, Tuple

from .member import Member, MemberStatus, UniqueAddress
from .reachability import Reachability
from .vector_clock import Ordering, VectorClock

# statuses counted for convergence seen-set (reference: Gossip.convergence)
_CONVERGENCE_STATUSES = {MemberStatus.UP, MemberStatus.LEAVING}
# statuses whose unreachability doesn't block convergence
_CONVERGENCE_SKIP_UNREACHABLE = {MemberStatus.DOWN, MemberStatus.EXITING}


@dataclass(frozen=True)
class Gossip:
    members: Tuple[Member, ...] = ()
    seen: FrozenSet[UniqueAddress] = frozenset()
    reachability: Reachability = field(default_factory=Reachability)
    version: VectorClock = field(default_factory=VectorClock)
    # removed members, kept so merges with stale gossip can't resurrect them
    # (reference: Gossip.tombstones, Gossip.scala)
    tombstones: FrozenSet[UniqueAddress] = frozenset()

    # -- membership ----------------------------------------------------------
    def member(self, node: UniqueAddress) -> Optional[Member]:
        for m in self.members:
            if m.unique_address == node:
                return m
        return None

    def has_member(self, node: UniqueAddress) -> bool:
        return self.member(node) is not None

    def with_member(self, member: Member) -> "Gossip":
        if member.unique_address in self.tombstones:
            return self
        others = tuple(m for m in self.members if m != member)
        return replace(self, members=tuple(sorted(others + (member,))))

    def without_member(self, member: Member) -> "Gossip":
        return replace(
            self,
            members=tuple(m for m in self.members if m != member),
            seen=frozenset(s for s in self.seen if s != member.unique_address),
            reachability=self.reachability.remove([member.unique_address]),
            version=self.version.prune(_vnode(member.unique_address)),
            tombstones=self.tombstones | {member.unique_address})

    # -- seen-set ------------------------------------------------------------
    def seen_by(self, node: UniqueAddress) -> "Gossip":
        return replace(self, seen=self.seen | {node})

    def only_seen_by(self, node: UniqueAddress) -> "Gossip":
        return replace(self, seen=frozenset({node}))

    # -- versioning ----------------------------------------------------------
    def bump(self, node: UniqueAddress) -> "Gossip":
        return replace(self, version=self.version.bump(_vnode(node)))

    def merge(self, other: "Gossip") -> "Gossip":
        """(reference: Gossip.merge — vclock merge, member union keeping the
        'larger' lifecycle status, reachability merge, empty seen)"""
        version = self.version.merge(other.version)
        tombstones = self.tombstones | other.tombstones
        by_addr = {}
        for m in self.members + other.members:
            if m.unique_address in tombstones:
                continue
            cur = by_addr.get(m.unique_address)
            by_addr[m.unique_address] = m if cur is None else _pick_highest(cur, m)
        members = tuple(sorted(by_addr.values()))
        return Gossip(members=members, seen=frozenset(),
                      reachability=self.reachability.merge(
                          other.reachability).remove(tombstones),
                      version=version, tombstones=tombstones)

    def compare(self, other: "Gossip") -> Ordering:
        return self.version.compare(other.version)

    # -- convergence + leader (reference: MembershipState.scala:56) -----------
    def convergence(self, self_node: UniqueAddress,
                    dc: Optional[str] = None) -> bool:
        """With `dc`, PER-DC convergence (the reference's MembershipState
        convergence over dcMembers): only members of that DC must have seen
        the gossip, and only that DC's unreachables block — a cross-DC
        partition must not freeze a healthy DC's leader."""
        unreachable = {n for n in self.reachability.all_unreachable
                       if n != self_node}
        for n in unreachable:
            m = self.member(n)
            if m is not None and m.status not in _CONVERGENCE_SKIP_UNREACHABLE \
                    and (dc is None or m.data_center == dc):
                return False
        for m in self.members:
            if dc is not None and m.data_center != dc:
                continue
            if m.status in _CONVERGENCE_STATUSES and m.unique_address not in self.seen:
                return False
        return True

    def leader(self, self_node: UniqueAddress,
               dc: Optional[str] = None) -> Optional[UniqueAddress]:
        """First reachable member allowed to lead (reference:
        MembershipState.leader — Up/Leaving preferred, else Joining/WeaklyUp).
        With `dc`, the PER-DATA-CENTER leader (MembershipState.leaderOf over
        the dcMembers subset): every DC runs its own leader actions."""
        pool = self.members if dc is None else [
            m for m in self.members if m.data_center == dc]
        candidates = [m for m in pool
                      if m.status in (MemberStatus.UP, MemberStatus.LEAVING)
                      and (m.unique_address == self_node
                           or self.reachability.is_reachable(m.unique_address))]
        if not candidates:
            candidates = [m for m in pool
                          if m.status in (MemberStatus.JOINING, MemberStatus.WEAKLY_UP)
                          and (m.unique_address == self_node
                               or self.reachability.is_reachable(m.unique_address))]
        return min(candidates).unique_address if candidates else None

    @property
    def youngest_up_number(self) -> int:
        nums = [m.up_number for m in self.members if m.up_number < 2**31 - 1]
        return max(nums, default=0)

    def __repr__(self) -> str:
        ms = ", ".join(f"{m.address_str}:{m.status.value}" for m in self.members)
        return f"Gossip([{ms}], seen={len(self.seen)}, {self.version!r})"


def _vnode(node: UniqueAddress) -> str:
    return f"{node.address_str}-{node.uid}"


_STATUS_RANK = {MemberStatus.JOINING: 0, MemberStatus.WEAKLY_UP: 1,
                MemberStatus.UP: 2, MemberStatus.LEAVING: 3,
                MemberStatus.EXITING: 4, MemberStatus.DOWN: 5,
                MemberStatus.REMOVED: 6}


def _pick_highest(a: Member, b: Member) -> Member:
    """Merge two views of the same member: furthest-along lifecycle wins
    (reference: Member.highestPriorityOf)."""
    ra, rb = _STATUS_RANK[a.status], _STATUS_RANK[b.status]
    if ra == rb:
        return a if a.up_number <= b.up_number else b
    return a if ra > rb else b
