"""Cluster extension: the per-system membership façade.

Reference parity: akka-cluster/src/main/scala/akka/cluster/Cluster.scala —
`Cluster(system)` extension exposing join/joinSeedNodes/leave/down, subscribe
with initial-state snapshot, selfMember/state, registerOnMemberUp; the daemon
hierarchy at /system/cluster (ClusterDaemon.scala:312); seed-node process
(SeedNodeProcess.scala, simplified: join the first seed, self-join if we ARE
the first seed); SBR wired per sbr/SplitBrainResolver.scala.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional

from ..actor.path import Address
from ..actor.props import Props
from ..actor.system import ActorSystem, CoordinatedShutdown, ExtensionId
from ..pattern.ask import ask_sync
from ..remote.failure_detector import PhiAccrualFailureDetector
from .daemon import ClusterCoreDaemon, DownCmd, JoinTo, LeaveCmd
from .events import CurrentClusterState, MemberRemoved, MemberUp
from .member import Member, MemberStatus, UniqueAddress
from .sbr import SplitBrainResolver, strategy_from_config


class Cluster:
    """Obtain via Cluster.get(system)."""

    _instances: dict = {}
    _lock = threading.Lock()

    @staticmethod
    def get(system: ActorSystem) -> "Cluster":
        with Cluster._lock:
            inst = Cluster._instances.get(system)
            if inst is None:
                inst = Cluster._instances[system] = Cluster(system)
            return inst

    def __init__(self, system: ActorSystem):
        provider = system.provider
        if not hasattr(provider, "local_address") or provider.local_address is None:
            raise RuntimeError(
                "Cluster requires akka.actor.provider = remote|cluster")
        self.system = system
        cfg = system.settings.config.get_config("akka.cluster")
        fd_cfg = cfg.get_config("failure-detector")
        self.self_unique_address = UniqueAddress(
            str(provider.local_address), provider.uid)
        # the data center rides the roles set as `dc-<name>` (reference:
        # ClusterSettings.DcRolePrefix; multi-DC membership per
        # CrossDcClusterHeartbeat.scala / MembershipState per-DC logic).
        # Natural TPU mapping: one DC per slice/pod, DCN between DCs.
        self.self_data_center = cfg.get_string(
            "multi-data-center.self-data-center", "default")
        user_roles = frozenset(cfg.get("roles", []) or [])
        reserved = [r for r in user_roles if r.startswith("dc-")]
        if reserved:
            # the dc- prefix is RESERVED for the data-center encoding
            # (reference: ClusterSettings requires roles not start with
            # the DcRolePrefix); a second dc- role would make
            # Member.data_center ambiguous
            raise ValueError(
                f"cluster roles must not use the reserved 'dc-' prefix "
                f"(got {reserved}); set "
                f"akka.cluster.multi-data-center.self-data-center instead")
        self.self_roles = user_roles | \
            frozenset({f"dc-{self.self_data_center}"})
        mdc = cfg.get_config("multi-data-center")
        self.cross_dc_settings = {
            "monitoring_members": mdc.get_int(
                "cross-dc-connections", 2),
            "interval_factor": max(1, mdc.get_int(
                "cross-dc-heartbeat-interval-factor", 3)),
        }
        self.fd_factory = lambda: PhiAccrualFailureDetector(
            threshold=fd_cfg.get_float("threshold", 8.0),
            max_sample_size=fd_cfg.get_int("max-sample-size", 1000),
            min_std_deviation=fd_cfg.get_duration("min-std-deviation", "100ms"),
            acceptable_heartbeat_pause=fd_cfg.get_duration(
                "acceptable-heartbeat-pause", "3s"),
            first_heartbeat_estimate=fd_cfg.get_duration(
                "expected-first-heartbeat-estimate", "1s"))
        self.settings = {
            "gossip_interval": cfg.get_duration("gossip-interval", "1s"),
            "leader_actions_interval": cfg.get_duration("leader-actions-interval", "1s"),
            "reaper_interval": cfg.get_duration("unreachable-nodes-reaper-interval", "1s"),
            "heartbeat_interval": fd_cfg.get_duration("heartbeat-interval", "1s"),
            "monitored_by_nr_of_members": fd_cfg.get_int("monitored-by-nr-of-members", 5),
            "allow_weakly_up": cfg.get_bool("allow-weakly-up-members", True),
        }
        self._latest_state = CurrentClusterState()
        self._on_member_up: List[Callable[[], None]] = []
        self._member_up_fired = False
        self._removed_event = threading.Event()

        # join-time configuration compatibility (reference:
        # JoinConfigCompatChecker.scala:18 — a configurable set of
        # cluster-critical paths is digested; the contact node compares)
        compat = cfg.get_config("configuration-compatibility-check")
        self.enforce_config_compat = compat.get_bool("enforce-on-join", True)
        self.config_compat_paths = tuple(
            compat.get("sensitive-config-paths", None) or (
                "downing-provider-class",
                "split-brain-resolver.active-strategy",
                "allow-weakly-up-members",
            ))
        import hashlib as _hashlib
        import json as _json
        snapshot = {p: cfg.get(p, None) for p in self.config_compat_paths}
        self.config_digest = _hashlib.sha256(
            _json.dumps(snapshot, sort_keys=True, default=str)
            .encode()).hexdigest()
        self.join_refused_reason: Optional[str] = None

        self.daemon = system.system_actor_of(
            Props.create(ClusterCoreDaemon, self), "cluster")

        # downing is OPT-IN (the reference defaults to no downing provider):
        # enable SBR only when explicitly selected, either via
        # downing-provider-class = "sbr" or a configured active-strategy
        sbr_cfg = cfg.get_config("split-brain-resolver")
        provider = cfg.get_string("downing-provider-class", "")
        active = sbr_cfg.get_string("active-strategy", "")
        if provider == "sbr" or active not in ("", "off"):
            self.sbr = system.system_actor_of(
                Props.create(SplitBrainResolver, self,
                             strategy_from_config(
                                 sbr_cfg, system=system,
                                 self_owner=str(self.self_unique_address)),
                             sbr_cfg.get_duration("stable-after", "20s")),
                "split-brain-resolver")
        else:
            self.sbr = None

        self._es_sub = self._on_event
        system.event_stream.subscribe(self._es_sub, MemberUp)
        system.event_stream.subscribe(self._es_sub, MemberRemoved)
        system.coordinated_shutdown.add_task(
            CoordinatedShutdown.PHASE_CLUSTER_LEAVE, "leave-cluster",
            self._leave_on_shutdown)

        seeds = cfg.get("seed-nodes", []) or []
        if seeds:
            self.join_seed_nodes(seeds)

    # -- event plumbing -------------------------------------------------------
    def _on_event(self, event: Any) -> None:
        if isinstance(event, MemberUp):
            if (event.member.unique_address == self.self_unique_address
                    and not self._member_up_fired):
                self._member_up_fired = True
                for cb in self._on_member_up:
                    try:
                        cb()
                    except Exception:  # noqa: BLE001
                        pass
        elif isinstance(event, MemberRemoved):
            if event.member.unique_address == self.self_unique_address:
                self._removed_event.set()

    def _on_self_removed(self) -> None:
        self._removed_event.set()

    # -- API (reference: Cluster.scala join/leave/down/subscribe) -------------
    def join(self, address: "str | Address") -> None:
        self.daemon.tell(JoinTo(_addr_str(address)))

    def join_seed_nodes(self, seeds: List[str]) -> None:
        seeds = [_addr_str(s) for s in seeds]
        if not seeds:
            return
        from .daemon import JoinSeedNodes
        if seeds[0] == self.self_unique_address.address_str:
            self.join(seeds[0])  # we are the first seed: self-join
        else:
            # rotate through seeds until one welcomes us
            self.daemon.tell(JoinSeedNodes(tuple(seeds)))

    def leave(self, address: "str | Address | None" = None) -> None:
        target = _addr_str(address) if address is not None else \
            self.self_unique_address.address_str
        # leaving must spread: tell ourselves AND every known node's daemon
        self.daemon.tell(LeaveCmd(target))

    def down(self, address: "str | Address") -> None:
        self.daemon.tell(DownCmd(_addr_str(address)))

    def subscribe(self, subscriber: Callable[[Any], None],
                  *event_classes: type, initial_state: bool = True) -> None:
        if initial_state:
            subscriber(self.state)
        for cls in event_classes:
            self.system.event_stream.subscribe(subscriber, cls)

    def unsubscribe(self, subscriber: Callable[[Any], None]) -> None:
        self.system.event_stream.unsubscribe(subscriber)

    @property
    def state(self) -> CurrentClusterState:
        return self._latest_state

    @property
    def self_member(self) -> Optional[Member]:
        for m in self._latest_state.members:
            if m.unique_address == self.self_unique_address:
                return m
        return None

    def register_on_member_up(self, cb: Callable[[], None]) -> None:
        if self._member_up_fired:
            cb()
        else:
            self._on_member_up.append(cb)

    @property
    def is_removed(self) -> bool:
        return self._removed_event.is_set()

    def await_removed(self, timeout: Optional[float] = None) -> bool:
        return self._removed_event.wait(timeout)

    def _leave_on_shutdown(self) -> None:
        if self.self_member is not None and not self.is_removed:
            self.leave()
            self._removed_event.wait(5.0)


class ClusterExtension(ExtensionId):
    def create_extension(self, system: ActorSystem) -> Cluster:
        return Cluster.get(system)


def _addr_str(address: "str | Address") -> str:
    return str(address) if isinstance(address, Address) else str(address)
