"""Cluster members: unique address + status lifecycle + ordering.

Reference parity: akka-cluster/src/main/scala/akka/cluster/Member.scala —
MemberStatus lifecycle Joining→(WeaklyUp)→Up→Leaving→Exiting→Removed plus
Down; `allowedTransitions`; Member ordering by address; `isOlderThan` by
up-number (age).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from enum import Enum
from typing import FrozenSet, Optional, Tuple

from ..actor.path import Address


class MemberStatus(Enum):
    JOINING = "Joining"
    WEAKLY_UP = "WeaklyUp"
    UP = "Up"
    LEAVING = "Leaving"
    EXITING = "Exiting"
    DOWN = "Down"
    REMOVED = "Removed"


# (reference: Member.scala allowedTransitions table)
ALLOWED_TRANSITIONS = {
    MemberStatus.JOINING: {MemberStatus.WEAKLY_UP, MemberStatus.UP,
                           MemberStatus.DOWN, MemberStatus.REMOVED},
    MemberStatus.WEAKLY_UP: {MemberStatus.UP, MemberStatus.LEAVING,
                             MemberStatus.DOWN, MemberStatus.REMOVED},
    MemberStatus.UP: {MemberStatus.LEAVING, MemberStatus.DOWN, MemberStatus.REMOVED},
    MemberStatus.LEAVING: {MemberStatus.EXITING, MemberStatus.DOWN, MemberStatus.REMOVED},
    MemberStatus.EXITING: {MemberStatus.REMOVED, MemberStatus.DOWN},
    MemberStatus.DOWN: {MemberStatus.REMOVED},
    MemberStatus.REMOVED: set(),
}


@dataclass(frozen=True, order=True)
class UniqueAddress:
    """Address + per-incarnation uid (reference: cluster/Member.scala
    UniqueAddress) — a restarted node is a different member."""
    address_str: str = field(compare=True)
    uid: int = field(compare=True)

    @property
    def address(self) -> Address:
        return Address.parse(self.address_str)

    def __repr__(self) -> str:
        return f"UniqueAddress({self.address_str}#{self.uid})"


@dataclass(frozen=True)
class Member:
    unique_address: UniqueAddress
    status: MemberStatus = MemberStatus.JOINING
    roles: FrozenSet[str] = frozenset()
    up_number: int = 2**31 - 1  # set when promoted to Up; age ordering

    @property
    def address(self) -> Address:
        return self.unique_address.address

    @property
    def address_str(self) -> str:
        return self.unique_address.address_str

    @property
    def data_center(self) -> str:
        """The member's data center, encoded as a `dc-<name>` role exactly
        like the reference (cluster/Member.scala dataCenter: the DC rides
        the roles set with the ClusterSettings.DcRolePrefix). Deterministic
        under multiple dc- roles (sorted) — though Cluster.__init__ rejects
        user roles with the reserved prefix, wire data is untrusted."""
        dcs = sorted(r for r in self.roles if r.startswith("dc-"))
        return dcs[0][3:] if dcs else "default"

    def copy_with(self, status: MemberStatus, up_number: Optional[int] = None) -> "Member":
        if status not in ALLOWED_TRANSITIONS[self.status] and status != self.status:
            raise ValueError(f"invalid transition {self.status} -> {status} for {self}")
        return replace(self, status=status,
                       up_number=self.up_number if up_number is None else up_number)

    def is_older_than(self, other: "Member") -> bool:
        """(reference: Member.isOlderThan — by up-number, ties by address)"""
        if self.up_number != other.up_number:
            return self.up_number < other.up_number
        return self.unique_address < other.unique_address

    def __lt__(self, other: "Member") -> bool:
        return self.unique_address < other.unique_address

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Member) and self.unique_address == other.unique_address

    def __hash__(self) -> int:
        return hash(self.unique_address)

    def __repr__(self) -> str:
        return f"Member({self.address_str}, {self.status.value})"
