"""Vector clocks for gossip versioning.

Reference parity: akka-cluster/src/main/scala/akka/cluster/VectorClock.scala
(:73) — node->counter map; comparisons Before/After/Same/Concurrent; merge
takes elementwise max; `:+` bumps this node's counter; pruning removes nodes.
"""

from __future__ import annotations

from enum import Enum
from typing import Dict, Mapping


class Ordering(Enum):
    BEFORE = "Before"
    AFTER = "After"
    SAME = "Same"
    CONCURRENT = "Concurrent"


class VectorClock:
    __slots__ = ("versions",)

    def __init__(self, versions: Mapping[str, int] | None = None):
        self.versions: Dict[str, int] = dict(versions or {})

    def bump(self, node: str) -> "VectorClock":
        v = dict(self.versions)
        v[node] = v.get(node, 0) + 1
        return VectorClock(v)

    def merge(self, other: "VectorClock") -> "VectorClock":
        v = dict(self.versions)
        for node, n in other.versions.items():
            if n > v.get(node, 0):
                v[node] = n
        return VectorClock(v)

    def prune(self, node: str) -> "VectorClock":
        v = dict(self.versions)
        v.pop(node, None)
        return VectorClock(v)

    def compare(self, other: "VectorClock") -> Ordering:
        lt = gt = False
        for node in set(self.versions) | set(other.versions):
            a = self.versions.get(node, 0)
            b = other.versions.get(node, 0)
            if a < b:
                lt = True
            elif a > b:
                gt = True
            if lt and gt:
                return Ordering.CONCURRENT
        if lt:
            return Ordering.BEFORE
        if gt:
            return Ordering.AFTER
        return Ordering.SAME

    def is_before(self, other: "VectorClock") -> bool:
        return self.compare(other) is Ordering.BEFORE

    def is_after(self, other: "VectorClock") -> bool:
        return self.compare(other) is Ordering.AFTER

    def is_concurrent(self, other: "VectorClock") -> bool:
        return self.compare(other) is Ordering.CONCURRENT

    def __eq__(self, other: object) -> bool:
        return isinstance(other, VectorClock) and self.compare(other) is Ordering.SAME

    def __hash__(self) -> int:
        return hash(frozenset(self.versions.items()))

    def __repr__(self) -> str:
        inner = ", ".join(f"{n}->{c}" for n, c in sorted(self.versions.items()))
        return f"VectorClock({inner})"
