"""Cluster membership: gossip + vector clocks + leader actions + SBR
(reference: akka-cluster — SURVEY.md §2.4, §3.6)."""

from .cluster import Cluster, ClusterExtension  # noqa: F401
from .member import Member, MemberStatus, UniqueAddress  # noqa: F401
from .vector_clock import VectorClock, Ordering  # noqa: F401
from .reachability import Reachability, ReachabilityStatus  # noqa: F401
from .gossip import Gossip  # noqa: F401
from .events import (ClusterDomainEvent, CurrentClusterState,  # noqa: F401
                     LeaderChanged, MemberDowned, MemberEvent, MemberExited,
                     MemberJoined, MemberLeft, MemberRemoved, MemberUp,
                     MemberWeaklyUp, ReachabilityEvent, ReachableMember,
                     UnreachableMember)
from .sbr import (DownAll, DowningStrategy, KeepMajority,  # noqa: F401
                  KeepOldest, SplitBrainResolver, StaticQuorum)
from .routing import (ClusterRouterGroup, ClusterRouterGroupSettings,  # noqa: F401
                      ClusterRouterPool, ClusterRouterPoolSettings)
