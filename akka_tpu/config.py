"""Layered configuration system.

TPU-native equivalent of the reference's Typesafe Config (HOCON) layer: every
module contributes reference defaults which are merged under user-supplied
overrides at system start (reference: akka-actor/src/main/resources/reference.conf,
read via ActorSystem.Settings, akka-actor/src/main/scala/akka/actor/ActorSystem.scala:398).

We use plain nested dicts with dotted-path access instead of HOCON files: config
is consumed from Python, and a dict round-trips through JSON for the cluster
join-config compatibility check (reference: cluster/JoinConfigCompatChecker.scala).
"""

from __future__ import annotations

import copy
import json
import re
from typing import Any, Iterator, Mapping

_DURATION_RE = re.compile(
    r"^\s*([0-9]*\.?[0-9]+)\s*(d|day|days|h|hour|hours|m|min|minute|minutes|"
    r"s|sec|second|seconds|ms|milli|millis|millisecond|milliseconds|"
    r"us|micro|micros|microsecond|microseconds|ns|nano|nanos|nanosecond|nanoseconds)?\s*$"
)

_UNIT_SECONDS = {
    None: 1.0,  # bare numbers are seconds
    "d": 86400.0, "day": 86400.0, "days": 86400.0,
    "h": 3600.0, "hour": 3600.0, "hours": 3600.0,
    "m": 60.0, "min": 60.0, "minute": 60.0, "minutes": 60.0,
    "s": 1.0, "sec": 1.0, "second": 1.0, "seconds": 1.0,
    "ms": 1e-3, "milli": 1e-3, "millis": 1e-3, "millisecond": 1e-3, "milliseconds": 1e-3,
    "us": 1e-6, "micro": 1e-6, "micros": 1e-6, "microsecond": 1e-6, "microseconds": 1e-6,
    "ns": 1e-9, "nano": 1e-9, "nanos": 1e-9, "nanosecond": 1e-9, "nanoseconds": 1e-9,
}


def parse_duration(value: Any) -> float:
    """Parse a duration into float seconds. Accepts numbers (seconds) or strings
    like "100ms", "5s", "1 minute", "off"/"infinite" (-> float('inf'))."""
    if isinstance(value, (int, float)):
        return float(value)
    if isinstance(value, str):
        v = value.strip().lower()
        if v in ("off", "infinite", "inf", "none"):
            return float("inf")
        m = _DURATION_RE.match(v)
        if m:
            return float(m.group(1)) * _UNIT_SECONDS[m.group(2)]
    raise ValueError(f"cannot parse duration: {value!r}")


def _deep_merge(base: dict, overrides: Mapping) -> dict:
    out = dict(base)
    for k, v in overrides.items():
        if k in out and isinstance(out[k], dict) and isinstance(v, Mapping):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = copy.deepcopy(v) if isinstance(v, (dict, list)) else v
    return out


class Config:
    """Immutable-ish layered config with dotted-path access.

    ``Config({"akka": {"loglevel": "INFO"}}).get("akka.loglevel")`` -> "INFO".
    """

    __slots__ = ("_data",)

    def __init__(self, data: Mapping | None = None):
        self._data: dict = dict(data or {})

    # -- access ------------------------------------------------------------
    def get(self, path: str, default: Any = None) -> Any:
        node: Any = self._data
        for part in path.split("."):
            if isinstance(node, Mapping) and part in node:
                node = node[part]
            else:
                return default
        return node

    def has_path(self, path: str) -> bool:
        sentinel = object()
        return self.get(path, sentinel) is not sentinel

    def get_config(self, path: str) -> "Config":
        v = self.get(path, {})
        return Config(v if isinstance(v, Mapping) else {})

    def get_int(self, path: str, default: int = 0) -> int:
        v = self.get(path, default)
        return int(v)

    def get_float(self, path: str, default: float = 0.0) -> float:
        return float(self.get(path, default))

    def get_bool(self, path: str, default: bool = False) -> bool:
        v = self.get(path, default)
        if isinstance(v, str):
            return v.strip().lower() in ("on", "true", "yes", "1")
        return bool(v)

    def get_string(self, path: str, default: str = "") -> str:
        v = self.get(path, default)
        return str(v)

    def get_list(self, path: str, default: list | None = None) -> list:
        v = self.get(path, default if default is not None else [])
        return list(v) if isinstance(v, (list, tuple)) else [v]

    def get_duration(self, path: str, default: Any = 0.0) -> float:
        """Duration in float seconds ('off' -> inf)."""
        return parse_duration(self.get(path, default))

    def keys(self, path: str = "") -> Iterator[str]:
        node = self.get(path, {}) if path else self._data
        if isinstance(node, Mapping):
            yield from node.keys()

    # -- combination -------------------------------------------------------
    def with_fallback(self, other: "Config | Mapping") -> "Config":
        other_data = other._data if isinstance(other, Config) else dict(other)
        return Config(_deep_merge(other_data, self._data))

    def with_overrides(self, overrides: Mapping) -> "Config":
        return Config(_deep_merge(self._data, overrides))

    def to_dict(self) -> dict:
        return copy.deepcopy(self._data)

    def to_json(self) -> str:
        return json.dumps(self._data, sort_keys=True, default=str)

    def __repr__(self) -> str:  # pragma: no cover
        return f"Config({self._data!r})"


def reference_config() -> Config:
    """Framework-wide defaults. Mirrors the union of the per-module
    reference.conf files in the reference (akka-actor 1307 lines, akka-remote
    1234, akka-cluster 480 — see SURVEY.md §5 config)."""
    return Config({
        "akka": {
            "loglevel": "INFO",
            "stdout-loglevel": "WARNING",
            "log-dead-letters": 10,
            "actor": {
                "provider": "local",  # local | remote | cluster
                "creation-timeout": "20s",
                "unstarted-push-timeout": "10s",
                "serialize-messages": False,
                "guardian-supervisor-strategy": "default",
                "default-dispatcher": {
                    "type": "Dispatcher",
                    "executor": "thread-pool-executor",
                    "throughput": 64,
                    "thread-pool-executor": {"fixed-pool-size": 0},  # 0 => ncores
                    "shutdown-timeout": "1s",
                },
                "internal-dispatcher": {
                    "type": "Dispatcher",
                    "executor": "thread-pool-executor",
                    "throughput": 64,
                    "thread-pool-executor": {"fixed-pool-size": 2},
                    "shutdown-timeout": "1s",
                },
                "tpu-dispatcher": {
                    # The flagship batched dispatcher (BASELINE north star):
                    # SoA actor slabs stepped on-device; see akka_tpu/dispatch/batched.py
                    "type": "tpu-batched",
                    "capacity": 1 << 20,
                    "payload-width": 8,
                    "out-degree": 1,
                    "host-inbox": 4096,
                    "mailbox-slots": 0,     # >0 = per-message ordered mailboxes
                    "promise-rows": 256,    # ask() promise slots
                    "auto-step-interval": "1ms",
                    "pipeline-depth": 2,    # in-flight programs for step(depth=)
                    # preemption tolerance: snapshot every N dispatched steps
                    # into checkpoint-dir, retaining checkpoint-keep newest
                    # (0 / "" disables; see docs/CHECKPOINT_RECOVERY.md)
                    "checkpoint-interval-steps": 0,
                    "checkpoint-dir": "",
                    "checkpoint-keep": 3,
                    # shard-failure sentinel (batched/sentinel.py): phi
                    # threshold + expected heartbeat cadence for the
                    # progress-lane detector, the wall-clock pause before
                    # a silent mesh is declared hung, and how many
                    # automatic failovers may run before the breaker
                    # halts the runtime degraded (docs/FAILOVER.md)
                    "sentinel-threshold": 8.0,
                    "sentinel-heartbeat-interval": "100ms",
                    "sentinel-acceptable-pause": "3s",
                    "sentinel-max-failovers": 3,
                    # degrade-ladder recovery: failover halves the
                    # speculation depth; this many consecutive healthy
                    # pump rounds restore the configured depth (0 = the
                    # halving is permanent, the pre-PR-10 behavior)
                    "sentinel-depth-recovery-rounds": 64,
                    "mesh-axes": {},
                    # per-dispatcher override of akka.metrics.enabled:
                    # compiles the device metric slab into this
                    # dispatcher's step even without the system-wide plane
                    "metrics-enabled": False,
                },
                "default-mailbox": {
                    "mailbox-type": "unbounded",
                    "mailbox-capacity": 1000,
                    "mailbox-push-timeout-time": "10s",
                },
                "mailbox": {"requirements": {}},
                "debug": {"receive": False, "autoreceive": False, "lifecycle": False,
                          "event-stream": False, "unhandled": False},
                "deployment": {},
            },
            "scheduler": {
                "tick-duration": "10ms",
                "ticks-per-wheel": 512,
                "shutdown-timeout": "5s",
            },
            "coordinated-shutdown": {
                "default-phase-timeout": "5s",
                "terminate-actor-system": True,
                "run-by-actor-system-terminate": True,
                "phases": {
                    "before-service-unbind": {"depends-on": []},
                    "service-unbind": {"depends-on": ["before-service-unbind"]},
                    "service-requests-done": {"depends-on": ["service-unbind"]},
                    "service-stop": {"depends-on": ["service-requests-done"]},
                    "before-cluster-shutdown": {"depends-on": ["service-stop"]},
                    "cluster-sharding-shutdown-region": {"depends-on": ["before-cluster-shutdown"]},
                    "cluster-leave": {"depends-on": ["cluster-sharding-shutdown-region"]},
                    "cluster-exiting": {"depends-on": ["cluster-leave"]},
                    "cluster-exiting-done": {"depends-on": ["cluster-exiting"]},
                    "cluster-shutdown": {"depends-on": ["cluster-exiting-done"]},
                    "before-actor-system-terminate": {"depends-on": ["cluster-shutdown"]},
                    "actor-system-terminate": {"depends-on": ["before-actor-system-terminate"]},
                },
            },
            "serialization": {
                "serializers": {},         # name -> FQCN
                "serialization-bindings": {},  # FQCN of message class -> serializer name
            },
            # unified telemetry plane (event/metrics.py + the device metric
            # slab, batched/metrics_slab.py): off by default — enabling it
            # compiles the slab into tpu-batched steps and builds the
            # system-owned MetricsRegistry. http-port > 0 serves
            # Prometheus exposition on 127.0.0.1; jsonl-path arms the
            # periodic emitter (flight-recorder file conventions).
            "metrics": {
                "enabled": False,
                "namespace": "akka",
                "http-port": 0,
                "jsonl-path": "",
                "jsonl-interval": "1s",
            },
            # elastic mesh autoscaler (batched/autoscale.py): off by
            # default — when enabled, autoscaler_from_config attaches a
            # MeshAutoscaler to the MeshSentinel, polled once per pump
            # round. Thresholds are per-poll growth deltas for the
            # counters and levels for the occupancies; hysteresis windows
            # are counted in polls (= pump rounds). max-shards 0 means
            # pool-bounded. docs/ELASTIC_MESH.md has tuning guidance.
            "autoscale": {
                "enabled": False,
                "min-shards": 1,
                "max-shards": 0,
                "widen-after-polls": 3,
                "narrow-after-polls": 16,
                "cooldown-polls": 8,
                "overflow-threshold": 1.0,
                "dropped-threshold": 1.0,
                "ask-occupancy-threshold": 0.9,
                "occupancy-p90-threshold": float("inf"),
            },
            "remote": {
                "canonical": {"hostname": "127.0.0.1", "port": 0},
                "handshake-timeout": "20s",
                "handshake-retry-interval": "1s",
                "quarantine-duration": "5d",
                "system-message-resend-interval": "1s",
                "system-message-buffer-size": 20000,
                "lanes": 4,
                "watch-failure-detector": {
                    "heartbeat-interval": "1s",
                    "threshold": 10.0,
                    "max-sample-size": 200,
                    "min-std-deviation": "100ms",
                    "acceptable-heartbeat-pause": "10s",
                    "expected-first-heartbeat-estimate": "1s",
                },
                "use-unsafe-remote-features-outside-cluster": False,
            },
            "cluster": {
                "seed-nodes": [],
                "seed-node-timeout": "5s",
                "retry-unsuccessful-join-after": "10s",
                "shutdown-after-unsuccessful-join-seed-nodes": "off",
                "periodic-tasks-initial-delay": "1s",
                "gossip-interval": "1s",
                "gossip-time-to-live": "2s",
                "leader-actions-interval": "1s",
                "unreachable-nodes-reaper-interval": "1s",
                "allow-weakly-up-members": True,
                "roles": [],
                "min-nr-of-members": 1,
                "downing-provider-class": "",
                "failure-detector": {
                    "heartbeat-interval": "1s",
                    "threshold": 8.0,
                    "max-sample-size": 1000,
                    "min-std-deviation": "100ms",
                    "acceptable-heartbeat-pause": "3s",
                    "monitored-by-nr-of-members": 5,
                    "expected-first-heartbeat-estimate": "1s",
                },
                "split-brain-resolver": {
                    "active-strategy": "keep-majority",
                    "stable-after": "20s",
                    "down-all-when-unstable": "on",
                    "static-quorum": {"quorum-size": 0, "role": ""},
                    "keep-majority": {"role": ""},
                    "keep-oldest": {"down-if-alone": True, "role": ""},
                    "lease-majority": {"lease-implementation": "", "acquire-lease-delay-for-minority": "2s", "role": ""},
                },
                "sharding": {
                    "number-of-shards": 256,
                    "guardian-name": "sharding",
                    "retry-interval": "2s",
                    "buffer-size": 100000,
                    "handoff-timeout": "60s",
                    "rebalance-interval": "10s",
                    "passivate-idle-entity-after": "120s",
                    "remember-entities": False,
                    "state-store-mode": "ddata",
                    "least-shard-allocation-strategy": {
                        "rebalance-absolute-limit": 0,
                        "rebalance-relative-limit": 0.1,
                    },
                },
                "singleton": {
                    "singleton-name": "singleton",
                    "hand-over-retry-interval": "1s",
                    "min-number-of-hand-over-retries": 15,
                },
                "singleton-proxy": {
                    "buffer-size": 1000,
                    "singleton-identification-interval": "1s",
                },
                "pub-sub": {
                    "gossip-interval": "1s",
                    "removed-time-to-live": "120s",
                },
                "metrics": {
                    "enabled": True,
                    "collect-interval": "3s",
                    "gossip-interval": "3s",
                    "moving-average-half-life": "12s",
                },
                "distributed-data": {
                    "gossip-interval": "2s",
                    "notify-subscribers-interval": "0.5s",
                    "max-delta-elements": 500,
                    "delta-crdt": {"enabled": True, "max-delta-size": 50},
                    "durable": {"keys": [], "store-dir": "ddata"},
                },
            },
            "persistence": {
                "journal": {"plugin": "akka.persistence.journal.inmem",
                            "inmem": {"class": "akka_tpu.persistence.journal.InMemJournal"},
                            "file": {"class": "akka_tpu.persistence.journal.FileJournal", "dir": "journal"}},
                "snapshot-store": {"plugin": "akka.persistence.snapshot-store.local",
                                   "local": {"class": "akka_tpu.persistence.snapshot.LocalSnapshotStore",
                                             "dir": "snapshots"}},
                "max-concurrent-recoveries": 50,
                "at-least-once-delivery": {
                    "redeliver-interval": "5s",
                    "redelivery-burst-limit": 10000,
                    "warn-after-number-of-unconfirmed-attempts": 5,
                    "max-unconfirmed-messages": 100000,
                },
            },
            "stream": {
                "materializer": {
                    "initial-input-buffer-size": 4,
                    "max-input-buffer-size": 16,
                    "dispatcher": "akka.actor.default-dispatcher",
                    "stream-ref": {"buffer-capacity": 32, "demand-redelivery-interval": "1s",
                                   "subscription-timeout": "30s"},
                },
            },
            "test": {
                "timefactor": 1.0,
                "single-expect-default": "3s",
                "default-timeout": "5s",
            },
        },
    })
