"""Ingress: the framed-TCP front door onto sharded device entities.

Wire protocol — `simpleFramingProtocol` (stream/framing.py): every frame
is `[u32 big-endian length][JSON body]`. Requests:

    {"id": 7, "tenant": "t0", "entity": "acct-42", "op": "add", "value": 3}

ops: "add" (apply value, reply new total — the acknowledged write),
"get" (read total). Replies:

    {"id": 7, "status": "ok", "value": 45.0}
    {"id": 8, "status": "shed", "reason": "rate_limited",
     "retry_after_ms": 120}
    {"id": 9, "status": "error", "reason": "timeout"}

"shed" is the admission layer speaking (typed backpressure — the client
knows why and when to retry); "error" is the runtime (ask timeout or
fault). The operator tenant `__admin` bypasses admission and reaches
control ops (sum / checkpoint / rebalance / failover / artifact / stats)
through the same front door — chaos is injected over the wire, the way
an operator would.

Request path: TCP bytes -> length-field decode -> handle_frame (admission
-> SLO clock -> backend ask) -> length-prefix encode -> TCP bytes. The
per-connection flow is ack-gated by the stream TCP layer (ONE Write in
flight), so a slow consumer throttles the producer instead of growing an
unbounded buffer — tested in tests/test_gateway.py.

`handle_frame` is transport-free: the tier-1 smoke test and the
gateway-slo bench drive it in-proc; the chaos tier drives it over real
sockets from other OS processes.

Entity hosting: `RegionBackend` adapts a DeviceShardRegion — entities are
rows on the mesh, requests are region asks (reply-to promise row in the
payload's last column), writes are journaled tells (WAL) so acknowledged
writes survive kill -9. The counter entity keeps the reduction
COMMUTATIVE (the dense-inbox contract): "get" is add(0), and the reply is
always the post-apply total.
"""

from __future__ import annotations

import json
import socket
import struct
import time
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from ..batched.bridge import AskPoolExhausted
from .admission import AdmissionController, Reject
from .slo import SloTracker

__all__ = ["encode_frame", "FrameReader", "counter_behavior",
           "RegionBackend", "GatewayServer", "GatewayClient"]

ADMIN_TENANT = "__admin"


# ---------------------------------------------------------------- wire codec
def encode_frame(obj: Dict[str, Any]) -> bytes:
    body = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    return struct.pack(">I", len(body)) + body


class FrameReader:
    """Incremental length-field frame reassembly for raw sockets (the
    client half; servers reuse the stream Framing stages)."""

    def __init__(self, max_frame: int = 1 << 20):
        self._buf = bytearray()
        self.max_frame = max_frame

    def feed(self, data: bytes):
        self._buf.extend(data)
        while len(self._buf) >= 4:
            n = struct.unpack(">I", self._buf[:4])[0]
            if n > self.max_frame:
                raise ValueError(f"frame of {n} bytes exceeds "
                                 f"{self.max_frame}")
            if len(self._buf) < 4 + n:
                return
            body = bytes(self._buf[4:4 + n])
            del self._buf[:4 + n]
            yield json.loads(body)


# ------------------------------------------------------------ entity backend
def counter_behavior(payload_width: int, out_degree: int = 1):
    """The serving entity: an event-sourced additive counter. Payload
    [value, ..., reply_row]; the reduction sums concurrent adds (the
    dense-inbox commutative contract) and the reply is the new total,
    emitted to the reply-to row (bridge ask convention)."""
    import jax.numpy as jnp
    from ..batched import Emit, behavior
    from ..batched.bridge import reply_dst
    P, k = payload_width, out_degree

    @behavior("gw_counter", {"total": ((), jnp.float32)})
    def counter(state, inbox, ctx):
        got = inbox.count > 0
        new_total = state["total"] + inbox.sum[0]
        reply = jnp.zeros((P,), jnp.float32).at[0].set(new_total)
        return ({"total": jnp.where(got, new_total, state["total"])},
                Emit.single(reply_dst(inbox.sum), reply, k, P, when=got))

    return counter


class RegionBackend:
    """Adapts a DeviceShardRegion of counter entities to the gateway:
    ask(entity_id, value) -> new total (acknowledged = applied + WAL'd,
    when the region has attach_journal'd).

    Batched by default (ISSUE 9): `ask` submits to an AskBatcher
    (sharding/ask_batch.py) and waits on its future, so asks from
    concurrent connections coalesce into shared device step rounds —
    `handle_frame` stays synchronous per connection, batching emerges
    from concurrency. `batch=False` restores the serialized per-ask
    path (the bench A/B baseline); a single caller is bit-identical
    either way (a solo batch runs the exact old step schedule)."""

    def __init__(self, region, steps: int = 2, max_extra_steps: int = 16,
                 batch: bool = True, max_batch: int = 32,
                 batch_window_s: float = 200e-6, registry=None):
        self.region = region
        self.steps = steps
        self.max_extra_steps = max_extra_steps
        self.batcher = None
        if batch:
            from ..sharding.ask_batch import AskBatcher
            self.batcher = AskBatcher(
                region, max_batch=max_batch, window_s=batch_window_s,
                steps=steps, max_extra_steps=max_extra_steps,
                registry=registry)

    def ask(self, entity_id: str, value: float) -> float:
        ref = self.region.entity_ref(entity_id)
        if self.batcher is not None:
            reply = self.batcher.ask(ref.shard, ref.index, [float(value)])
        else:
            reply = self.region.ask(ref.shard, ref.index, [float(value)],
                                    steps=self.steps,
                                    max_extra_steps=self.max_extra_steps)
        return float(np.asarray(reply)[0])

    def close(self) -> None:
        if self.batcher is not None:
            self.batcher.close()

    def sum_all(self) -> float:
        """Conserved-value probe: sum of every spawned entity's total."""
        region = self.region
        with region._ask_lock:  # quiesce vs concurrent asks/maintenance
            return self._sum_locked(region)

    @staticmethod
    def _sum_locked(region) -> float:
        region.block_until_ready()
        rows = []
        with region._lock:
            for shard, ents in enumerate(region._entities):
                base = int(region._shard_block[shard]) * region.eps
                rows.extend(base + idx for idx in ents.values())
        if not rows:
            return 0.0
        vals = region.system.read_state(
            "total", np.asarray(sorted(rows), np.int32))
        return float(np.asarray(vals, np.float64).sum())

    def pressure_signals(self) -> Dict[str, Callable[[], float]]:
        from .admission import region_pressure_signals
        return region_pressure_signals(self.region)


# ------------------------------------------------------------------- server
class GatewayServer:
    """The front door: admission -> SLO clock -> backend ask, over TCP
    (stream layer) and/or in-proc frames (`handle_frame`)."""

    def __init__(self, system, backend, admission: AdmissionController,
                 slo: SloTracker, host: str = "127.0.0.1", port: int = 0,
                 max_frame: int = 1 << 16):
        self.system = system
        self.backend = backend
        self.admission = admission
        self.slo = slo
        self.host = host
        self.port = port
        self.max_frame = max_frame
        self._binding = None
        self._seq = 0

    # ------------------------------------------------------------ transport
    def start(self) -> Tuple[str, int]:
        from ..stream.dsl import Keep, Sink
        from ..stream.framing import Framing
        from ..stream.tcp import Tcp
        if self.port == 0:
            with socket.socket() as s:
                s.bind((self.host, 0))
                self.port = s.getsockname()[1]
        tcp = Tcp.get(self.system)

        def handle(conn):
            conn.handle_with(
                Framing.simple_framing_protocol_decoder(self.max_frame)
                .map(self.handle_frame)
                .via(Framing.simple_framing_protocol_encoder(
                    self.max_frame)),
                self.system)

        fut = tcp.bind(self.host, self.port) \
            .to_mat(Sink.foreach(handle), Keep.left).run(self.system)
        self._binding = fut.result(10.0)
        return self.host, self.port

    def stop(self) -> None:
        if self._binding is not None:
            self._binding.unbind()
            self._binding = None

    # ------------------------------------------------------------- requests
    def handle_frame(self, frame: bytes) -> bytes:
        try:
            req = json.loads(frame)
            rid = req.get("id", -1)
            tenant = str(req["tenant"])
            op = str(req["op"])
        except Exception as e:  # malformed frame: typed error, keep serving
            return encode_body({"id": -1, "status": "error",
                                "reason": f"bad_request:{type(e).__name__}"})
        if tenant == ADMIN_TENANT:
            return encode_body(self._handle_admin(rid, op, req))

        if "entity" not in req:
            # typed BEFORE admission: a malformed frame must not charge
            # the tenant's token bucket and then surface as fault:KeyError
            self.slo.record(tenant, "error")
            return encode_body({"id": rid, "status": "error",
                                "reason": "bad_request:missing_entity"})
        rej = self.admission.admit(tenant)
        if rej is not None:
            self.slo.record(tenant, "reject")
            return encode_body(self._shed(rid, rej))
        value = float(req.get("value", 0.0)) if op == "add" else 0.0
        if op not in ("add", "get"):
            self.slo.record(tenant, "error")
            return encode_body({"id": rid, "status": "error",
                                "reason": f"unknown_op:{op}"})
        t0 = time.perf_counter()
        try:
            total = self.backend.ask(str(req["entity"]), value)
        except AskPoolExhausted:
            # the typed fast-fail the admission layer sheds on: convert to
            # a shed reply AND arm the controller's cooldown
            self.admission.note_ask_pool_exhausted()
            self.slo.record(tenant, "reject")
            return encode_body(self._shed(
                rid, Reject("ask_pool_exhausted",
                            self.admission.cooldown_s)))
        except TimeoutError:
            self.slo.record(tenant, "timeout",
                            time.perf_counter() - t0)
            return encode_body({"id": rid, "status": "error",
                                "reason": "timeout"})
        except Exception as e:  # noqa: BLE001 — fault isolation per request
            # latency recorded on the fault leg too (the timeout leg always
            # did): error-leg p99s stay honest in the SLO artifact
            self.slo.record(tenant, "error", time.perf_counter() - t0)
            return encode_body({"id": rid, "status": "error",
                                "reason": f"fault:{type(e).__name__}"})
        self.slo.record(tenant, "ok", time.perf_counter() - t0)
        return encode_body({"id": rid, "status": "ok", "value": total})

    @staticmethod
    def _shed(rid, rej: Reject) -> Dict[str, Any]:
        return {"id": rid, "status": "shed", "reason": rej.reason,
                "retry_after_ms": int(rej.retry_after_s * 1e3)}

    # ---------------------------------------------------------------- admin
    def _handle_admin(self, rid, op: str, req: Dict[str, Any]) \
            -> Dict[str, Any]:
        """Operator channel (not admission-gated): chaos legs and probes
        ride the same wire as traffic."""
        try:
            if op == "sum":
                return {"id": rid, "status": "ok",
                        "value": self.backend.sum_all()}
            if op == "artifact":
                return {"id": rid, "status": "ok",
                        "data": self.slo.artifact()}
            if op == "stats":
                data = {"admission": self.admission.stats(),
                        "region": self.backend.region.stats(),
                        "ask_pool": self.backend.region.ask_pool_stats()}
                batcher = getattr(self.backend, "batcher", None)
                if batcher is not None:
                    data["ask_batch"] = batcher.stats()
                return {"id": rid, "status": "ok", "data": data}
            if op == "checkpoint":
                return {"id": rid, "status": "ok",
                        "data": {"path": self.backend.region.checkpoint()}}
            if op == "rebalance":
                shard = int(req.get("value", 0))
                blk = self.backend.region.rebalance(shard)
                return {"id": rid, "status": "ok", "value": float(blk)}
            if op == "failover":
                import jax
                n = int(req.get("value", 1))
                step = self.backend.region.failover(jax.devices()[:n])
                return {"id": rid, "status": "ok", "value": float(step)}
            return {"id": rid, "status": "error",
                    "reason": f"unknown_admin_op:{op}"}
        except Exception as e:  # noqa: BLE001 — admin faults must reply
            return {"id": rid, "status": "error",
                    "reason": f"admin_fault:{type(e).__name__}:{e}"}


def encode_body(obj: Dict[str, Any]) -> bytes:
    """Reply body only — the stream encoder stage (or the in-proc caller)
    adds the length prefix."""
    return json.dumps(obj, separators=(",", ":")).encode("utf-8")


# ------------------------------------------------------------------- client
class GatewayClient:
    """Blocking raw-socket client (tests / load generators / example).
    One request in flight per connection; `request` returns the decoded
    reply dict. `request_retry` reconnects through server restarts — the
    chaos legs' client behavior."""

    def __init__(self, host: str, port: int, timeout: float = 15.0):
        self.host = host
        self.port = port
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._reader = FrameReader()
        self._seq = 0

    def connect(self) -> None:
        self.close()
        s = socket.create_connection((self.host, self.port),
                                     timeout=self.timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = s
        self._reader = FrameReader()

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def request(self, tenant: str, entity: str, op: str,
                value: float = 0.0) -> Dict[str, Any]:
        if self._sock is None:
            self.connect()
        self._seq += 1
        req = {"id": self._seq, "tenant": tenant, "entity": entity,
               "op": op, "value": value}
        self._sock.sendall(encode_frame(req))
        while True:
            data = self._sock.recv(65536)
            if not data:
                raise ConnectionError("gateway closed the connection")
            for reply in self._reader.feed(data):
                return reply

    def request_retry(self, tenant: str, entity: str, op: str,
                      value: float = 0.0, deadline_s: float = 60.0,
                      pause_s: float = 0.2) -> Dict[str, Any]:
        """Retry through connection failures (server crash/restart) until
        `deadline_s`. Shed replies are returned to the caller — backoff
        on rejects is a POLICY, reconnection is plumbing."""
        deadline = time.monotonic() + deadline_s
        last: Optional[Exception] = None
        while time.monotonic() < deadline:
            try:
                return self.request(tenant, entity, op, value)
            except (OSError, ConnectionError, socket.timeout) as e:
                last = e
                self.close()
                time.sleep(pause_s)
        raise TimeoutError(f"gateway unreachable for {deadline_s}s: {last!r}")

    def admin(self, op: str, value: float = 0.0) -> Dict[str, Any]:
        return self.request(ADMIN_TENANT, "", op, value)
