"""Ingress: the framed-TCP front door onto sharded device entities.

Wire protocol — `simpleFramingProtocol` (stream/framing.py): every frame
is `[u32 big-endian length][body]`, and TWO body encodings coexist on
one connection, sniffed by the first body byte (ISSUE 11):

- **JSON** (first byte `{`) — the debuggable fallback and the admin
  channel. Requests:

      {"id": 7, "tenant": "t0", "entity": "acct-42", "op": "add",
       "value": 3}

  ops: "add" (apply value, reply new total — the acknowledged write),
  "get" (read total). Replies:

      {"id": 7, "status": "ok", "value": 45.0}
      {"id": 8, "status": "shed", "reason": "rate_limited",
       "retry_after_ms": 120}
      {"id": 9, "status": "error", "reason": "timeout"}

- **Binary** (first byte 0xAB — serialization/frames.py): a versioned
  fixed-schema batch of packed request records. A whole window decodes
  in ONE `np.frombuffer` pass into columns (op, entity, value) that
  feed the columnar ask wave (`RegionBackend.ask_many` ->
  `AskBatcher.ask_many` -> `execute_ask_batch`'s coalesced flush), and
  the reply wave encodes in one vectorized pass — zero per-request
  dict/object construction between wire bytes and the staging slab. A
  batch of one is the solo ask, bit-identical to its JSON twin.

"shed" is the admission layer speaking (typed backpressure — the client
knows why and when to retry); "error" is the runtime (ask timeout or
fault). The operator tenant `__admin` bypasses admission and reaches
control ops (sum / checkpoint / rebalance / failover / artifact / stats)
through the same front door — chaos is injected over the wire, the way
an operator would. Admin ops are JSON-only (a binary frame addressed to
the admin tenant gets a typed error): the operator channel stays
human-readable.

Request path: TCP bytes -> length-field decode -> handle_frame (admission
-> SLO clock -> backend ask) -> length-prefix encode -> TCP bytes. The
per-connection flow is ack-gated by the stream TCP layer (ONE Write in
flight), so a slow consumer throttles the producer instead of growing an
unbounded buffer — tested in tests/test_gateway.py. In-proc transports
(bench, batched load generators) can additionally hand
`handle_frame_batch` a window of frames: contiguous binary frames merge
into one decode + one ask wave.

ONE frame-size limit (`frames.DEFAULT_MAX_FRAME`) is the default at
BOTH ends — the server's framing stages and the client's FrameReader —
so a server-legal reply can never exceed what the client will reassemble
(the 1<<20 / 1<<16 mismatch is gone; pass `max_frame` to both ends
together to change it).

`handle_frame` is transport-free: the tier-1 smoke test and the
gateway-slo bench drive it in-proc; the chaos tier drives it over real
sockets from other OS processes.

Entity hosting: `RegionBackend` adapts a DeviceShardRegion — entities are
rows on the mesh, requests are region asks (reply-to promise row in the
payload's last column), writes are journaled tells (WAL) so acknowledged
writes survive kill -9. The counter entity keeps the reduction
COMMUTATIVE (the dense-inbox contract): "get" is add(0), and the reply is
always the post-apply total.
"""

from __future__ import annotations

import itertools
import json
import random
import socket
import struct
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..batched.bridge import AskPoolExhausted
from ..event.tracing import reset_ctx, set_ctx
from ..serialization import frames
from ..pattern.backoff import backoff_delay
from .admission import AdmissionController
from .dedup import DUPLICATE_INFLIGHT
from .slo import SloTracker

__all__ = ["encode_frame", "encode_body", "FrameReader", "counter_behavior",
           "RegionBackend", "GatewayServer", "GatewayClient",
           "DEFAULT_MAX_FRAME"]

ADMIN_TENANT = "__admin"

# one limit, both ends (see module docstring)
DEFAULT_MAX_FRAME = frames.DEFAULT_MAX_FRAME


# ---------------------------------------------------------------- wire codec
def encode_body(obj: Dict[str, Any]) -> bytes:
    """JSON reply/request body only — the stream encoder stage (or the
    in-proc caller) adds the length prefix."""
    return json.dumps(obj, separators=(",", ":")).encode("utf-8")


def encode_frame(obj: Dict[str, Any]) -> bytes:
    """Length-prefixed JSON frame: the ONE frame-encode helper (shared
    by server, client and the binary path via `frames.frame`)."""
    return frames.frame(encode_body(obj))


class FrameReader:
    """Incremental length-field frame reassembly for raw sockets (the
    client half; servers reuse the stream Framing stages). `feed` yields
    decoded JSON bodies; `feed_raw` yields raw bodies (the binary reply
    path decodes them with frames.decode_replies)."""

    def __init__(self, max_frame: int = DEFAULT_MAX_FRAME):
        self._buf = bytearray()
        self.max_frame = max_frame

    def feed_raw(self, data: bytes):
        self._buf.extend(data)
        while len(self._buf) >= 4:
            n = struct.unpack(">I", self._buf[:4])[0]
            if n > self.max_frame:
                raise ValueError(f"frame of {n} bytes exceeds "
                                 f"{self.max_frame}")
            if len(self._buf) < 4 + n:
                return
            body = bytes(self._buf[4:4 + n])
            del self._buf[:4 + n]
            yield body

    def feed(self, data: bytes):
        for body in self.feed_raw(data):
            yield json.loads(body)


# ------------------------------------------------------------ entity backend
def counter_behavior(payload_width: int, out_degree: int = 1):
    """The serving entity: an event-sourced additive counter. Payload
    [value, ..., reply_row]; the reduction sums concurrent adds (the
    dense-inbox commutative contract) and the reply is the new total,
    emitted to the reply-to row (bridge ask convention)."""
    import jax.numpy as jnp
    from ..batched import Emit, behavior
    from ..batched.bridge import reply_dst
    P, k = payload_width, out_degree

    @behavior("gw_counter", {"total": ((), jnp.float32)})
    def counter(state, inbox, ctx):
        got = inbox.count > 0
        new_total = state["total"] + inbox.sum[0]
        reply = jnp.zeros((P,), jnp.float32).at[0].set(new_total)
        return ({"total": jnp.where(got, new_total, state["total"])},
                Emit.single(reply_dst(inbox.sum), reply, k, P, when=got))

    return counter


class RegionBackend:
    """Adapts a DeviceShardRegion of counter entities to the gateway:
    ask(entity_id, value) -> new total (acknowledged = applied + WAL'd,
    when the region has attach_journal'd).

    Batched by default (ISSUE 9): `ask` submits to an AskBatcher
    (sharding/ask_batch.py) and waits on its future, so asks from
    concurrent connections coalesce into shared device step rounds —
    `handle_frame` stays synchronous per connection, batching emerges
    from concurrency. `batch=False` restores the serialized per-ask
    path (the bench A/B baseline); a single caller is bit-identical
    either way (a solo batch runs the exact old step schedule)."""

    def __init__(self, region, steps: int = 2, max_extra_steps: int = 16,
                 batch: bool = True, max_batch: int = 32,
                 batch_window_s: float = 200e-6, registry=None,
                 continuous: bool = False, pipeline_depth: int = 4):
        self.region = region
        self.steps = steps
        self.max_extra_steps = max_extra_steps
        # continuous wave formation (ISSUE 16): waves overlap on the
        # bridge via the ContinuousWaveScheduler instead of serializing
        # under _ask_lock; False keeps the PR 15 serve path byte-for-byte
        self.continuous = bool(continuous) and batch
        self.batcher = None
        if batch:
            from ..sharding.ask_batch import AskBatcher
            self.batcher = AskBatcher(
                region, max_batch=max_batch, window_s=batch_window_s,
                steps=steps, max_extra_steps=max_extra_steps,
                registry=registry, continuous=continuous,
                pipeline_depth=pipeline_depth)

    def ask(self, entity_id: str, value: float) -> float:
        ref = self.region.entity_ref(entity_id)
        if self.batcher is not None:
            reply = self.batcher.ask(ref.shard, ref.index, [float(value)])
        else:
            reply = self.region.ask(ref.shard, ref.index, [float(value)],
                                    steps=self.steps,
                                    max_extra_steps=self.max_extra_steps)
        return float(np.asarray(reply)[0])

    def _resolve_wave(self, entity_ids: Sequence[str],
                      values: Sequence[float],
                      ctxs: Optional[Sequence[Any]],
                      keys: Optional[Sequence[Any]] = None):
        """Shared wave prep: entity ids resolved ONCE per unique id;
        unresolvable entities land their typed exception in `out`
        directly; the rest compact into (shard, index, payload) requests
        with aligned origin slots, span contexts and dedup keys."""
        refs: Dict[str, Any] = {}
        for e in entity_ids:
            if e not in refs:
                try:
                    refs[e] = self.region.entity_ref(e)
                except Exception as exc:  # noqa: BLE001 — per-entity typed
                    refs[e] = exc
        reqs, slots = [], []
        req_ctxs: Optional[List[Any]] = [] if ctxs is not None else None
        req_keys: Optional[List[Any]] = [] if keys is not None else None
        out: List[Any] = [None] * len(entity_ids)
        for i, (e, v) in enumerate(zip(entity_ids, values)):
            r = refs[e]
            if isinstance(r, BaseException):
                out[i] = r
                continue
            reqs.append((r.shard, r.index, [float(v)]))
            slots.append(i)
            if req_ctxs is not None:
                req_ctxs.append(ctxs[i])
            if req_keys is not None:
                req_keys.append(keys[i])
        return out, reqs, slots, req_ctxs, req_keys

    def ask_many(self, entity_ids: Sequence[str],
                 values: Sequence[float],
                 ctxs: Optional[Sequence[Any]] = None,
                 with_seqs: bool = False,
                 keys: Optional[Sequence[Any]] = None):
        """Columnar wave ask for a decoded binary window: entity ids are
        resolved ONCE per unique id, the whole wave rides
        `AskBatcher.ask_many` (one coalesced flush + one shared step
        budget, no per-call future hop) and the return is outcome-
        aligned — a float total or the per-ask exception INSTANCE
        (AskPoolExhausted / TimeoutError / ...), never a raise, so one
        member's failure cannot fail its wave-mates.

        `ctxs` (ISSUE 12): optional aligned per-request span contexts —
        one window carries many traces, so each sampled member's ctx
        travels next to its request instead of in the ambient var.

        `with_seqs` (ISSUE 16): also return the aligned per-member
        resolve ordinals (continuous mode; None under the serialized
        engine, where waves already resolve in submit order) — the
        gateway's replica-publish monotonicity key.

        `keys` (ISSUE 20): optional aligned per-request dedup keys —
        `(tenant, id)` tuples (or None) that ride the wave into the
        entity journal's group commit, so ok replies are durable before
        their acks (the reply-cache's commit-before-ack contract)."""
        out, reqs, slots, req_ctxs, req_keys = self._resolve_wave(
            entity_ids, values, ctxs, keys)
        seqs_out: Optional[List[int]] = None
        if reqs:
            rseqs = None
            if self.batcher is not None:
                if with_seqs:
                    replies, rseqs = self.batcher.ask_many(
                        reqs, req_ctxs, with_seqs=True, keys=req_keys)
                else:
                    replies = self.batcher.ask_many(reqs, req_ctxs,
                                                    keys=req_keys)
            else:
                replies = self.region.ask_many(
                    reqs, steps=self.steps,
                    max_extra_steps=self.max_extra_steps, ctxs=req_ctxs)
            for i, rep in zip(slots, replies):
                out[i] = rep if isinstance(rep, BaseException) \
                    else float(np.asarray(rep)[0])
            if rseqs is not None:
                seqs_out = [0] * len(entity_ids)
                for i, s in zip(slots, rseqs):
                    seqs_out[i] = int(s)
        return (out, seqs_out) if with_seqs else out

    def ask_many_async(self, entity_ids: Sequence[str],
                       values: Sequence[float],
                       ctxs: Optional[Sequence[Any]],
                       on_done: Callable[[List[Any], List[int]], Any],
                       keys: Optional[Sequence[Any]] = None) -> None:
        """Continuous-mode async wave (ISSUE 16): refs resolve and the
        wave STAGES on the calling thread (staging order is the
        linearization order, so per-connection ordering is preserved);
        `on_done(outcomes, seqs)` — both aligned with `entity_ids` —
        fires at the wave's resolve boundary on the scheduler thread.
        `keys` as in `ask_many` (ISSUE 20)."""
        out, reqs, slots, req_ctxs, req_keys = self._resolve_wave(
            entity_ids, values, ctxs, keys)
        seqs_out = [0] * len(entity_ids)
        if not reqs:
            on_done(out, seqs_out)
            return

        def _done(replies: List[Any], rseqs: List[int]) -> None:
            for i, rep, s in zip(slots, replies, rseqs):
                out[i] = rep if isinstance(rep, BaseException) \
                    else float(np.asarray(rep)[0])
                seqs_out[i] = int(s)
            on_done(out, seqs_out)

        self.batcher.ask_many_async(reqs, req_ctxs, _done, keys=req_keys)

    def close(self) -> None:
        if self.batcher is not None:
            self.batcher.close()

    def sum_all(self) -> float:
        """Conserved-value probe: sum of every spawned entity's total."""
        region = self.region
        if self.batcher is not None:
            # continuous mode: open waves resolve before the probe reads
            # device state (serialized engine calls are synchronous under
            # the ask lock below, so this is a no-op there)
            self.batcher.quiesce()
        with region._ask_lock:  # quiesce vs concurrent asks/maintenance
            return self._sum_locked(region)

    @staticmethod
    def _sum_locked(region) -> float:
        region.block_until_ready()
        rows = []
        with region._lock:
            for shard, ents in enumerate(region._entities):
                base = int(region._shard_block[shard]) * region.eps
                rows.extend(base + idx for idx in ents.values())
        if not rows:
            return 0.0
        vals = region.system.read_state(
            "total", np.asarray(sorted(rows), np.int32))
        return float(np.asarray(vals, np.float64).sum())

    def pressure_signals(self) -> Dict[str, Callable[[], float]]:
        # includes open_wave_depth when this backend batches asks
        # (ISSUE 18 satellite): admission sheds on a full wave pipeline
        # before the promise pool is the thing that says no
        from .admission import region_pressure_signals
        return region_pressure_signals(self.region, batcher=self.batcher)


# -------------------------------------------------- mixed-encoding windows
# JSON rows that cannot map onto the wire op space get sentinel codes so
# they flow through the same post-admission typed-error branch their
# scalar twins used (charged, like any unknown op)
_OP_JSON_UNKNOWN = 255
_OP_JSON_BAD_VALUE = 254

_MISSING = object()  # raw_ids sentinel: "id": null must echo null


class _WindowAux:
    """JSON-origin overlays for a mixed-encoding record window: the
    record columns hold the wire-shaped view (fixed-width bytes, op
    codes); these per-row maps carry what only JSON can express — raw
    reply ids, op labels for reasons and span attrs, value-conversion
    failures, and reasons past the wire's 32-byte truncation."""

    __slots__ = ("json_rows", "raw_ids", "op_labels", "bad_values",
                 "reasons_full")

    def __init__(self) -> None:
        self.json_rows: set = set()        # rows decoded from JSON bodies
        self.raw_ids: Dict[int, Any] = {}      # row -> non-int64 JSON id
        self.op_labels: Dict[int, str] = {}    # row -> original op string
        self.bad_values: Dict[int, str] = {}   # row -> typed value reason
        self.reasons_full: Dict[int, str] = {}  # row -> untruncated reason


class _ServeState:
    """One record window's staged serve state, crossing the
    stage/resolve seam (ISSUE 16): the reply columns being filled, the
    per-row trace roots, the deferred SLO rounds, and the compacted ask
    wave (`serve` row indices with aligned vals/ents/ctxs). The
    synchronous path builds and consumes it on one thread; the
    continuous path hands it from the staging thread to the wave
    scheduler's resolve boundary."""

    __slots__ = ("aux", "ids", "ops", "tenants", "status", "reason",
                 "value", "retry", "step_lag", "traces", "roots",
                 "slo_outcomes", "slo_lat", "slo_rep", "serve", "vals",
                 "ents", "ctxs", "dedup", "dedup_keys", "dedup_alias",
                 "ask_keys")

    def __init__(self) -> None:
        self.slo_outcomes: Dict[bytes, List[str]] = {}
        self.slo_lat: Dict[bytes, List[Optional[float]]] = {}
        self.slo_rep: Dict[bytes, List[bool]] = {}
        # reply-cache dedup (ISSUE 20): flag column (None = dedup off),
        # row -> pending (tenant, id) key awaiting record/release, and
        # same-window duplicate row -> its source row
        self.dedup: Optional[np.ndarray] = None
        self.dedup_keys: Dict[int, Tuple[str, int]] = {}
        self.dedup_alias: Dict[int, int] = {}
        self.ask_keys: Optional[List[Any]] = None


# ------------------------------------------------------------------- server
class GatewayServer:
    """The front door: admission -> SLO clock -> backend ask, over TCP
    (stream layer) and/or in-proc frames (`handle_frame`)."""

    def __init__(self, system, backend, admission: AdmissionController,
                 slo: SloTracker, host: str = "127.0.0.1", port: int = 0,
                 max_frame: int = DEFAULT_MAX_FRAME, registry=None,
                 tracer=None, aggregate: bool = False,
                 max_window: int = 64, window_wait_s: float = 150e-6,
                 pipeline_depth: int = 4, replica_cache=None,
                 transport: str = "stream", accept_shards: int = 1,
                 dedup=None, idle_timeout_s: float = 0.0):
        if transport not in ("stream", "evloop"):
            raise ValueError(f"unknown transport {transport!r} "
                             "(expected 'stream' or 'evloop')")
        self.system = system
        self.backend = backend
        self.admission = admission
        self.slo = slo
        # replicated hot-key read path (ISSUE 14): optional
        # ReadReplicaCache — gets for hot entities answered before the
        # ask wave under its bounded-staleness contract, every wave's ok
        # totals published back at the flush boundary
        self.replica_cache = replica_cache
        if replica_cache is not None and slo is not None:
            slo.attach_replica_cache(replica_cache)
        if replica_cache is not None:
            # durable-restore seam (ISSUE 15): a region restored before
            # the gateway came up replayed the entity journal — overwrite
            # any pre-crash replica entries (local or ddata-fed) with the
            # acked-frontier totals at the NEW step, before first serve
            region = getattr(backend, "region", None)
            replayed = getattr(region, "_durable_replayed_totals", None)
            if replayed is not None:
                replica_cache.republish_restored(replayed)
        # exactly-once effects (ISSUE 20): optional ReplyCacheTable —
        # duplicate request ids short-circuit with the cached reply
        # instead of re-entering the ask wave. Ok replies rode the
        # entity journal's group commit (`append_wave(replies=)`), so a
        # region restored before the gateway came up replayed the dedup
        # frontier too — rehydrate it before first serve, the replica
        # republish_restored twin above.
        self.dedup = dedup
        self._dedup_lock = threading.Lock()
        self.idle_timeout_s = float(idle_timeout_s)
        if dedup is not None:
            region = getattr(backend, "region", None)
            ej = getattr(region, "_entity_journal", None)
            replayed_replies = getattr(ej, "replies", None)
            if replayed_replies is not None:
                entries = replayed_replies()
                if entries:
                    dedup.load(entries)
            if registry is not None:
                registry.register_collector("gateway_dedup", dedup.stats)
        self.host = host
        self.port = port
        self.max_frame = max_frame
        self._binding = None
        self._seq = 0
        self._registry = registry
        self.pipeline_depth = int(pipeline_depth)
        self._conn_ids = itertools.count(1)
        # continuous wave formation (ISSUE 16): autodetected from the
        # backend's batcher. When on, windows may resolve out of submit
        # order, so replica publishes are filtered per entity by resolve
        # ordinal — a slow wave's stale total must never overwrite a
        # newer wave's published one.
        self.continuous = bool(getattr(
            getattr(backend, "batcher", None), "continuous", False))
        self._pub_lock = threading.Lock()
        self._pub_seq: Dict[str, int] = {}
        # causal tracing (event/tracing.py): explicit tracer wins, else
        # the system-wired one (akka.tracing.* config); None keeps every
        # hook below at one `is not None` predicate
        self._tracer = tracer if tracer is not None \
            else getattr(system, "tracer", None)
        if self._tracer is not None:
            region = getattr(backend, "region", None)
            if region is not None and hasattr(region, "attach_tracer"):
                region.attach_tracer(self._tracer)
        self._h_decode_size = self._h_decode_ns = None
        if registry is not None:
            self._h_decode_size = registry.histogram(
                "gateway_decode_batch_size",
                "binary request records decoded per window")
            self._h_decode_ns = registry.histogram(
                "gateway_decode_ns_per_frame",
                "nanoseconds of wire decode per binary request record")
        # C1M front door (ISSUE 18): transport picks who owns the
        # sockets — "stream" materializes a per-connection stage graph
        # (the A/B twin, bit-identical to the seed), "evloop" runs ALL
        # sockets on selector loop threads (evloop.EvLoopIngress). Both
        # funnel frames into the same serve path.
        self.transport = transport
        self.accept_shards = max(1, int(accept_shards))
        self._evloop = None
        # cross-connection ingest windowing (ISSUE 13): off by default —
        # the per-frame path below stays bit-identical to the seed. The
        # evloop transport has no per-frame stage to fall back on, so it
        # always gets the shared aggregator.
        self.aggregator = None
        if aggregate or transport == "evloop":
            from .aggregator import IngestAggregator
            self.aggregator = IngestAggregator(
                self, max_window=max_window, window_s=window_wait_s,
                registry=registry)
            if slo is not None:
                slo.attach_aggregator(self.aggregator)

    # ------------------------------------------------------------ transport
    def start(self) -> Tuple[str, int]:
        if self.transport == "evloop":
            from .evloop import EvLoopIngress
            self._evloop = EvLoopIngress(
                self, host=self.host, port=self.port,
                n_shards=self.accept_shards, registry=self._registry,
                idle_timeout_s=self.idle_timeout_s)
            self.host, self.port = self._evloop.start()
            return self.host, self.port
        from ..stream.dsl import Keep, Sink
        from ..stream.framing import Framing
        from ..stream.tcp import Tcp
        if self.port == 0:
            with socket.socket() as s:
                s.bind((self.host, 0))
                self.port = s.getsockname()[1]
        tcp = Tcp.get(self.system)

        def handle(conn):
            stage = Framing.simple_framing_protocol_decoder(self.max_frame)
            if self.aggregator is not None:
                # bounded per-connection pipelining: up to pipeline_depth
                # frames of one socket in flight at the shared aggregator;
                # MapAsync's ordered drain preserves per-connection reply
                # order and its in-flight cap keeps the demand chain
                # intact (a slow consumer still throttles its own socket)
                cid = next(self._conn_ids)
                stage = stage.map_async(
                    self.pipeline_depth,
                    lambda body, _c=cid: self.aggregator.submit(body, _c))
            else:
                stage = stage.map(self.handle_frame)
            conn.handle_with(
                stage.via(Framing.simple_framing_protocol_encoder(
                    self.max_frame)),
                self.system)

        fut = tcp.bind(self.host, self.port) \
            .to_mat(Sink.foreach(handle), Keep.left).run(self.system)
        self._binding = fut.result(10.0)
        return self.host, self.port

    def stop(self) -> None:
        if self._evloop is not None:
            self._evloop.stop()
            self._evloop = None
        if self._binding is not None:
            self._binding.unbind()
            self._binding = None
        if self.aggregator is not None:
            self.aggregator.close()

    # ------------------------------------------------------------- requests
    def handle_frame(self, frame: bytes) -> bytes:
        """One frame in, one reply body out. Binary solos keep the
        zero-copy decode; everything else — including solo JSON — is a
        one-frame window through the SAME columnar serve path a
        cross-connection window rides (ISSUE 13: the scalar JSON
        admission/SLO/trace block is gone, so check-order parity is
        structural, not mirrored)."""
        if frames.is_binary(frame):
            return self.handle_binary(frame)
        return self._serve_frames([frame])[0]

    def _bad_request_reply(self, e: Exception) -> Dict[str, Any]:
        """Malformed JSON frame: typed error, keep serving."""
        reason = f"bad_request:{type(e).__name__}"
        tr = self._tracer
        trace = tr.start_trace() if tr is not None else 0
        if trace:  # greppable: the reply's trace id is in the spans
            t_now = time.monotonic()
            tr.emit("gw.bad_request", trace, t0=t_now, t1=t_now,
                    reason=reason, proto="json")
        return self._traced(
            {"id": -1, "status": "error", "reason": reason}, trace)

    @staticmethod
    def _traced(rep: Dict[str, Any], trace: int) -> Dict[str, Any]:
        """Mirror the trace id into the reply — EVERY reply of a sampled
        request, so the JSON dict stays the exact twin of a version-2
        binary record's reply_to_dict (trace column on all records)."""
        if trace:
            rep["trace"] = trace
        return rep

    # ------------------------------------------------------ binary requests
    @staticmethod
    def _binary_error(code: str, trace: int = 0) -> bytes:
        """Typed malformed-binary reply (the `bad_request:` twin): one
        error record with id -1, mirroring the JSON path's keep-serving
        discipline. A sampled decode failure carries its trace id (the
        version-2 reply record) so the failure is greppable server-side."""
        return frames.encode_reply_batch(
            np.asarray([-1], np.int64),
            np.asarray([frames.ST_ERROR], np.uint8),
            np.asarray([f"bad_frame:{code}".encode("utf-8")
                        [:frames.REASON_BYTES]]),
            np.zeros(1), np.zeros(1, np.uint32),
            np.asarray([trace], np.uint64) if trace else None)

    def handle_binary(self, body: bytes) -> bytes:
        """One binary window: batch decode -> columnar serve -> one
        vectorized reply encode."""
        t0d = time.monotonic() if self._tracer is not None else 0.0
        rec = self._decode_window([body])
        if isinstance(rec, bytes):  # typed decode error
            return rec
        decode_t = (t0d, time.monotonic()) \
            if self._tracer is not None else None
        cols = self._serve_records(rec, decode_t)
        return frames.encode_reply_batch(*cols)

    def handle_frame_batch(self, bodies: Sequence[bytes]) -> List[bytes]:
        """Window entry point for the ingest aggregator, in-proc
        transports and batched load generators: ALL binary frames in
        `bodies` — contiguous or not — merge into ONE decode pass, JSON
        frames ride the SAME record columns, and the whole window is one
        admission charge + one ask wave + one SLO round (ISSUE 13).
        Admin and malformed frames stay standalone. Returns one reply
        body per input frame, aligned."""
        return self._serve_frames(bodies)

    def _bad_frame_reply(self, e: frames.FrameFormatError) -> bytes:
        """Typed reply for ONE malformed binary frame (keep serving the
        rest of the window); sampled failures are greppable."""
        tr = self._tracer
        trace = tr.start_trace() if tr is not None else 0
        if trace:  # the bad_frame reply's trace id is in the spans
            t_now = time.monotonic()
            tr.emit("gw.bad_frame", trace, t0=t_now, t1=t_now,
                    reason=f"bad_frame:{e.code}", proto="binary")
        return self._binary_error(e.code, trace)

    def _serve_frames(self, bodies: Sequence[bytes]) -> List[bytes]:
        """ONE ingest window across frames of ANY encoding and ANY
        interleaving (ISSUE 13 tentpole): every valid binary body merges
        into a single `np.frombuffer` decode, every JSON request lands
        in the SAME record columns, and the whole window rides one
        `_serve_records` pass — one vectorized admission charge, one ask
        wave, one SLO round. Admin and malformed frames are typed
        standalone (never windowed, never charged). Replies demux back
        1:1 with `bodies`, each in its own encoding; window row order is
        arrival order, so per-entity linearization order is frame order
        (the wave scheduler serves duplicate destinations in row order)."""
        out, windowed, spans, count_of, rec, aux, decode_t = \
            self._window_prep(bodies)
        if not windowed:
            return out  # type: ignore[return-value]
        t_serve0 = time.monotonic() if self._tracer is not None else 0.0
        cols = self._serve_records(rec, decode_t, aux)
        self._window_demux(out, windowed, spans, count_of, cols, aux,
                           t_serve0)
        return out  # type: ignore[return-value]

    def submit_frames(self, bodies: Sequence[bytes]) -> "Future":
        """Continuous-mode async twin of `handle_frame_batch` (ISSUE 16
        tentpole): decode + admission + replica reads + wave STAGING run
        on the caller's thread (arrival order stays the linearization
        order), then this returns a Future of the aligned reply bodies
        immediately — outcome columns, replica publishes, SLO rounds and
        reply encode all run at the wave's resolve boundary on the
        scheduler thread. The caller (IngestAggregator) is then free to
        decode and admission-charge window N+1 while window N's device
        rounds are still in flight."""
        fut: Future = Future()
        try:
            out, windowed, spans, count_of, rec, aux, decode_t = \
                self._window_prep(bodies)
            if not windowed:
                fut.set_result(out)
                return fut
            t_serve0 = time.monotonic() if self._tracer is not None \
                else 0.0
            st = self._serve_stage(rec, decode_t, aux)
            if not len(st.serve):
                cols = self._serve_resolve(st, [], 0.0)
                self._window_demux(out, windowed, spans, count_of, cols,
                                   aux, t_serve0)
                fut.set_result(out)
                return fut
            t0 = time.perf_counter()

            def _done(outcomes: List[Any], seqs: List[int]) -> None:
                try:
                    cols = self._serve_resolve(
                        st, outcomes, time.perf_counter() - t0, seqs)
                    self._window_demux(out, windowed, spans, count_of,
                                       cols, aux, t_serve0)
                    fut.set_result(out)
                except BaseException as e:  # noqa: BLE001 — never hang
                    fut.set_exception(e)

            self.backend.ask_many_async(st.ents, st.vals, st.ctxs, _done,
                                        keys=st.ask_keys)
        except BaseException as e:  # noqa: BLE001 — never hang the caller
            fut.set_exception(e)
        return fut

    def _window_prep(self, bodies: Sequence[bytes]):
        """Frame demux + merged decode + arrival-order row spans + mixed
        columnization — everything in `_serve_frames` upstream of the
        serve pass, shared with the async `submit_frames` path. Returns
        `(out, windowed, spans, count_of, rec, aux, decode_t)`; empty
        `windowed` means every frame was answered standalone and `out`
        is already complete."""
        n_f = len(bodies)
        out: List[Optional[bytes]] = [None] * n_f
        bin_idx: List[int] = []     # frame index per valid binary body
        bin_bodies: List[bytes] = []
        json_reqs: Dict[int, Dict[str, Any]] = {}  # frame idx -> parsed
        for f, body in enumerate(bodies):
            if frames.is_binary(body):
                try:
                    frames.check_request_batch(body, self.max_frame)
                except frames.FrameFormatError as e:
                    out[f] = self._bad_frame_reply(e)
                    continue
                bin_idx.append(f)
                bin_bodies.append(body)
                continue
            try:
                req = json.loads(body)
                tenant = str(req["tenant"])
                str(req["op"])  # the scalar path's parse contract
            except Exception as e:  # malformed: typed, keep serving
                out[f] = encode_body(self._bad_request_reply(e))
                continue
            if tenant == ADMIN_TENANT:
                out[f] = encode_body(self._handle_admin(
                    req.get("id", -1), str(req["op"]), req))
                continue
            json_reqs[f] = req
        if not bin_bodies and not json_reqs:
            return out, [], {}, {}, None, None, None

        # ---- merged decode: ONE frombuffer for the window's binary rows
        tr = self._tracer
        rec_bin = None
        counts: List[int] = []
        decode_t = None
        if bin_bodies:
            t0d = time.monotonic() if tr is not None else 0.0
            t0 = time.perf_counter_ns()
            rec_bin, counts = frames.decode_request_batches(
                bin_bodies, self.max_frame)
            if tr is not None:
                decode_t = (t0d, time.monotonic())
            if self._h_decode_size is not None and len(rec_bin):
                dt = time.perf_counter_ns() - t0
                step = self._registry.step
                self._h_decode_size.observe(float(len(rec_bin)), step=step)
                self._h_decode_ns.observe(dt / len(rec_bin), step=step)

        # ---- arrival-order row spans (rows must NOT sort binary-first:
        # same-entity adds linearize in window row order)
        count_of = dict(zip(bin_idx, counts))
        spans: Dict[int, Tuple[int, int]] = {}
        cursor = 0
        windowed = sorted(set(count_of) | set(json_reqs))
        for f in windowed:
            k = count_of.get(f, 1)
            spans[f] = (cursor, cursor + k)
            cursor += k
        n = cursor

        aux: Optional[_WindowAux] = None
        if not json_reqs:
            rec = rec_bin  # pure binary: zero-copy straight through
        else:
            rec, aux = self._columnize_mixed(rec_bin, bin_idx, spans,
                                             json_reqs, n)
        return out, windowed, spans, count_of, rec, aux, decode_t

    def _window_demux(self, out: List[Optional[bytes]],
                      windowed: List[int],
                      spans: Dict[int, Tuple[int, int]],
                      count_of: Dict[int, int], cols, aux,
                      t_serve0: float) -> None:
        """Reply columns back to per-frame bodies, each in its own
        encoding, plus the window-level join span. Runs on the serving
        thread in the synchronous path and at the wave's resolve
        boundary in the continuous path."""
        ids, status, reason, value, retry, traces, step_lag, dedups = cols
        tr = self._tracer
        if tr is not None and traces is not None and len(windowed) > 1:
            member = [int(t) for t in traces if t]
            if member:  # window-level join span, the ask.wave convention
                tr.emit("gw.ingest_window", member[0], t0=t_serve0,
                        t1=time.monotonic(), n_frames=len(windowed),
                        n_records=spans[windowed[-1]][1],
                        member_traces=member)

        # ---- demux: each frame's reply slice in its own encoding
        for f in windowed:
            lo, hi = spans[f]
            if f in count_of:
                out[f] = frames.encode_reply_batch(
                    ids[lo:hi], status[lo:hi], reason[lo:hi],
                    value[lo:hi], retry[lo:hi],
                    None if traces is None else traces[lo:hi],
                    step_lag[lo:hi],
                    None if dedups is None else dedups[lo:hi])
            else:
                out[f] = encode_body(self._row_reply(
                    lo, ids, status, reason, value, retry, traces, aux,
                    step_lag, dedups))

    @staticmethod
    def _columnize_mixed(rec_bin, bin_idx: List[int],
                         spans: Dict[int, Tuple[int, int]],
                         json_reqs: Dict[int, Dict[str, Any]],
                         n: int) -> Tuple[np.ndarray, _WindowAux]:
        """Lower parsed JSON requests into the binary record schema so a
        mixed window serves as ONE column pass. Tenant/entity columns
        widen to the window's longest JSON string (the wire's fixed
        widths are a floor, not a ceiling); binary records scatter into
        their arrival-order rows with five vectorized field copies."""
        aux = _WindowAux()
        tw, ew = frames.TENANT_BYTES, frames.ENTITY_BYTES
        prep: Dict[int, Tuple[Dict[str, Any], bytes, bytes]] = {}
        for f, req in json_reqs.items():
            r = spans[f][0]
            tb = str(req["tenant"]).encode("utf-8")
            eb = str(req["entity"]).encode("utf-8") \
                if "entity" in req else b""
            tw, ew = max(tw, len(tb)), max(ew, len(eb))
            prep[r] = (req, tb, eb)
        rec = np.zeros((n,), np.dtype(
            [("id", "i8"), ("op", "u1"), ("tenant", f"S{tw}"),
             ("entity", f"S{ew}"), ("value", "f8")]))
        if rec_bin is not None and len(rec_bin):
            rows = np.concatenate([np.arange(*spans[f]) for f in bin_idx])
            for field in ("id", "op", "tenant", "entity", "value"):
                rec[field][rows] = rec_bin[field]
        for r, (req, tb, eb) in prep.items():
            aux.json_rows.add(r)
            rid = req.get("id", -1)
            if type(rid) is int and -(1 << 63) <= rid < (1 << 63):
                rec["id"][r] = rid
            else:  # echo non-wire ids (str/float/null/huge) verbatim
                rec["id"][r] = -1
                aux.raw_ids[r] = rid
            rec["tenant"][r] = tb
            rec["entity"][r] = eb
            op = str(req["op"])
            aux.op_labels[r] = op
            code = frames.OP_CODES.get(op)
            if code is None:
                rec["op"][r] = _OP_JSON_UNKNOWN
                continue
            rec["op"][r] = code
            if code == frames.OP_ADD:
                try:
                    rec["value"][r] = float(req.get("value", 0.0))
                except Exception as e:  # typed, not a connection fault
                    rec["op"][r] = _OP_JSON_BAD_VALUE
                    aux.bad_values[r] = f"bad_request:{type(e).__name__}"
        return rec, aux

    @staticmethod
    def _row_reply(r: int, ids, status, reason, value, retry, traces,
                   aux: Optional[_WindowAux],
                   step_lag=None, dedups=None) -> Dict[str, Any]:
        """One window row back to the exact reply dict the scalar JSON
        path built: per-status key set, raw id echo, untruncated
        reasons, trace id on sampled replies; replica-served reads carry
        `replica`/`step_lag` exactly as a version-3 binary record's
        reply_to_dict does."""
        st = int(status[r])
        rid = aux.raw_ids.get(r, _MISSING) if aux is not None else _MISSING
        rep: Dict[str, Any] = {
            "id": int(ids[r]) if rid is _MISSING else rid}
        if st == frames.ST_OK:
            rep["status"] = "ok"
            rep["value"] = float(value[r])
            if step_lag is not None and int(step_lag[r]) >= 0:
                rep["replica"] = True
                rep["step_lag"] = int(step_lag[r])
        else:
            rep["status"] = "shed" if st == frames.ST_SHED else "error"
            full = aux.reasons_full.get(r) if aux is not None else None
            rep["reason"] = full if full is not None else \
                bytes(reason[r]).rstrip(b"\x00").decode("utf-8", "replace")
            if st == frames.ST_SHED:
                rep["retry_after_ms"] = int(retry[r])
        if dedups is not None and int(dedups[r]):
            rep["dedup"] = True  # the version-4 record flag's JSON twin
        if traces is not None and int(traces[r]):
            rep["trace"] = int(traces[r])
        return rep

    def _decode_window(self, bodies: Sequence[bytes]):
        """Decode one or more binary bodies; returns the record array or
        an encoded typed-error reply (bytes). Decode metrics ride the
        registry step axis like the ask-batch stats."""
        t0 = time.perf_counter_ns()
        try:
            recs = [frames.decode_request_batch(b, self.max_frame)
                    for b in bodies]
            rec = np.concatenate(recs) if len(recs) > 1 else recs[0]
        except frames.FrameFormatError as e:
            return self._bad_frame_reply(e)
        if self._h_decode_size is not None:
            dt = time.perf_counter_ns() - t0
            step = self._registry.step
            self._h_decode_size.observe(float(len(rec)), step=step)
            self._h_decode_ns.observe(dt / len(rec), step=step)
        return rec

    def _serve_records(self, rec: np.ndarray, decode_t=None,
                       aux: Optional[_WindowAux] = None):
        """The whole serving path, one record window at a time:
        admin/malformed checks -> vectorized per-tenant admission charge
        (ONE pressure poll via admit_groups) -> ONE ask wave ->
        vectorized reply columns. This is now the ONLY request path —
        solo JSON is a 1-row window — so check order is a single
        implementation, not a mirrored pair: missing entity is typed
        BEFORE admission and never charges the bucket; unknown op (and a
        JSON "add" whose value fails float()) is typed AFTER admission,
        charged. SLO counters are recorded per tenant with
        `record_many` — counter-identical to N scalar requests.

        Split at the stage/resolve seam (ISSUE 16): `_serve_stage` does
        everything UP TO the ask wave, `_serve_resolve` everything after
        it; this synchronous composition is the serialized serve path,
        bit-identical to PR 15, and `submit_frames` recomposes the same
        halves around an async continuous wave.

        `aux` (ISSUE 13) carries the JSON overlays of a mixed window:
        raw reply ids, op-label strings for span attrs and unknown_op
        reasons, and untruncated reasons for JSON replies.

        Tracing (ISSUE 12): each record gets its own head-sampled trace
        at ingress (one window holds MANY traces); sampled records get a
        root span whose ctx rides next to the request through the ask
        wave, and the reply wave carries the trace-id column (version-2
        records) when any record was sampled. Tracing off ⇒ one
        predicate, identical columns, version-1 bytes."""
        st = self._serve_stage(rec, decode_t, aux)
        outcomes: List[Any] = []
        dt = 0.0
        seqs: Optional[List[int]] = None
        if len(st.serve):
            t0 = time.perf_counter()
            if self.continuous:
                # even the synchronous path needs resolve ordinals when
                # waves overlap: concurrent handle_frame threads resolve
                # out of submit order under the continuous scheduler
                outcomes, seqs = self.backend.ask_many(
                    st.ents, st.vals, st.ctxs, with_seqs=True,
                    keys=st.ask_keys)
            else:
                outcomes = self._backend_ask_many(st.ents, st.vals,
                                                  st.ctxs, st.ask_keys)
            dt = time.perf_counter() - t0
        return self._serve_resolve(st, outcomes, dt, seqs)

    def _serve_stage(self, rec: np.ndarray, decode_t=None,
                     aux: Optional[_WindowAux] = None) -> "_ServeState":
        """Stage phase: reply columns allocated, traces rooted, typed
        admin/missing checks, the vectorized admission charge, unknown-op
        typing, replica reads — ending with the compacted serve rows
        (`st.serve/vals/ents/ctxs`) ready to ride an ask wave."""
        n = len(rec)
        st = _ServeState()
        st.aux = aux
        st.ids = rec["id"].astype(np.int64)
        ops = st.ops = rec["op"]
        tenants = st.tenants = rec["tenant"]
        entities = rec["entity"]
        status = st.status = np.full((n,), frames.ST_ERROR, np.uint8)
        reason = st.reason = np.zeros((n,), f"S{frames.REASON_BYTES}")
        value = st.value = np.zeros((n,), np.float64)
        retry = st.retry = np.zeros((n,), np.uint32)
        # >=0 <=> replica-served
        step_lag = st.step_lag = np.full((n,), -1, np.int32)

        tr = self._tracer
        st.traces = None
        roots = st.roots = {}
        if tr is not None:
            st.traces = np.zeros((n,), np.uint64)
            for i in range(n):
                is_json = aux is not None and i in aux.json_rows
                rid: Any = aux.raw_ids.get(i, _MISSING) if is_json \
                    else _MISSING
                if rid is _MISSING:
                    rid = int(st.ids[i])
                tid = tr.start_trace(
                    tenants[i].decode("utf-8", "replace"), rid)
                if tid:
                    st.traces[i] = tid
                    roots[i] = tr.begin(
                        "gw.request", tid, id=rid,
                        tenant=tenants[i].decode("utf-8", "replace"),
                        op=(aux.op_labels[i] if is_json else int(ops[i])),
                        proto="json" if is_json else "binary")
            if roots and decode_t is not None:
                # the window's decode, retro-emitted under the first
                # sampled root (one decode serves many traces — the
                # wave-span convention)
                first = next(iter(roots.values()))
                tr.emit("gw.decode", first.ctx, t0=decode_t[0],
                        t1=decode_t[1], n_records=n)

        admin = tenants == ADMIN_TENANT.encode("utf-8")
        reason[admin] = b"bad_request:admin_requires_json"
        missing = ~admin & (entities == b"")
        reason[missing] = b"bad_request:missing_entity"
        eligible = ~admin & ~missing

        # ---- vectorized per-tenant admission charge: ONE pressure poll
        # for the whole window, one bucket debit per tenant
        aspan = None
        if roots:  # one admit_batch span joined to the rest by traces
            aspan = tr.begin("gw.admit_batch",
                             next(iter(roots.values())).ctx,
                             member_traces=[s.trace_id
                                            for s in roots.values()])
        admitted = np.zeros((n,), bool)
        groups: Dict[bytes, np.ndarray] = {}
        if eligible.any():
            for t in np.unique(tenants[eligible]):
                groups[t] = np.nonzero(eligible & (tenants == t))[0]
        verdicts = self.admission.admit_groups(
            {t.decode("utf-8"): len(rows) for t, rows in groups.items()})
        for t, rows in groups.items():
            k, rej = verdicts[t.decode("utf-8")]
            admitted[rows[:k]] = True
            if rej is not None:
                shed = rows[k:]
                status[shed] = frames.ST_SHED
                reason[shed] = rej.reason.encode("utf-8") \
                    [:frames.REASON_BYTES]
                retry[shed] = int(rej.retry_after_s * 1e3)
                self._note(st, t, "reject", count=len(shed))
        if aspan is not None:
            aspan.finish(admitted=int(admitted.sum()))

        # unknown-op is typed AFTER admission (the scalar path charged
        # the bucket before it inspected the op); JSON sentinel rows
        # (unmappable op string, bad "add" value) ride the same branch
        known = np.isin(ops, (frames.OP_GET, frames.OP_ADD))
        for i in np.nonzero(admitted & ~known)[0]:
            full = aux.bad_values.get(i) if aux is not None else None
            if full is None:
                lbl = aux.op_labels.get(i) if aux is not None else None
                full = f"unknown_op:{lbl if lbl is not None else int(ops[i])}"
            self._set_reason(st, i, full)
            self._note(st, tenants[i], "error")
        for i in np.nonzero(missing)[0]:
            self._note(st, tenants[i], "error")

        # ---- replicated read path (ISSUE 14): hot-entity gets answered
        # from the local replica BEFORE the ask wave, strictly after the
        # admission charge (sheds/charging identical to the wave path);
        # stale-beyond-bound and cold entities fall through to the wave
        serve = np.nonzero(admitted & known)[0]
        cache = self.replica_cache
        if cache is not None and len(serve):
            t0r = time.perf_counter()
            replica_rows: List[int] = []
            for i in serve:
                if ops[i] != frames.OP_GET:
                    continue
                hit = cache.try_read(entities[i].decode("utf-8"))
                if hit is None:
                    continue
                status[i] = frames.ST_OK
                value[i], step_lag[i] = hit[0], hit[1]
                replica_rows.append(int(i))
            if replica_rows:
                dtr = time.perf_counter() - t0r
                for i in replica_rows:
                    self._note(st, tenants[i], "ok", dtr, replica=True)
                    sp = roots.get(i)
                    if sp is not None:  # parented under gw.request; the
                        # fall-through rows keep their ask.member spans
                        tr.emit("gw.replica_read", sp.ctx, t0=t0r,
                                t1=t0r + dtr, step_lag=int(step_lag[i]))
                keep = ~np.isin(serve, replica_rows)
                serve = serve[keep]

        # ---- journaled reply-cache dedup (ISSUE 20): ONE vectorized
        # check per window, strictly AFTER the admission charge (a shed
        # retry is a shed, never a cached hit) — duplicate ids replay
        # the cached reply and never re-enter the ask wave; same-window
        # duplicates alias their source row's reply at resolve; a
        # duplicate of a still-in-flight first attempt is a typed shed.
        dd = self.dedup
        if dd is not None and len(serve):
            keys: List[Optional[Tuple[str, int]]] = []
            for i in serve:
                if aux is not None and int(i) in aux.raw_ids:
                    keys.append(None)  # non-wire JSON ids never dedup
                else:
                    keys.append((tenants[i].decode("utf-8", "replace"),
                                 int(st.ids[i])))
            with self._dedup_lock:
                verdicts = dd.begin(keys)
            dedups = st.dedup = np.zeros((n,), np.uint8)
            keep = np.ones(len(serve), bool)
            for j, v in enumerate(verdicts):
                kind = v[0]
                i = int(serve[j])
                if kind == "hit":
                    status[i] = np.uint8(v[1])
                    value[i] = v[2]
                    if v[3]:
                        reason[i] = v[3]
                    dedups[i] = 1
                    keep[j] = False
                    self._note(st, tenants[i], "ok"
                               if v[1] == frames.ST_OK else "error")
                elif kind == "alias":
                    st.dedup_alias[i] = int(serve[v[1]])
                    dedups[i] = 1
                    keep[j] = False
                elif kind == "inflight":
                    status[i] = frames.ST_SHED
                    reason[i] = DUPLICATE_INFLIGHT.encode("utf-8") \
                        [:frames.REASON_BYTES]
                    retry[i] = 20  # first attempt resolves within a wave
                    dedups[i] = 1
                    keep[j] = False
                    self._note(st, tenants[i], "reject")
                elif kind == "miss":
                    st.dedup_keys[i] = keys[j]
            serve = serve[keep]

        st.serve = serve
        st.vals = np.where(ops[serve] == frames.OP_ADD,
                           rec["value"][serve].astype(np.float64), 0.0)
        st.ents = [entities[i].decode("utf-8") for i in serve]
        if st.dedup_keys:
            # aligned (tenant, id) per ask-wave member: rides the wave
            # into the entity journal's group commit (commit-before-ack
            # covers the reply cache) via ask_many(keys=)
            st.ask_keys = [st.dedup_keys.get(int(i)) for i in serve]
        st.ctxs = None
        if roots:  # each sampled request's ctx rides with its ask
            st.ctxs = [roots[i].ctx if i in roots else None
                       for i in serve]
        return st

    def _serve_resolve(self, st: "_ServeState", outcomes: List[Any],
                       dt: float, seqs: Optional[List[int]] = None):
        """Resolve phase: ask outcomes -> reply columns, replica
        publishes (seq-filtered when waves overlap), SLO rounds, root
        span finish. Runs on the serving thread in the synchronous path
        and on the scheduler thread at the wave's resolve boundary in
        the continuous path."""
        status, reason, value, retry = st.status, st.reason, st.value, \
            st.retry
        cache = self.replica_cache
        dd = self.dedup
        if len(st.serve):
            pool_noted = False
            wave_totals: Dict[str, float] = {}
            wave_seqs: Dict[str, int] = {}
            for j, (i, outc, ent) in enumerate(
                    zip(st.serve, outcomes, st.ents)):
                t = st.tenants[i]
                key = st.dedup_keys.get(int(i))
                if isinstance(outc, AskPoolExhausted):
                    if not pool_noted:
                        self.admission.note_ask_pool_exhausted()
                        pool_noted = True
                    status[i] = frames.ST_SHED
                    reason[i] = b"ask_pool_exhausted"
                    retry[i] = int(self.admission.cooldown_s * 1e3)
                    self._note(st, t, "reject")
                    if key is not None:  # nothing applied: retry fresh
                        with self._dedup_lock:
                            dd.release(key)
                elif isinstance(outc, TimeoutError):
                    reason[i] = b"timeout"
                    self._note(st, t, "timeout", dt)
                    if key is not None:
                        # ambiguous — the apply may have landed without
                        # latching a reply; cache the timeout so the id
                        # stays at-most-once (see dedup module docstring)
                        with self._dedup_lock:
                            dd.record(key, frames.ST_ERROR, 0.0,
                                      b"timeout")
                elif isinstance(outc, BaseException):
                    self._set_reason(st, i, f"fault:{type(outc).__name__}")
                    self._note(st, t, "error", dt)
                    if key is not None:  # typed fault: nothing applied
                        with self._dedup_lock:
                            dd.release(key)
                else:
                    status[i] = frames.ST_OK
                    value[i] = outc
                    self._note(st, t, "ok", dt)
                    if key is not None:
                        # journal already group-committed this reply
                        # (commit-before-ack); now the live table
                        with self._dedup_lock:
                            dd.record(key, frames.ST_OK, float(outc))
                    # last ok outcome per entity wins: rows are in wave
                    # linearization order, so this IS the post-wave total
                    wave_totals[ent] = float(outc)
                    if seqs is not None:
                        wave_seqs[ent] = int(seqs[j])
            if cache is not None and wave_totals:
                # ONE batched publish per ask wave (the coalesced-flush
                # boundary): authoritative totals re-arm the replica —
                # including for reads that just fell through as stale
                if seqs is None:
                    cache.publish_wave(wave_totals)
                else:
                    self._publish_filtered(wave_totals, wave_seqs)

        # same-window duplicates: copy the source row's resolved reply
        # (byte-identical on both encodings) — after the wave resolved
        # the source, before SLO rounds and span finishes
        for i, src in st.dedup_alias.items():
            status[i] = status[src]
            reason[i] = reason[src]
            value[i] = value[src]
            retry[i] = retry[src]
            stt = int(status[i])
            self._note(st, st.tenants[i],
                       "ok" if stt == frames.ST_OK else
                       ("reject" if stt == frames.ST_SHED else "error"))

        for t, outs in st.slo_outcomes.items():
            self.slo.record_many(t.decode("utf-8"), outs, st.slo_lat[t],
                                 st.slo_rep[t])
        if st.roots:
            st_names = {frames.ST_OK: "ok", frames.ST_SHED: "shed",
                        frames.ST_ERROR: "error"}
            aux = st.aux
            for i, sp in st.roots.items():
                full = aux.reasons_full.get(i) if aux is not None else None
                rsn = full if full is not None else \
                    bytes(reason[i]).rstrip(b"\x00") \
                    .decode("utf-8", "replace")
                sp.finish(status=st_names.get(int(status[i]), "error"),
                          **({"reason": rsn} if rsn else {}))
        return st.ids, status, reason, value, retry, st.traces, \
            st.step_lag, st.dedup

    def _publish_filtered(self, totals: Dict[str, float],
                          wave_seqs: Dict[str, int]) -> None:
        """Per-entity monotone replica publish for overlapping waves
        (ISSUE 16): a wave that resolves LATE must not overwrite an
        entity total a younger wave already published — each entity's
        publish is gated on its members' global resolve ordinal. The
        lock also serializes `publish_wave`'s step stamping, so the
        cache's own step-monotonic feed contract holds too."""
        with self._pub_lock:
            fresh: Dict[str, float] = {}
            for e, tot in totals.items():
                s = wave_seqs.get(e, 0)
                if s > self._pub_seq.get(e, -1):
                    self._pub_seq[e] = s
                    fresh[e] = tot
            if fresh:
                self.replica_cache.publish_wave(fresh)

    @staticmethod
    def _note(st: "_ServeState", t: bytes, outcome: str,
              lat: Optional[float] = None, count: int = 1,
              replica: bool = False) -> None:
        st.slo_outcomes.setdefault(t, []).extend([outcome] * count)
        st.slo_lat.setdefault(t, []).extend([lat] * count)
        st.slo_rep.setdefault(t, []).extend([replica] * count)

    @staticmethod
    def _set_reason(st: "_ServeState", i, full: str) -> None:
        # wire truncation on the column; JSON replies keep the full
        # string through the aux overlay (the scalar path never
        # truncated, so neither does its windowed twin)
        b = full.encode("utf-8")
        st.reason[i] = b[:frames.REASON_BYTES]
        if (st.aux is not None and len(b) > frames.REASON_BYTES
                and i in st.aux.json_rows):
            st.aux.reasons_full[int(i)] = full

    def _backend_ask_many(self, entity_ids: List[str],
                          values: np.ndarray,
                          ctxs: Optional[List[Any]] = None,
                          keys: Optional[List[Any]] = None) -> List[Any]:
        asker = getattr(self.backend, "ask_many", None)
        if asker is not None:
            # ctxs exist only when tracing is on; backends that batch
            # (RegionBackend) accept them, and the fallback loop below
            # pins each member's ctx as the ambient one per ask; keys
            # (ISSUE 20) ride only when dedup staged some — backends
            # without the kwarg never see it
            if keys is not None:
                return asker(entity_ids, values, ctxs, keys=keys)
            return asker(entity_ids, values) if ctxs is None \
                else asker(entity_ids, values, ctxs)
        out: List[Any] = []
        for j, (e, v) in enumerate(zip(entity_ids, values)):
            tok = set_ctx(ctxs[j]) \
                if ctxs is not None and ctxs[j] is not None else None
            try:
                out.append(self.backend.ask(e, float(v)))
            except Exception as exc:  # noqa: BLE001 — per-ask outcome
                out.append(exc)
            finally:
                if tok is not None:
                    reset_ctx(tok)
        return out

    # ---------------------------------------------------------------- admin
    def _handle_admin(self, rid, op: str, req: Dict[str, Any]) \
            -> Dict[str, Any]:
        """Operator channel (not admission-gated): chaos legs and probes
        ride the same wire as traffic."""
        try:
            if op == "sum":
                return {"id": rid, "status": "ok",
                        "value": self.backend.sum_all()}
            if op == "artifact":
                return {"id": rid, "status": "ok",
                        "data": self.slo.artifact()}
            if op == "stats":
                data = {"admission": self.admission.stats(),
                        "region": self.backend.region.stats(),
                        "ask_pool": self.backend.region.ask_pool_stats()}
                batcher = getattr(self.backend, "batcher", None)
                if batcher is not None:
                    data["ask_batch"] = batcher.stats()
                if self.dedup is not None:
                    with self._dedup_lock:
                        data["dedup"] = self.dedup.stats()
                return {"id": rid, "status": "ok", "data": data}
            if op == "checkpoint":
                return {"id": rid, "status": "ok",
                        "data": {"path": self.backend.region.checkpoint()}}
            if op == "rebalance":
                shard = int(req.get("value", 0))
                blk = self.backend.region.rebalance(shard)
                return {"id": rid, "status": "ok", "value": float(blk)}
            if op == "failover":
                import jax
                n = int(req.get("value", 1))
                region = self.backend.region
                step = region.failover(jax.devices()[:n])
                replayed = getattr(region, "_durable_replayed_totals",
                                   None)
                if self.replica_cache is not None and replayed is not None:
                    # failover truncated device state to the acked
                    # frontier — stale replica entries must not outlive it
                    self.replica_cache.republish_restored(replayed)
                return {"id": rid, "status": "ok", "value": float(step)}
            if op == "durable":
                region = self.backend.region
                ej = getattr(region, "_entity_journal", None)
                data: Dict[str, Any] = {"attached": ej is not None}
                if ej is not None:
                    data["journal"] = ej.stats()
                    data["replayed_entities"] = len(
                        region._durable_replayed_totals or {})
                store = getattr(region.spec, "remember_store", None)
                if store is not None:
                    data["remembered"] = sum(
                        len(store.remembered(region.type_name, str(s)))
                        for s in range(region.spec.n_shards))
                return {"id": rid, "status": "ok", "data": data}
            return {"id": rid, "status": "error",
                    "reason": f"unknown_admin_op:{op}"}
        except Exception as e:  # noqa: BLE001 — admin faults must reply
            return {"id": rid, "status": "error",
                    "reason": f"admin_fault:{type(e).__name__}:{e}"}


# ------------------------------------------------------------------- client
class GatewayClient:
    """Blocking raw-socket client (tests / load generators / example).
    One request in flight per connection; `request` returns the decoded
    reply dict. `request_retry` reconnects through server restarts — the
    chaos legs' client behavior.

    Idempotent sessions (ISSUE 20): every request id is
    `(session << 24) | seq` — a random per-client session tag over a
    monotone sequence — so ids are unique ACROSS clients and reconnects,
    and `request_retry` resends the SAME id on every attempt. Against a
    dedup-enabled gateway that makes a retried effect exactly-once: the
    server replays the cached reply instead of re-applying. The id is
    masked positive-int64 (the wire's `>i8`), leaving an effective
    39-bit session tag over a 24-bit sequence."""

    def __init__(self, host: str, port: int, timeout: float = 15.0,
                 max_frame: int = DEFAULT_MAX_FRAME,
                 session: Optional[int] = None):
        self.host = host
        self.port = port
        self.timeout = timeout
        self.max_frame = max_frame
        self._sock: Optional[socket.socket] = None
        self._reader = FrameReader(max_frame)
        self.session = random.getrandbits(64) if session is None \
            else int(session)
        self._seq = 0

    def _next_id(self) -> int:
        """Mint the next idempotent request id for this session."""
        self._seq += 1
        return ((self.session << 24) | (self._seq & 0xFFFFFF)) \
            & 0x7FFFFFFFFFFFFFFF

    def connect(self) -> None:
        self.close()
        s = socket.create_connection((self.host, self.port),
                                     timeout=self.timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = s
        self._reader = FrameReader(self.max_frame)

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def request(self, tenant: str, entity: str, op: str,
                value: float = 0.0) -> Dict[str, Any]:
        req = {"id": self._next_id(), "tenant": tenant, "entity": entity,
               "op": op, "value": value}
        return self._request_raw(req)

    def _request_raw(self, req: Dict[str, Any]) -> Dict[str, Any]:
        """Send a prebuilt request dict — `request_retry` resends the
        SAME dict (same id) across reconnects, the idempotent half of
        the exactly-once contract."""
        if self._sock is None:
            self.connect()
        self._sock.sendall(encode_frame(req))
        while True:
            data = self._sock.recv(65536)
            if not data:
                raise ConnectionError("gateway closed the connection")
            for reply in self._reader.feed(data):
                return reply

    def request_many(self, requests: Sequence[Tuple[str, str, str, float]]
                     ) -> List[Dict[str, Any]]:
        """Binary window ask: `requests` is a sequence of
        `(tenant, entity, op, value)`; the whole window rides ONE binary
        frame (one batch decode + one ask wave server-side) and the
        reply wave decodes to JSON-twin dicts, aligned with the input.
        One window in flight per connection, like `request`."""
        if self._sock is None:
            self.connect()
        ids, tenants, entities, ops, values = [], [], [], [], []
        for tenant, entity, op, val in requests:
            ids.append(self._next_id())
            tenants.append(tenant)
            entities.append(entity)
            ops.append(op)
            values.append(float(val))
        body = frames.encode_request_batch(ids, tenants, entities, ops,
                                           values)
        self._sock.sendall(frames.frame(body))
        while True:
            data = self._sock.recv(65536)
            if not data:
                raise ConnectionError("gateway closed the connection")
            for reply in self._reader.feed_raw(data):
                return frames.decode_replies(reply, self.max_frame)

    def request_binary(self, tenant: str, entity: str, op: str,
                       value: float = 0.0) -> Dict[str, Any]:
        """Solo binary ask — the JSON `request`'s bit-identical twin."""
        return self.request_many([(tenant, entity, op, value)])[0]

    def request_many_pipelined(
            self, windows: Sequence[Sequence[Tuple[str, str, str, float]]],
            depth: int = 4) -> List[List[Dict[str, Any]]]:
        """Depth-k pipelined binary windows (ISSUE 13): up to `depth`
        window frames outstanding on the connection before the first
        reply is read — the client-side load shape that actually fills
        the server's cross-connection ingest windows. Replies come back
        in order (the server's per-connection FIFO contract) and each is
        matched to its window by the first record's sequence id; a
        mismatch raises. Returns one reply list per input window,
        aligned."""
        if self._sock is None:
            self.connect()
        depth = max(1, int(depth))
        encoded: List[bytes] = []
        first_ids: List[int] = []
        for win in windows:
            if not win:
                raise ValueError("empty window in pipelined request")
            ids, tenants, entities, ops, values = [], [], [], [], []
            for tenant, entity, op, val in win:
                ids.append(self._next_id())
                tenants.append(tenant)
                entities.append(entity)
                ops.append(op)
                values.append(float(val))
            encoded.append(frames.frame(frames.encode_request_batch(
                ids, tenants, entities, ops, values)))
            first_ids.append(ids[0])
        out: List[List[Dict[str, Any]]] = []
        sent = 0
        while len(out) < len(encoded):
            while sent < len(encoded) and sent - len(out) < depth:
                self._sock.sendall(encoded[sent])
                sent += 1
            data = self._sock.recv(65536)
            if not data:
                raise ConnectionError("gateway closed the connection")
            for body in self._reader.feed_raw(data):
                reps = frames.decode_replies(body, self.max_frame)
                want = first_ids[len(out)]
                got = reps[0]["id"]
                if got != want:
                    raise ValueError(
                        f"pipelined reply out of order: got first id "
                        f"{got}, want {want}")
                out.append(reps)
        return out

    def request_retry(self, tenant: str, entity: str, op: str,
                      value: float = 0.0, deadline_s: float = 60.0,
                      pause_s: float = 0.2, max_backoff_s: float = 2.0,
                      jitter: float = 0.25,
                      retry_sheds: bool = False) -> Dict[str, Any]:
        """Retry through connection failures (server crash/restart) until
        `deadline_s`, resending the SAME request id on every attempt
        (idempotent session — a dedup-enabled gateway replays the cached
        reply instead of re-applying). Attempts pace with exponential
        backoff + jitter (`pattern/backoff.py`): `pause_s` is the floor,
        `max_backoff_s` the cap. Shed replies are returned to the caller
        (backoff on rejects is a POLICY, reconnection is plumbing) —
        except `duplicate_inflight`, which only this client's own retry
        can provoke, and sheds in general when `retry_sheds` is set.
        The returned reply carries `attempts` and, when any attempt
        failed, `last_error`."""
        deadline = time.monotonic() + deadline_s
        last: Optional[BaseException] = None
        attempts = 0
        req = {"id": self._next_id(), "tenant": tenant, "entity": entity,
               "op": op, "value": value}
        while time.monotonic() < deadline:
            attempts += 1
            delay = backoff_delay(attempts - 1, pause_s, max_backoff_s,
                                  jitter)
            try:
                rep = self._request_raw(req)
            except (OSError, ConnectionError, socket.timeout) as e:
                last = e
                self.close()
                time.sleep(delay)
                continue
            if rep.get("status") == "shed" and \
                    (retry_sheds or
                     rep.get("reason") == DUPLICATE_INFLIGHT):
                last = None
                time.sleep(max(delay,
                               rep.get("retry_after_ms", 0) / 1e3))
                continue
            rep["attempts"] = attempts
            if last is not None:
                rep["last_error"] = repr(last)
            return rep
        raise TimeoutError(f"gateway unreachable for {deadline_s}s: {last!r}")

    def admin(self, op: str, value: float = 0.0) -> Dict[str, Any]:
        return self.request(ADMIN_TENANT, "", op, value)
