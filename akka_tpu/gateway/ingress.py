"""Ingress: the framed-TCP front door onto sharded device entities.

Wire protocol — `simpleFramingProtocol` (stream/framing.py): every frame
is `[u32 big-endian length][body]`, and TWO body encodings coexist on
one connection, sniffed by the first body byte (ISSUE 11):

- **JSON** (first byte `{`) — the debuggable fallback and the admin
  channel. Requests:

      {"id": 7, "tenant": "t0", "entity": "acct-42", "op": "add",
       "value": 3}

  ops: "add" (apply value, reply new total — the acknowledged write),
  "get" (read total). Replies:

      {"id": 7, "status": "ok", "value": 45.0}
      {"id": 8, "status": "shed", "reason": "rate_limited",
       "retry_after_ms": 120}
      {"id": 9, "status": "error", "reason": "timeout"}

- **Binary** (first byte 0xAB — serialization/frames.py): a versioned
  fixed-schema batch of packed request records. A whole window decodes
  in ONE `np.frombuffer` pass into columns (op, entity, value) that
  feed the columnar ask wave (`RegionBackend.ask_many` ->
  `AskBatcher.ask_many` -> `execute_ask_batch`'s coalesced flush), and
  the reply wave encodes in one vectorized pass — zero per-request
  dict/object construction between wire bytes and the staging slab. A
  batch of one is the solo ask, bit-identical to its JSON twin.

"shed" is the admission layer speaking (typed backpressure — the client
knows why and when to retry); "error" is the runtime (ask timeout or
fault). The operator tenant `__admin` bypasses admission and reaches
control ops (sum / checkpoint / rebalance / failover / artifact / stats)
through the same front door — chaos is injected over the wire, the way
an operator would. Admin ops are JSON-only (a binary frame addressed to
the admin tenant gets a typed error): the operator channel stays
human-readable.

Request path: TCP bytes -> length-field decode -> handle_frame (admission
-> SLO clock -> backend ask) -> length-prefix encode -> TCP bytes. The
per-connection flow is ack-gated by the stream TCP layer (ONE Write in
flight), so a slow consumer throttles the producer instead of growing an
unbounded buffer — tested in tests/test_gateway.py. In-proc transports
(bench, batched load generators) can additionally hand
`handle_frame_batch` a window of frames: contiguous binary frames merge
into one decode + one ask wave.

ONE frame-size limit (`frames.DEFAULT_MAX_FRAME`) is the default at
BOTH ends — the server's framing stages and the client's FrameReader —
so a server-legal reply can never exceed what the client will reassemble
(the 1<<20 / 1<<16 mismatch is gone; pass `max_frame` to both ends
together to change it).

`handle_frame` is transport-free: the tier-1 smoke test and the
gateway-slo bench drive it in-proc; the chaos tier drives it over real
sockets from other OS processes.

Entity hosting: `RegionBackend` adapts a DeviceShardRegion — entities are
rows on the mesh, requests are region asks (reply-to promise row in the
payload's last column), writes are journaled tells (WAL) so acknowledged
writes survive kill -9. The counter entity keeps the reduction
COMMUTATIVE (the dense-inbox contract): "get" is add(0), and the reply is
always the post-apply total.
"""

from __future__ import annotations

import json
import socket
import struct
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..batched.bridge import AskPoolExhausted
from ..event.tracing import reset_ctx, set_ctx
from ..serialization import frames
from .admission import AdmissionController, Reject
from .slo import SloTracker

__all__ = ["encode_frame", "encode_body", "FrameReader", "counter_behavior",
           "RegionBackend", "GatewayServer", "GatewayClient",
           "DEFAULT_MAX_FRAME"]

ADMIN_TENANT = "__admin"

# one limit, both ends (see module docstring)
DEFAULT_MAX_FRAME = frames.DEFAULT_MAX_FRAME


# ---------------------------------------------------------------- wire codec
def encode_body(obj: Dict[str, Any]) -> bytes:
    """JSON reply/request body only — the stream encoder stage (or the
    in-proc caller) adds the length prefix."""
    return json.dumps(obj, separators=(",", ":")).encode("utf-8")


def encode_frame(obj: Dict[str, Any]) -> bytes:
    """Length-prefixed JSON frame: the ONE frame-encode helper (shared
    by server, client and the binary path via `frames.frame`)."""
    return frames.frame(encode_body(obj))


class FrameReader:
    """Incremental length-field frame reassembly for raw sockets (the
    client half; servers reuse the stream Framing stages). `feed` yields
    decoded JSON bodies; `feed_raw` yields raw bodies (the binary reply
    path decodes them with frames.decode_replies)."""

    def __init__(self, max_frame: int = DEFAULT_MAX_FRAME):
        self._buf = bytearray()
        self.max_frame = max_frame

    def feed_raw(self, data: bytes):
        self._buf.extend(data)
        while len(self._buf) >= 4:
            n = struct.unpack(">I", self._buf[:4])[0]
            if n > self.max_frame:
                raise ValueError(f"frame of {n} bytes exceeds "
                                 f"{self.max_frame}")
            if len(self._buf) < 4 + n:
                return
            body = bytes(self._buf[4:4 + n])
            del self._buf[:4 + n]
            yield body

    def feed(self, data: bytes):
        for body in self.feed_raw(data):
            yield json.loads(body)


# ------------------------------------------------------------ entity backend
def counter_behavior(payload_width: int, out_degree: int = 1):
    """The serving entity: an event-sourced additive counter. Payload
    [value, ..., reply_row]; the reduction sums concurrent adds (the
    dense-inbox commutative contract) and the reply is the new total,
    emitted to the reply-to row (bridge ask convention)."""
    import jax.numpy as jnp
    from ..batched import Emit, behavior
    from ..batched.bridge import reply_dst
    P, k = payload_width, out_degree

    @behavior("gw_counter", {"total": ((), jnp.float32)})
    def counter(state, inbox, ctx):
        got = inbox.count > 0
        new_total = state["total"] + inbox.sum[0]
        reply = jnp.zeros((P,), jnp.float32).at[0].set(new_total)
        return ({"total": jnp.where(got, new_total, state["total"])},
                Emit.single(reply_dst(inbox.sum), reply, k, P, when=got))

    return counter


class RegionBackend:
    """Adapts a DeviceShardRegion of counter entities to the gateway:
    ask(entity_id, value) -> new total (acknowledged = applied + WAL'd,
    when the region has attach_journal'd).

    Batched by default (ISSUE 9): `ask` submits to an AskBatcher
    (sharding/ask_batch.py) and waits on its future, so asks from
    concurrent connections coalesce into shared device step rounds —
    `handle_frame` stays synchronous per connection, batching emerges
    from concurrency. `batch=False` restores the serialized per-ask
    path (the bench A/B baseline); a single caller is bit-identical
    either way (a solo batch runs the exact old step schedule)."""

    def __init__(self, region, steps: int = 2, max_extra_steps: int = 16,
                 batch: bool = True, max_batch: int = 32,
                 batch_window_s: float = 200e-6, registry=None):
        self.region = region
        self.steps = steps
        self.max_extra_steps = max_extra_steps
        self.batcher = None
        if batch:
            from ..sharding.ask_batch import AskBatcher
            self.batcher = AskBatcher(
                region, max_batch=max_batch, window_s=batch_window_s,
                steps=steps, max_extra_steps=max_extra_steps,
                registry=registry)

    def ask(self, entity_id: str, value: float) -> float:
        ref = self.region.entity_ref(entity_id)
        if self.batcher is not None:
            reply = self.batcher.ask(ref.shard, ref.index, [float(value)])
        else:
            reply = self.region.ask(ref.shard, ref.index, [float(value)],
                                    steps=self.steps,
                                    max_extra_steps=self.max_extra_steps)
        return float(np.asarray(reply)[0])

    def ask_many(self, entity_ids: Sequence[str],
                 values: Sequence[float],
                 ctxs: Optional[Sequence[Any]] = None) -> List[Any]:
        """Columnar wave ask for a decoded binary window: entity ids are
        resolved ONCE per unique id, the whole wave rides
        `AskBatcher.ask_many` (one coalesced flush + one shared step
        budget, no per-call future hop) and the return is outcome-
        aligned — a float total or the per-ask exception INSTANCE
        (AskPoolExhausted / TimeoutError / ...), never a raise, so one
        member's failure cannot fail its wave-mates.

        `ctxs` (ISSUE 12): optional aligned per-request span contexts —
        one window carries many traces, so each sampled member's ctx
        travels next to its request instead of in the ambient var."""
        refs: Dict[str, Any] = {}
        for e in entity_ids:
            if e not in refs:
                try:
                    refs[e] = self.region.entity_ref(e)
                except Exception as exc:  # noqa: BLE001 — per-entity typed
                    refs[e] = exc
        reqs, slots = [], []
        req_ctxs: Optional[List[Any]] = [] if ctxs is not None else None
        out: List[Any] = [None] * len(entity_ids)
        for i, (e, v) in enumerate(zip(entity_ids, values)):
            r = refs[e]
            if isinstance(r, BaseException):
                out[i] = r
                continue
            reqs.append((r.shard, r.index, [float(v)]))
            slots.append(i)
            if req_ctxs is not None:
                req_ctxs.append(ctxs[i])
        if reqs:
            if self.batcher is not None:
                replies = self.batcher.ask_many(reqs, req_ctxs)
            else:
                replies = self.region.ask_many(
                    reqs, steps=self.steps,
                    max_extra_steps=self.max_extra_steps, ctxs=req_ctxs)
            for i, rep in zip(slots, replies):
                out[i] = rep if isinstance(rep, BaseException) \
                    else float(np.asarray(rep)[0])
        return out

    def close(self) -> None:
        if self.batcher is not None:
            self.batcher.close()

    def sum_all(self) -> float:
        """Conserved-value probe: sum of every spawned entity's total."""
        region = self.region
        with region._ask_lock:  # quiesce vs concurrent asks/maintenance
            return self._sum_locked(region)

    @staticmethod
    def _sum_locked(region) -> float:
        region.block_until_ready()
        rows = []
        with region._lock:
            for shard, ents in enumerate(region._entities):
                base = int(region._shard_block[shard]) * region.eps
                rows.extend(base + idx for idx in ents.values())
        if not rows:
            return 0.0
        vals = region.system.read_state(
            "total", np.asarray(sorted(rows), np.int32))
        return float(np.asarray(vals, np.float64).sum())

    def pressure_signals(self) -> Dict[str, Callable[[], float]]:
        from .admission import region_pressure_signals
        return region_pressure_signals(self.region)


# ------------------------------------------------------------------- server
class GatewayServer:
    """The front door: admission -> SLO clock -> backend ask, over TCP
    (stream layer) and/or in-proc frames (`handle_frame`)."""

    def __init__(self, system, backend, admission: AdmissionController,
                 slo: SloTracker, host: str = "127.0.0.1", port: int = 0,
                 max_frame: int = DEFAULT_MAX_FRAME, registry=None,
                 tracer=None):
        self.system = system
        self.backend = backend
        self.admission = admission
        self.slo = slo
        self.host = host
        self.port = port
        self.max_frame = max_frame
        self._binding = None
        self._seq = 0
        self._registry = registry
        # causal tracing (event/tracing.py): explicit tracer wins, else
        # the system-wired one (akka.tracing.* config); None keeps every
        # hook below at one `is not None` predicate
        self._tracer = tracer if tracer is not None \
            else getattr(system, "tracer", None)
        if self._tracer is not None:
            region = getattr(backend, "region", None)
            if region is not None and hasattr(region, "attach_tracer"):
                region.attach_tracer(self._tracer)
        self._h_decode_size = self._h_decode_ns = None
        if registry is not None:
            self._h_decode_size = registry.histogram(
                "gateway_decode_batch_size",
                "binary request records decoded per window")
            self._h_decode_ns = registry.histogram(
                "gateway_decode_ns_per_frame",
                "nanoseconds of wire decode per binary request record")

    # ------------------------------------------------------------ transport
    def start(self) -> Tuple[str, int]:
        from ..stream.dsl import Keep, Sink
        from ..stream.framing import Framing
        from ..stream.tcp import Tcp
        if self.port == 0:
            with socket.socket() as s:
                s.bind((self.host, 0))
                self.port = s.getsockname()[1]
        tcp = Tcp.get(self.system)

        def handle(conn):
            conn.handle_with(
                Framing.simple_framing_protocol_decoder(self.max_frame)
                .map(self.handle_frame)
                .via(Framing.simple_framing_protocol_encoder(
                    self.max_frame)),
                self.system)

        fut = tcp.bind(self.host, self.port) \
            .to_mat(Sink.foreach(handle), Keep.left).run(self.system)
        self._binding = fut.result(10.0)
        return self.host, self.port

    def stop(self) -> None:
        if self._binding is not None:
            self._binding.unbind()
            self._binding = None

    # ------------------------------------------------------------- requests
    def handle_frame(self, frame: bytes) -> bytes:
        if frames.is_binary(frame):
            return self.handle_binary(frame)
        tr = self._tracer
        try:
            req = json.loads(frame)
            rid = req.get("id", -1)
            tenant = str(req["tenant"])
            op = str(req["op"])
        except Exception as e:  # malformed frame: typed error, keep serving
            reason = f"bad_request:{type(e).__name__}"
            trace = tr.start_trace() if tr is not None else 0
            if trace:  # greppable: the reply's trace id is in the spans
                t_now = time.monotonic()
                tr.emit("gw.bad_request", trace, t0=t_now, t1=t_now,
                        reason=reason, proto="json")
            return encode_body(self._traced(
                {"id": -1, "status": "error", "reason": reason}, trace))
        if tenant == ADMIN_TENANT:
            return encode_body(self._handle_admin(rid, op, req))
        # head sampling: ONE decision per trace, made here at ingress
        trace = tr.start_trace(tenant, rid) if tr is not None else 0
        if not trace:
            return encode_body(self._serve_json(rid, tenant, op, req, 0))
        root = tr.span("gw.request", trace, id=rid, tenant=tenant, op=op,
                       proto="json")
        with root:  # sets the ambient ctx: submit() snapshots it
            rep = self._serve_json(rid, tenant, op, req, trace)
            root.set(status=rep.get("status"))
        return encode_body(rep)

    def _serve_json(self, rid, tenant: str, op: str, req: Dict[str, Any],
                    trace: int) -> Dict[str, Any]:
        """The JSON serving path behind the root span; every reply is
        trace-stamped when the request was sampled (ISSUE 12 satellite:
        a client-reported failure is greppable in the span JSONL)."""
        tr = self._tracer
        if "entity" not in req:
            # typed BEFORE admission: a malformed frame must not charge
            # the tenant's token bucket and then surface as fault:KeyError
            self.slo.record(tenant, "error")
            return self._traced(
                {"id": rid, "status": "error",
                 "reason": "bad_request:missing_entity"}, trace)
        if trace:
            with tr.span("gw.admit", trace):
                rej = self.admission.admit(tenant)
        else:
            rej = self.admission.admit(tenant)
        if rej is not None:
            self.slo.record(tenant, "reject")
            return self._traced(self._shed(rid, rej), trace)
        value = float(req.get("value", 0.0)) if op == "add" else 0.0
        if op not in ("add", "get"):
            self.slo.record(tenant, "error")
            return self._traced({"id": rid, "status": "error",
                                 "reason": f"unknown_op:{op}"}, trace)
        t0 = time.perf_counter()
        try:
            if trace:
                with tr.span("gw.ask", trace, entity=str(req["entity"])):
                    total = self.backend.ask(str(req["entity"]), value)
            else:
                total = self.backend.ask(str(req["entity"]), value)
        except AskPoolExhausted:
            # the typed fast-fail the admission layer sheds on: convert to
            # a shed reply AND arm the controller's cooldown
            self.admission.note_ask_pool_exhausted()
            self.slo.record(tenant, "reject")
            return self._traced(self._shed(
                rid, Reject("ask_pool_exhausted",
                            self.admission.cooldown_s)), trace)
        except TimeoutError:
            self.slo.record(tenant, "timeout",
                            time.perf_counter() - t0)
            return self._traced({"id": rid, "status": "error",
                                 "reason": "timeout"}, trace)
        except Exception as e:  # noqa: BLE001 — fault isolation per request
            # latency recorded on the fault leg too (the timeout leg always
            # did): error-leg p99s stay honest in the SLO artifact
            self.slo.record(tenant, "error", time.perf_counter() - t0)
            return self._traced({"id": rid, "status": "error",
                                 "reason": f"fault:{type(e).__name__}"},
                                trace)
        self.slo.record(tenant, "ok", time.perf_counter() - t0)
        return self._traced({"id": rid, "status": "ok", "value": total},
                            trace)

    @staticmethod
    def _traced(rep: Dict[str, Any], trace: int) -> Dict[str, Any]:
        """Mirror the trace id into the reply — EVERY reply of a sampled
        request, so the JSON dict stays the exact twin of a version-2
        binary record's reply_to_dict (trace column on all records)."""
        if trace:
            rep["trace"] = trace
        return rep

    @staticmethod
    def _shed(rid, rej: Reject) -> Dict[str, Any]:
        return {"id": rid, "status": "shed", "reason": rej.reason,
                "retry_after_ms": int(rej.retry_after_s * 1e3)}

    # ------------------------------------------------------ binary requests
    @staticmethod
    def _binary_error(code: str, trace: int = 0) -> bytes:
        """Typed malformed-binary reply (the `bad_request:` twin): one
        error record with id -1, mirroring the JSON path's keep-serving
        discipline. A sampled decode failure carries its trace id (the
        version-2 reply record) so the failure is greppable server-side."""
        return frames.encode_reply_batch(
            np.asarray([-1], np.int64),
            np.asarray([frames.ST_ERROR], np.uint8),
            np.asarray([f"bad_frame:{code}".encode("utf-8")
                        [:frames.REASON_BYTES]]),
            np.zeros(1), np.zeros(1, np.uint32),
            np.asarray([trace], np.uint64) if trace else None)

    def handle_binary(self, body: bytes) -> bytes:
        """One binary window: batch decode -> columnar serve -> one
        vectorized reply encode."""
        t0d = time.monotonic() if self._tracer is not None else 0.0
        rec = self._decode_window([body])
        if isinstance(rec, bytes):  # typed decode error
            return rec
        decode_t = (t0d, time.monotonic()) \
            if self._tracer is not None else None
        cols = self._serve_records(rec, decode_t)
        return frames.encode_reply_batch(*cols)

    def handle_frame_batch(self, bodies: Sequence[bytes]) -> List[bytes]:
        """Window entry point for in-proc transports and batched load
        generators: contiguous BINARY frames in `bodies` merge into one
        decode pass and ONE ask wave; JSON frames are served one by one
        (the fallback stays frame-at-a-time). Returns one reply body per
        input frame, aligned."""
        out: List[Optional[bytes]] = [None] * len(bodies)
        i = 0
        while i < len(bodies):
            if not frames.is_binary(bodies[i]):
                out[i] = self.handle_frame(bodies[i])
                i += 1
                continue
            # accumulate the contiguous binary run [i, j)
            j = i
            spans: List[Tuple[int, int]] = []  # (frame index, n records)
            recs = []
            while j < len(bodies) and frames.is_binary(bodies[j]):
                r = self._decode_window([bodies[j]])
                if isinstance(r, bytes):
                    out[j] = r  # typed decode error for THIS frame only
                else:
                    spans.append((j, len(r)))
                    recs.append(r)
                j += 1
            if recs:
                merged = np.concatenate(recs) if len(recs) > 1 else recs[0]
                ids, st, rsn, val, retry, trc = self._serve_records(merged)
                lo = 0
                for idx, n in spans:
                    hi = lo + n
                    out[idx] = frames.encode_reply_batch(
                        ids[lo:hi], st[lo:hi], rsn[lo:hi], val[lo:hi],
                        retry[lo:hi],
                        None if trc is None else trc[lo:hi])
                    lo = hi
            i = j
        return out  # type: ignore[return-value]

    def _decode_window(self, bodies: Sequence[bytes]):
        """Decode one or more binary bodies; returns the record array or
        an encoded typed-error reply (bytes). Decode metrics ride the
        registry step axis like the ask-batch stats."""
        t0 = time.perf_counter_ns()
        try:
            recs = [frames.decode_request_batch(b, self.max_frame)
                    for b in bodies]
            rec = np.concatenate(recs) if len(recs) > 1 else recs[0]
        except frames.FrameFormatError as e:
            tr = self._tracer
            trace = tr.start_trace() if tr is not None else 0
            if trace:  # the bad_frame reply's trace id is in the spans
                t_now = time.monotonic()
                tr.emit("gw.bad_frame", trace, t0=t_now, t1=t_now,
                        reason=f"bad_frame:{e.code}", proto="binary")
            return self._binary_error(e.code, trace)
        if self._h_decode_size is not None:
            dt = time.perf_counter_ns() - t0
            step = self._registry.step
            self._h_decode_size.observe(float(len(rec)), step=step)
            self._h_decode_ns.observe(dt / len(rec), step=step)
        return rec

    def _serve_records(self, rec: np.ndarray, decode_t=None):
        """The columnar twin of the JSON request path, one whole window
        at a time: admin/malformed checks -> vectorized per-tenant
        admission charge -> ONE ask wave -> vectorized reply columns.
        Check order mirrors the JSON path exactly (missing entity is
        typed BEFORE admission and never charges the bucket; unknown op
        is typed AFTER admission, charged, like JSON); SLO counters are
        recorded per tenant with `record_many` — counter-identical to N
        JSON requests.

        Tracing (ISSUE 12): each record gets its own head-sampled trace
        at ingress (one window holds MANY traces); sampled records get a
        root span whose ctx rides next to the request through the ask
        wave, and the reply wave carries the trace-id column (version-2
        records) when any record was sampled. Tracing off ⇒ one
        predicate, identical columns, version-1 bytes."""
        n = len(rec)
        ids = rec["id"].astype(np.int64)
        ops = rec["op"]
        tenants = rec["tenant"]
        entities = rec["entity"]
        status = np.full((n,), frames.ST_ERROR, np.uint8)
        reason = np.zeros((n,), f"S{frames.REASON_BYTES}")
        value = np.zeros((n,), np.float64)
        retry = np.zeros((n,), np.uint32)

        tr = self._tracer
        traces = None
        roots: Dict[int, Any] = {}
        if tr is not None:
            traces = np.zeros((n,), np.uint64)
            for i in range(n):
                tid = tr.start_trace(
                    tenants[i].decode("utf-8", "replace"), int(ids[i]))
                if tid:
                    traces[i] = tid
                    roots[i] = tr.begin(
                        "gw.request", tid, id=int(ids[i]),
                        tenant=tenants[i].decode("utf-8", "replace"),
                        op=int(ops[i]), proto="binary")
            if roots and decode_t is not None:
                # the window's decode, retro-emitted under the first
                # sampled root (one decode serves many traces — the
                # wave-span convention)
                first = next(iter(roots.values()))
                tr.emit("gw.decode", first.ctx, t0=decode_t[0],
                        t1=decode_t[1], n_records=n)

        admin = tenants == ADMIN_TENANT.encode("utf-8")
        reason[admin] = b"bad_request:admin_requires_json"
        missing = ~admin & (entities == b"")
        reason[missing] = b"bad_request:missing_entity"
        eligible = ~admin & ~missing

        slo_outcomes: Dict[bytes, List[str]] = {}
        slo_lat: Dict[bytes, List[Optional[float]]] = {}

        def note(t: bytes, outcome: str, lat: Optional[float] = None,
                 count: int = 1) -> None:
            slo_outcomes.setdefault(t, []).extend([outcome] * count)
            slo_lat.setdefault(t, []).extend([lat] * count)

        # ---- vectorized per-tenant admission charge (one debit/tenant)
        aspan = None
        if roots:  # one admit_batch span joined to the rest by traces
            aspan = tr.begin("gw.admit_batch",
                             next(iter(roots.values())).ctx,
                             member_traces=[s.trace_id
                                            for s in roots.values()])
        admitted = np.zeros((n,), bool)
        for t in np.unique(tenants[eligible]) if eligible.any() else ():
            rows = np.nonzero(eligible & (tenants == t))[0]
            k, rej = self.admission.admit_batch(t.decode("utf-8"), len(rows))
            admitted[rows[:k]] = True
            if rej is not None:
                shed = rows[k:]
                status[shed] = frames.ST_SHED
                reason[shed] = rej.reason.encode("utf-8") \
                    [:frames.REASON_BYTES]
                retry[shed] = int(rej.retry_after_s * 1e3)
                note(t, "reject", count=len(shed))
        if aspan is not None:
            aspan.finish(admitted=int(admitted.sum()))

        # unknown-op is typed AFTER admission (the JSON path charges the
        # bucket before it inspects the op)
        known = np.isin(ops, (frames.OP_GET, frames.OP_ADD))
        for i in np.nonzero(admitted & ~known)[0]:
            reason[i] = f"unknown_op:{int(ops[i])}".encode("utf-8") \
                [:frames.REASON_BYTES]
            note(tenants[i], "error")
        for i in np.nonzero(missing)[0]:
            note(tenants[i], "error")

        # ---- ONE ask wave for the whole admitted window
        serve = np.nonzero(admitted & known)[0]
        if len(serve):
            vals = np.where(ops[serve] == frames.OP_ADD,
                            rec["value"][serve].astype(np.float64), 0.0)
            ents = [entities[i].decode("utf-8") for i in serve]
            ctxs = None
            if roots:  # each sampled request's ctx rides with its ask
                ctxs = [roots[i].ctx if i in roots else None
                        for i in serve]
            t0 = time.perf_counter()
            outcomes = self._backend_ask_many(ents, vals, ctxs)
            dt = time.perf_counter() - t0
            pool_noted = False
            for i, outc in zip(serve, outcomes):
                t = tenants[i]
                if isinstance(outc, AskPoolExhausted):
                    if not pool_noted:
                        self.admission.note_ask_pool_exhausted()
                        pool_noted = True
                    status[i] = frames.ST_SHED
                    reason[i] = b"ask_pool_exhausted"
                    retry[i] = int(self.admission.cooldown_s * 1e3)
                    note(t, "reject")
                elif isinstance(outc, TimeoutError):
                    reason[i] = b"timeout"
                    note(t, "timeout", dt)
                elif isinstance(outc, BaseException):
                    reason[i] = f"fault:{type(outc).__name__}" \
                        .encode("utf-8")[:frames.REASON_BYTES]
                    note(t, "error", dt)
                else:
                    status[i] = frames.ST_OK
                    value[i] = outc
                    note(t, "ok", dt)

        for t, outs in slo_outcomes.items():
            self.slo.record_many(t.decode("utf-8"), outs, slo_lat[t])
        if roots:
            st_names = {frames.ST_OK: "ok", frames.ST_SHED: "shed",
                        frames.ST_ERROR: "error"}
            for i, sp in roots.items():
                rsn = bytes(reason[i]).rstrip(b"\x00")
                sp.finish(status=st_names.get(int(status[i]), "error"),
                          **({"reason": rsn.decode("utf-8", "replace")}
                             if rsn else {}))
        return ids, status, reason, value, retry, traces

    def _backend_ask_many(self, entity_ids: List[str],
                          values: np.ndarray,
                          ctxs: Optional[List[Any]] = None) -> List[Any]:
        asker = getattr(self.backend, "ask_many", None)
        if asker is not None:
            # ctxs exist only when tracing is on; backends that batch
            # (RegionBackend) accept them, and the fallback loop below
            # pins each member's ctx as the ambient one per ask
            return asker(entity_ids, values) if ctxs is None \
                else asker(entity_ids, values, ctxs)
        out: List[Any] = []
        for j, (e, v) in enumerate(zip(entity_ids, values)):
            tok = set_ctx(ctxs[j]) \
                if ctxs is not None and ctxs[j] is not None else None
            try:
                out.append(self.backend.ask(e, float(v)))
            except Exception as exc:  # noqa: BLE001 — per-ask outcome
                out.append(exc)
            finally:
                if tok is not None:
                    reset_ctx(tok)
        return out

    # ---------------------------------------------------------------- admin
    def _handle_admin(self, rid, op: str, req: Dict[str, Any]) \
            -> Dict[str, Any]:
        """Operator channel (not admission-gated): chaos legs and probes
        ride the same wire as traffic."""
        try:
            if op == "sum":
                return {"id": rid, "status": "ok",
                        "value": self.backend.sum_all()}
            if op == "artifact":
                return {"id": rid, "status": "ok",
                        "data": self.slo.artifact()}
            if op == "stats":
                data = {"admission": self.admission.stats(),
                        "region": self.backend.region.stats(),
                        "ask_pool": self.backend.region.ask_pool_stats()}
                batcher = getattr(self.backend, "batcher", None)
                if batcher is not None:
                    data["ask_batch"] = batcher.stats()
                return {"id": rid, "status": "ok", "data": data}
            if op == "checkpoint":
                return {"id": rid, "status": "ok",
                        "data": {"path": self.backend.region.checkpoint()}}
            if op == "rebalance":
                shard = int(req.get("value", 0))
                blk = self.backend.region.rebalance(shard)
                return {"id": rid, "status": "ok", "value": float(blk)}
            if op == "failover":
                import jax
                n = int(req.get("value", 1))
                step = self.backend.region.failover(jax.devices()[:n])
                return {"id": rid, "status": "ok", "value": float(step)}
            return {"id": rid, "status": "error",
                    "reason": f"unknown_admin_op:{op}"}
        except Exception as e:  # noqa: BLE001 — admin faults must reply
            return {"id": rid, "status": "error",
                    "reason": f"admin_fault:{type(e).__name__}:{e}"}


# ------------------------------------------------------------------- client
class GatewayClient:
    """Blocking raw-socket client (tests / load generators / example).
    One request in flight per connection; `request` returns the decoded
    reply dict. `request_retry` reconnects through server restarts — the
    chaos legs' client behavior."""

    def __init__(self, host: str, port: int, timeout: float = 15.0,
                 max_frame: int = DEFAULT_MAX_FRAME):
        self.host = host
        self.port = port
        self.timeout = timeout
        self.max_frame = max_frame
        self._sock: Optional[socket.socket] = None
        self._reader = FrameReader(max_frame)
        self._seq = 0

    def connect(self) -> None:
        self.close()
        s = socket.create_connection((self.host, self.port),
                                     timeout=self.timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = s
        self._reader = FrameReader(self.max_frame)

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def request(self, tenant: str, entity: str, op: str,
                value: float = 0.0) -> Dict[str, Any]:
        if self._sock is None:
            self.connect()
        self._seq += 1
        req = {"id": self._seq, "tenant": tenant, "entity": entity,
               "op": op, "value": value}
        self._sock.sendall(encode_frame(req))
        while True:
            data = self._sock.recv(65536)
            if not data:
                raise ConnectionError("gateway closed the connection")
            for reply in self._reader.feed(data):
                return reply

    def request_many(self, requests: Sequence[Tuple[str, str, str, float]]
                     ) -> List[Dict[str, Any]]:
        """Binary window ask: `requests` is a sequence of
        `(tenant, entity, op, value)`; the whole window rides ONE binary
        frame (one batch decode + one ask wave server-side) and the
        reply wave decodes to JSON-twin dicts, aligned with the input.
        One window in flight per connection, like `request`."""
        if self._sock is None:
            self.connect()
        ids, tenants, entities, ops, values = [], [], [], [], []
        for tenant, entity, op, val in requests:
            self._seq += 1
            ids.append(self._seq)
            tenants.append(tenant)
            entities.append(entity)
            ops.append(op)
            values.append(float(val))
        body = frames.encode_request_batch(ids, tenants, entities, ops,
                                           values)
        self._sock.sendall(frames.frame(body))
        while True:
            data = self._sock.recv(65536)
            if not data:
                raise ConnectionError("gateway closed the connection")
            for reply in self._reader.feed_raw(data):
                return frames.decode_replies(reply, self.max_frame)

    def request_binary(self, tenant: str, entity: str, op: str,
                       value: float = 0.0) -> Dict[str, Any]:
        """Solo binary ask — the JSON `request`'s bit-identical twin."""
        return self.request_many([(tenant, entity, op, value)])[0]

    def request_retry(self, tenant: str, entity: str, op: str,
                      value: float = 0.0, deadline_s: float = 60.0,
                      pause_s: float = 0.2) -> Dict[str, Any]:
        """Retry through connection failures (server crash/restart) until
        `deadline_s`. Shed replies are returned to the caller — backoff
        on rejects is a POLICY, reconnection is plumbing."""
        deadline = time.monotonic() + deadline_s
        last: Optional[Exception] = None
        while time.monotonic() < deadline:
            try:
                return self.request(tenant, entity, op, value)
            except (OSError, ConnectionError, socket.timeout) as e:
                last = e
                self.close()
                time.sleep(pause_s)
        raise TimeoutError(f"gateway unreachable for {deadline_s}s: {last!r}")

    def admin(self, op: str, value: float = 0.0) -> Dict[str, Any]:
        return self.request(ADMIN_TENANT, "", op, value)
