"""Admission control for the serving gateway: token buckets + load shed.

Two layers, both returning TYPED decisions (shed load is backpressure the
client can act on — retry_after, reason — never a silent drop):

1. Per-tenant token buckets (rate + burst): the fairness layer. One tenant
   flooding the front door cannot starve the others; its excess is shed
   with reason "rate_limited" while everyone else stays under SLO.

2. Pressure-driven shedding: the protection layer. The runtime already
   exposes every signal an overload shows up in FIRST — per-shard
   `mailbox_overflow`/`dropped` counters in the packed attention word
   (device mail being lost), bridge `pipeline_stats` (dispatch backlog),
   and ask-pool occupancy (promise rows claimed by in-flight asks, the
   typed `AskPoolExhausted` fast-fail when fully drained). The controller
   polls them at `check_interval` (they are device/stats reads — never
   per-request) and sheds with reason "overloaded:<signal>" while any
   holds, plus a cooldown so recovery is hysteretic, not flappy.

Reference shape: stream-level backpressure ends at the TCP edge; from
there on, the gateway converts queue growth into explicit rejects the way
Akka HTTP's `ServiceUnavailable` + Retry-After does, driven by the same
kind of signals a mailbox-size-based `MailboxPressure` custom dispatcher
would read.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from operator import itemgetter
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..batched.bridge import AskPoolExhausted

__all__ = ["TokenBucket", "VectorTenantTable", "Reject",
           "AdmissionController", "region_pressure_signals",
           "handle_pressure_signals", "AskPoolExhausted"]


class TokenBucket:
    """Classic token bucket: `rate` tokens/s refill, `burst` capacity.
    Lazy refill on acquire; monotonic clock injectable for tests."""

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst)
        self.clock = clock
        self._tokens = float(burst)
        self._last = clock()
        self._lock = threading.Lock()

    def try_acquire(self, n: float = 1.0) -> bool:
        with self._lock:
            now = self.clock()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.rate)
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def acquire_upto(self, n: int) -> int:
        """Vectorized charge: take as many whole tokens as available, up
        to `n`, in ONE refill+debit. Returns the count taken — exactly
        the number `n` sequential try_acquire() calls would have granted
        at this instant (fractional tokens never admit)."""
        with self._lock:
            now = self.clock()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.rate)
            self._last = now
            k = int(min(self._tokens, float(n)))
            if k > 0:
                self._tokens -= k
            return k

    def retry_after(self, n: float = 1.0) -> float:
        """Seconds until `n` tokens will be available (0 if now)."""
        with self._lock:
            missing = n - self._tokens
        return max(0.0, missing / self.rate) if self.rate > 0 else 60.0


class VectorTenantTable:
    """Columnar tenant admission state (ISSUE 18 tentpole): the token
    buckets of every RESIDENT tenant live as numpy columns — `tokens[f8]`,
    `last_refill[f8]`, `last_used[f8]` — indexed by an interned
    tenant-id -> slot table, so a whole ingest window's admission charge
    is ONE vectorized refill+debit

        tok = minimum(burst, tokens[slots] + (now - last[slots]) * rate)
        k   = minimum(floor(tok), n)          # fractional tokens never admit
        tokens[slots] = tok - k

    instead of a per-tenant walk over locked `TokenBucket` objects.

    Grant parity with sequential `TokenBucket.acquire_upto` is exact and
    bit-equal (asserted by tests/test_vector_admission.py): for integer
    `n` and `tok >= 0`, `min(floor(tok), n) == int(min(tok, float(n)))`,
    the refill expression is the same IEEE-754 arithmetic elementwise, and
    a fresh tenant interned at charge time starts at `tokens == burst`
    exactly as a just-constructed bucket refills to.

    Residency: columns grow by doubling up to `max_resident` slots; past
    that, interning a new tenant SPILLS the least-recently-used resident —
    its raw `(tokens, last_refill)` floats move to a plain dict — and a
    returning spilled tenant REHYDRATES those exact floats, so an
    LRU round trip is bit-invisible to grants. Cold-tenant state is two
    floats in a dict, not a lock + bucket object.

    Not internally locked: the AdmissionController serializes access
    under its own lock (the table replaces per-bucket locks, it does not
    add a second layer)."""

    def __init__(self, rate: float, burst: float,
                 max_resident: int = 1 << 17, init_capacity: int = 1024):
        self.rate = float(rate)
        self.burst = float(burst)
        self.max_resident = max(1, int(max_resident))
        cap = max(1, min(int(init_capacity), self.max_resident))
        self._cap = cap
        self._tokens = np.zeros(cap, np.float64)
        self._last = np.zeros(cap, np.float64)
        # +inf on free slots keeps them out of the LRU argmin
        self._last_used = np.full(cap, np.inf, np.float64)
        self._slot_of: Dict[str, int] = {}
        self._tenant_of: List[Optional[str]] = [None] * cap
        self._free: List[int] = list(range(cap - 1, -1, -1))
        self._spilled: Dict[str, Tuple[float, float]] = {}
        self.spills = 0
        self.rehydrates = 0
        self.vector_charges = 0

    # ------------------------------------------------------------ residency
    @property
    def resident(self) -> int:
        return len(self._slot_of)

    @property
    def tenant_count(self) -> int:
        return len(self._slot_of) + len(self._spilled)

    def _grow(self) -> None:
        new_cap = min(self.max_resident, self._cap * 2)
        grown = new_cap - self._cap
        self._tokens = np.concatenate(
            [self._tokens, np.zeros(grown, np.float64)])
        self._last = np.concatenate(
            [self._last, np.zeros(grown, np.float64)])
        self._last_used = np.concatenate(
            [self._last_used, np.full(grown, np.inf, np.float64)])
        self._tenant_of.extend([None] * grown)
        self._free.extend(range(new_cap - 1, self._cap - 1, -1))
        self._cap = new_cap

    def _evict_lru(self) -> int:
        s = int(np.argmin(self._last_used[:self._cap]))
        tenant = self._tenant_of[s]
        # spill the RAW floats: rehydration must be bit-invisible
        self._spilled[tenant] = (float(self._tokens[s]),
                                 float(self._last[s]))
        del self._slot_of[tenant]
        self._tenant_of[s] = None
        self._last_used[s] = np.inf
        self.spills += 1
        return s

    def _intern(self, tenant: str, now: float) -> int:
        s = self._slot_of.get(tenant)
        if s is not None:
            return s
        if not self._free:
            if self._cap < self.max_resident:
                self._grow()
            else:
                self._free.append(self._evict_lru())
        s = self._free.pop()
        self._slot_of[tenant] = s
        self._tenant_of[s] = tenant
        spilled = self._spilled.pop(tenant, None)
        if spilled is not None:
            self._tokens[s], self._last[s] = spilled
            self.rehydrates += 1
        else:
            # a fresh TokenBucket(rate, burst) refills to exactly burst
            # on its first acquire — start there, baselined at `now`
            self._tokens[s] = self.burst
            self._last[s] = now
        self._last_used[s] = now
        return s

    def _slots_for(self, tenants: Sequence[str], now: float) -> np.ndarray:
        """Slot indices for a window's tenant list, interning (and
        spilling/rehydrating) as needed. All-resident windows resolve in
        ONE itemgetter call — no per-tenant Python-object walk."""
        m = len(tenants)
        try:
            got = itemgetter(*tenants)(self._slot_of)
        except KeyError:
            if m > self.max_resident:
                raise ValueError(
                    f"window charges {m} tenants but max_resident is "
                    f"{self.max_resident}: every window tenant must be "
                    "resident for the vectorized charge")
            # slow path: some window tenant needs interning. Pin each
            # resolved slot at last_used=inf until the whole window is
            # mapped — a later intern's LRU eviction must never reclaim
            # a slot this window already holds an index to.
            slots = np.empty(m, np.int64)
            for j, t in enumerate(tenants):
                s = self._slot_of.get(t)
                if s is None:
                    s = self._intern(t, now)
                self._last_used[s] = np.inf
                slots[j] = s
            self._last_used[slots] = now  # the charge re-stamps anyway
            return slots
        if m == 1:
            return np.asarray([got], np.int64)
        return np.fromiter(got, np.int64, m)

    # -------------------------------------------------------------- charge
    def charge_groups(self, tenants: Sequence[str], counts: Sequence[int],
                      now: float) -> Tuple[np.ndarray, np.ndarray]:
        """ONE vectorized refill+debit for a window: `tenants` must be
        unique (they are dict keys upstream). Returns aligned
        `(granted[i8], retry_after[f8])` — granted is exactly what
        sequential `acquire_upto` calls would give each tenant, and
        retry_after is the post-debit time until 1 token, matching
        `TokenBucket.retry_after()` after the charge."""
        slots = self._slots_for(tenants, now)
        n = np.asarray(counts, np.float64)
        tok = np.minimum(self.burst,
                         self._tokens[slots]
                         + (now - self._last[slots]) * self.rate)
        k = np.minimum(np.floor(tok), n)
        self._tokens[slots] = tok - k
        self._last[slots] = now
        self._last_used[slots] = now
        self.vector_charges += 1
        if self.rate > 0:
            retry = np.maximum(0.0, (1.0 - (tok - k)) / self.rate)
        else:
            retry = np.full(len(slots), 60.0)
        return k.astype(np.int64), retry

    def acquire_upto(self, tenant: str, n: int, now: float) -> int:
        """Scalar twin of `charge_groups` for the single-request admit
        path — same arithmetic, plain-float fast path."""
        s = self._intern(tenant, now)
        tok = min(self.burst, float(self._tokens[s])
                  + (now - float(self._last[s])) * self.rate)
        k = int(min(tok, float(n)))
        self._tokens[s] = tok - k if k > 0 else tok
        self._last[s] = now
        self._last_used[s] = now
        return k

    def retry_after(self, tenant: str, n: float = 1.0) -> float:
        """Post-charge seconds until `n` tokens (no refill — call right
        after the charge, mirroring TokenBucket.retry_after)."""
        if self.rate <= 0:
            return 60.0
        s = self._slot_of.get(tenant)
        if s is not None:
            tokens = float(self._tokens[s])
        else:
            tokens = self._spilled.get(tenant, (self.burst, 0.0))[0]
        return max(0.0, (n - tokens) / self.rate)

    def stats(self) -> Dict[str, float]:
        return {"resident_tenants": float(len(self._slot_of)),
                "spilled_tenants": float(len(self._spilled)),
                "capacity": float(self._cap),
                "spills": float(self.spills),
                "rehydrates": float(self.rehydrates),
                "vector_charges": float(self.vector_charges)}


@dataclass
class Reject:
    """Typed shed decision: the wire reply carries both fields, so shed
    load is visible backpressure (reason + when to come back), never a
    timeout the client must discover."""

    reason: str
    retry_after_s: float = 0.0


class AdmissionController:
    """admit(tenant) -> None (admitted) | Reject(reason, retry_after).

    `pressure_signals` maps signal name -> zero-arg callable returning a
    float; a signal above its threshold (same key in `thresholds`) sheds
    ALL tenants with reason "overloaded:<name>" until it drops AND the
    `cooldown_s` hysteresis window passes. Signals are polled at most
    every `check_interval_s` — admission itself is lock + dict work.
    """

    def __init__(self, rate: float = 100.0, burst: float = 50.0,
                 pressure_signals: Optional[Dict[str, Callable[[], float]]]
                 = None,
                 thresholds: Optional[Dict[str, float]] = None,
                 check_interval_s: float = 0.05,
                 cooldown_s: float = 0.25,
                 clock: Callable[[], float] = time.monotonic,
                 metrics_registry=None, max_resident: int = 1 << 17):
        self.rate = float(rate)
        self.burst = float(burst)
        self.clock = clock
        self.pressure_signals = dict(pressure_signals or {})
        self.thresholds = dict(thresholds or {})
        self.check_interval_s = float(check_interval_s)
        self.cooldown_s = float(cooldown_s)
        # columnar tenant store (ISSUE 18): per-tenant TokenBucket
        # objects replaced by numpy columns + LRU spill past
        # max_resident; serialized under self._lock (the table carries
        # no lock of its own)
        self.table = VectorTenantTable(self.rate, self.burst,
                                       max_resident=max_resident)
        self._lock = threading.Lock()
        self._next_check = 0.0
        self._overload_until = 0.0
        self._overload_reason: Optional[str] = None
        self._last_values: Dict[str, float] = {}
        self.admitted = 0
        self.rejected = 0
        self.rejected_by_reason: Dict[str, int] = {}
        self._registry = metrics_registry
        if metrics_registry is not None:
            metrics_registry.register_collector("gateway_admission",
                                                self.stats)

    # ------------------------------------------------------------- signals
    def _poll_pressure(self, now: float) -> None:
        self._next_check = now + self.check_interval_s
        for name, fn in self.pressure_signals.items():
            try:
                v = float(fn())
            except Exception:  # a dead signal must not take down ingress
                continue
            self._last_values[name] = v
            if v > self.thresholds.get(name, float("inf")):
                self._overload_until = now + self.cooldown_s
                self._overload_reason = name

    def note_ask_pool_exhausted(self) -> None:
        """The backend fast-failed with AskPoolExhausted: treat it as an
        instantly-observed pressure signal (no poll latency) and shed for
        a cooldown window."""
        now = self.clock()
        with self._lock:
            self._overload_until = max(self._overload_until,
                                       now + self.cooldown_s)
            self._overload_reason = "ask_pool_exhausted"

    # -------------------------------------------------------------- admit
    def admit(self, tenant: str) -> Optional[Reject]:
        now = self.clock()
        with self._lock:
            if now >= self._next_check and self.pressure_signals:
                self._poll_pressure(now)
            if now < self._overload_until:
                self.rejected += 1
                reason = f"overloaded:{self._overload_reason}"
                self.rejected_by_reason[reason] = \
                    self.rejected_by_reason.get(reason, 0) + 1
                return Reject(reason, round(self._overload_until - now, 3))
            if self.table.acquire_upto(tenant, 1, now) == 1:
                self.admitted += 1
                return None
            self.rejected += 1
            self.rejected_by_reason["rate_limited"] = \
                self.rejected_by_reason.get("rate_limited", 0) + 1
            return Reject("rate_limited",
                          round(self.table.retry_after(tenant), 3))

    def admit_batch(self, tenant: str, n: int):
        """Vectorized per-tenant charge for a decoded binary window:
        admit the first `k` of `n` same-tenant requests with ONE bucket
        refill+debit instead of `n` lock round-trips. Returns
        `(k, reject)` where `reject` (a Reject, or None when k == n)
        carries the typed reason/retry for the `n - k` shed members.

        Counter/outcome parity with `n` sequential admit() calls is
        exact under a frozen clock: buckets are per-tenant, so charging
        a tenant's window in one debit grants the same k as charging its
        members one by one (fractional tokens never admit either way).
        Pressure is polled once per window instead of once per request —
        strictly fewer polls, same signals."""
        n = int(n)
        if n <= 0:
            return 0, None
        now = self.clock()
        with self._lock:
            if now >= self._next_check and self.pressure_signals:
                self._poll_pressure(now)
            if now < self._overload_until:
                self.rejected += n
                reason = f"overloaded:{self._overload_reason}"
                self.rejected_by_reason[reason] = \
                    self.rejected_by_reason.get(reason, 0) + n
                return 0, Reject(reason, round(self._overload_until - now, 3))
            k = self.table.acquire_upto(tenant, n, now)
            rej = None if k == n else Reject(
                "rate_limited", round(self.table.retry_after(tenant), 3))
            self.admitted += k
            if k < n:
                self.rejected += n - k
                self.rejected_by_reason["rate_limited"] = \
                    self.rejected_by_reason.get("rate_limited", 0) + (n - k)
        return k, rej

    def admit_groups(self, counts: Dict[str, int]):
        """Window-level charge for a cross-connection ingest window
        (ISSUE 13 / ISSUE 18): `counts` maps tenant -> request count;
        pressure is polled ONCE for the whole window, then EVERY tenant
        in the window is charged by ONE vectorized refill+debit on the
        columnar table — zero per-tenant Python-object walks for
        resident tenants. Returns `{tenant: (k, reject_or_None)}` —
        per-tenant outcome parity with one admit_batch call per tenant
        is exact (slots are independent columns; the poll is shared, and
        strictly fewer polls can only see the same-or-fresher
        signals)."""
        out: Dict[str, Any] = {}
        if not counts:
            return out
        now = self.clock()
        with self._lock:
            if now >= self._next_check and self.pressure_signals:
                self._poll_pressure(now)
            if now < self._overload_until:
                reason = f"overloaded:{self._overload_reason}"
                rej = Reject(reason, round(self._overload_until - now, 3))
                for tenant, n in counts.items():
                    n = int(n)
                    self.rejected += n
                    self.rejected_by_reason[reason] = \
                        self.rejected_by_reason.get(reason, 0) + n
                    out[tenant] = (0, rej)
                return out
            tenants = list(counts.keys())
            ns = [int(counts[t]) for t in tenants]
            ks, retry = self.table.charge_groups(tenants, ns, now)
            granted = int(ks.sum())
            shed = sum(ns) - granted
            self.admitted += granted
            if shed > 0:
                self.rejected += shed
                self.rejected_by_reason["rate_limited"] = \
                    self.rejected_by_reason.get("rate_limited", 0) + shed
            for j, tenant in enumerate(tenants):
                k, n = int(ks[j]), ns[j]
                out[tenant] = (k, None) if k == n else \
                    (k, Reject("rate_limited", round(float(retry[j]), 3)))
        return out

    # -------------------------------------------------------------- stats
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            overloaded = self.clock() < self._overload_until
            tstats = self.table.stats()
            return {"admitted": self.admitted,
                    "rejected": self.rejected,
                    "overloaded": int(overloaded),
                    "tenants": self.table.tenant_count,
                    "resident_tenants": int(tstats["resident_tenants"]),
                    "spilled_tenants": int(tstats["spilled_tenants"]),
                    "tenant_spills": int(tstats["spills"]),
                    "tenant_rehydrates": int(tstats["rehydrates"]),
                    **{f"signal_{k}": v
                       for k, v in self._last_values.items()}}


# -------------------------------------------------- runtime pressure wiring
def region_pressure_signals(region, batcher=None) \
        -> Dict[str, Callable[[], float]]:
    """Admission signals for a DeviceShardRegion backend.

    | signal             | source                                   |
    |--------------------|------------------------------------------|
    | mailbox_overflow   | attention word mailbox_overflow (total)  |
    | exchange_dropped   | attention word dropped (total)           |
    | ask_pool_occupancy | region promise-slot occupancy            |
    | open_wave_depth    | batcher open waves / pipeline_depth      |

    `batcher` (ISSUE 18 satellite): the backend's AskBatcher, when it
    has one — its `open_wave_depth` level sheds BEFORE the promise pool
    fills, because a full wave pipeline is the leading edge of the same
    overload ask_pool_occupancy reports one window later.

    Overflow counters are CUMULATIVE: the signal is their GROWTH since
    the previous poll (device mail being lost right now), so thresholds
    compare against a per-interval delta, and a long-dead spike does not
    shed forever.

    The delta/clamp bookkeeping lives in event/pressure.PressureReader —
    the SAME class the mesh autoscaler polls, so admission shedding and
    autoscaling can never disagree about what "pressure" means. Each
    caller gets its OWN reader (own baselines): the two consumers poll at
    different cadences and must not steal each other's deltas."""
    from ..event.pressure import PressureReader, system_pressure_sources
    return PressureReader(system_pressure_sources(
        region, ask_pool_stats=region.ask_pool_stats,
        open_wave_depth=(batcher.open_wave_depth
                         if batcher is not None else None))).signals()


def handle_pressure_signals(handle) -> Dict[str, Callable[[], float]]:
    """Admission signals for a BatchedRuntimeHandle backend: pipeline
    backlog (programs enqueued minus drained, vs configured depth) and
    ask-pool occupancy."""

    def backlog() -> float:
        st = handle.pipeline_stats()
        depth = max(1, int(st.get("depth", 1)))
        return (st.get("steps", 0) - st.get("drains", 0)) / depth

    return {"pipeline_backlog": backlog,
            "ask_pool_occupancy":
                lambda: float(handle.ask_pool_stats()["occupancy"])}
