"""Admission control for the serving gateway: token buckets + load shed.

Two layers, both returning TYPED decisions (shed load is backpressure the
client can act on — retry_after, reason — never a silent drop):

1. Per-tenant token buckets (rate + burst): the fairness layer. One tenant
   flooding the front door cannot starve the others; its excess is shed
   with reason "rate_limited" while everyone else stays under SLO.

2. Pressure-driven shedding: the protection layer. The runtime already
   exposes every signal an overload shows up in FIRST — per-shard
   `mailbox_overflow`/`dropped` counters in the packed attention word
   (device mail being lost), bridge `pipeline_stats` (dispatch backlog),
   and ask-pool occupancy (promise rows claimed by in-flight asks, the
   typed `AskPoolExhausted` fast-fail when fully drained). The controller
   polls them at `check_interval` (they are device/stats reads — never
   per-request) and sheds with reason "overloaded:<signal>" while any
   holds, plus a cooldown so recovery is hysteretic, not flappy.

Reference shape: stream-level backpressure ends at the TCP edge; from
there on, the gateway converts queue growth into explicit rejects the way
Akka HTTP's `ServiceUnavailable` + Retry-After does, driven by the same
kind of signals a mailbox-size-based `MailboxPressure` custom dispatcher
would read.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

from ..batched.bridge import AskPoolExhausted

__all__ = ["TokenBucket", "Reject", "AdmissionController",
           "region_pressure_signals", "handle_pressure_signals",
           "AskPoolExhausted"]


class TokenBucket:
    """Classic token bucket: `rate` tokens/s refill, `burst` capacity.
    Lazy refill on acquire; monotonic clock injectable for tests."""

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst)
        self.clock = clock
        self._tokens = float(burst)
        self._last = clock()
        self._lock = threading.Lock()

    def try_acquire(self, n: float = 1.0) -> bool:
        with self._lock:
            now = self.clock()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.rate)
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def acquire_upto(self, n: int) -> int:
        """Vectorized charge: take as many whole tokens as available, up
        to `n`, in ONE refill+debit. Returns the count taken — exactly
        the number `n` sequential try_acquire() calls would have granted
        at this instant (fractional tokens never admit)."""
        with self._lock:
            now = self.clock()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.rate)
            self._last = now
            k = int(min(self._tokens, float(n)))
            if k > 0:
                self._tokens -= k
            return k

    def retry_after(self, n: float = 1.0) -> float:
        """Seconds until `n` tokens will be available (0 if now)."""
        with self._lock:
            missing = n - self._tokens
        return max(0.0, missing / self.rate) if self.rate > 0 else 60.0


@dataclass
class Reject:
    """Typed shed decision: the wire reply carries both fields, so shed
    load is visible backpressure (reason + when to come back), never a
    timeout the client must discover."""

    reason: str
    retry_after_s: float = 0.0


class AdmissionController:
    """admit(tenant) -> None (admitted) | Reject(reason, retry_after).

    `pressure_signals` maps signal name -> zero-arg callable returning a
    float; a signal above its threshold (same key in `thresholds`) sheds
    ALL tenants with reason "overloaded:<name>" until it drops AND the
    `cooldown_s` hysteresis window passes. Signals are polled at most
    every `check_interval_s` — admission itself is lock + dict work.
    """

    def __init__(self, rate: float = 100.0, burst: float = 50.0,
                 pressure_signals: Optional[Dict[str, Callable[[], float]]]
                 = None,
                 thresholds: Optional[Dict[str, float]] = None,
                 check_interval_s: float = 0.05,
                 cooldown_s: float = 0.25,
                 clock: Callable[[], float] = time.monotonic,
                 metrics_registry=None):
        self.rate = float(rate)
        self.burst = float(burst)
        self.clock = clock
        self.pressure_signals = dict(pressure_signals or {})
        self.thresholds = dict(thresholds or {})
        self.check_interval_s = float(check_interval_s)
        self.cooldown_s = float(cooldown_s)
        self._buckets: Dict[str, TokenBucket] = {}
        self._lock = threading.Lock()
        self._next_check = 0.0
        self._overload_until = 0.0
        self._overload_reason: Optional[str] = None
        self._last_values: Dict[str, float] = {}
        self.admitted = 0
        self.rejected = 0
        self.rejected_by_reason: Dict[str, int] = {}
        self._registry = metrics_registry
        if metrics_registry is not None:
            metrics_registry.register_collector("gateway_admission",
                                                self.stats)

    # ------------------------------------------------------------- signals
    def _poll_pressure(self, now: float) -> None:
        self._next_check = now + self.check_interval_s
        for name, fn in self.pressure_signals.items():
            try:
                v = float(fn())
            except Exception:  # a dead signal must not take down ingress
                continue
            self._last_values[name] = v
            if v > self.thresholds.get(name, float("inf")):
                self._overload_until = now + self.cooldown_s
                self._overload_reason = name

    def note_ask_pool_exhausted(self) -> None:
        """The backend fast-failed with AskPoolExhausted: treat it as an
        instantly-observed pressure signal (no poll latency) and shed for
        a cooldown window."""
        now = self.clock()
        with self._lock:
            self._overload_until = max(self._overload_until,
                                       now + self.cooldown_s)
            self._overload_reason = "ask_pool_exhausted"

    # -------------------------------------------------------------- admit
    def admit(self, tenant: str) -> Optional[Reject]:
        now = self.clock()
        with self._lock:
            if now >= self._next_check and self.pressure_signals:
                self._poll_pressure(now)
            if now < self._overload_until:
                self.rejected += 1
                reason = f"overloaded:{self._overload_reason}"
                self.rejected_by_reason[reason] = \
                    self.rejected_by_reason.get(reason, 0) + 1
                return Reject(reason, round(self._overload_until - now, 3))
            bucket = self._buckets.get(tenant)
            if bucket is None:
                bucket = self._buckets[tenant] = TokenBucket(
                    self.rate, self.burst, self.clock)
        if not bucket.try_acquire():
            with self._lock:
                self.rejected += 1
                self.rejected_by_reason["rate_limited"] = \
                    self.rejected_by_reason.get("rate_limited", 0) + 1
            return Reject("rate_limited", round(bucket.retry_after(), 3))
        with self._lock:
            self.admitted += 1
        return None

    def admit_batch(self, tenant: str, n: int):
        """Vectorized per-tenant charge for a decoded binary window:
        admit the first `k` of `n` same-tenant requests with ONE bucket
        refill+debit instead of `n` lock round-trips. Returns
        `(k, reject)` where `reject` (a Reject, or None when k == n)
        carries the typed reason/retry for the `n - k` shed members.

        Counter/outcome parity with `n` sequential admit() calls is
        exact under a frozen clock: buckets are per-tenant, so charging
        a tenant's window in one debit grants the same k as charging its
        members one by one (fractional tokens never admit either way).
        Pressure is polled once per window instead of once per request —
        strictly fewer polls, same signals."""
        n = int(n)
        if n <= 0:
            return 0, None
        now = self.clock()
        with self._lock:
            if now >= self._next_check and self.pressure_signals:
                self._poll_pressure(now)
            if now < self._overload_until:
                self.rejected += n
                reason = f"overloaded:{self._overload_reason}"
                self.rejected_by_reason[reason] = \
                    self.rejected_by_reason.get(reason, 0) + n
                return 0, Reject(reason, round(self._overload_until - now, 3))
            bucket = self._buckets.get(tenant)
            if bucket is None:
                bucket = self._buckets[tenant] = TokenBucket(
                    self.rate, self.burst, self.clock)
        k = bucket.acquire_upto(n)
        rej = None if k == n else Reject("rate_limited",
                                         round(bucket.retry_after(), 3))
        with self._lock:
            self.admitted += k
            if k < n:
                self.rejected += n - k
                self.rejected_by_reason["rate_limited"] = \
                    self.rejected_by_reason.get("rate_limited", 0) + (n - k)
        return k, rej

    def admit_groups(self, counts: Dict[str, int]):
        """Window-level charge for a cross-connection ingest window
        (ISSUE 13): `counts` maps tenant -> request count; pressure is
        polled ONCE for the whole window, then each tenant's bucket is
        charged with one acquire_upto. Returns
        `{tenant: (k, reject_or_None)}` — per-tenant outcome parity with
        one admit_batch call per tenant is exact (buckets are
        independent; the poll is shared, and strictly fewer polls can
        only see the same-or-fresher signals)."""
        out: Dict[str, Any] = {}
        if not counts:
            return out
        now = self.clock()
        with self._lock:
            if now >= self._next_check and self.pressure_signals:
                self._poll_pressure(now)
            if now < self._overload_until:
                reason = f"overloaded:{self._overload_reason}"
                rej = Reject(reason, round(self._overload_until - now, 3))
                for tenant, n in counts.items():
                    n = int(n)
                    self.rejected += n
                    self.rejected_by_reason[reason] = \
                        self.rejected_by_reason.get(reason, 0) + n
                    out[tenant] = (0, rej)
                return out
            buckets = {}
            for tenant in counts:
                bucket = self._buckets.get(tenant)
                if bucket is None:
                    bucket = self._buckets[tenant] = TokenBucket(
                        self.rate, self.burst, self.clock)
                buckets[tenant] = bucket
        for tenant, n in counts.items():
            n = int(n)
            bucket = buckets[tenant]
            k = bucket.acquire_upto(n)
            rej = None if k == n else Reject(
                "rate_limited", round(bucket.retry_after(), 3))
            with self._lock:
                self.admitted += k
                if k < n:
                    self.rejected += n - k
                    self.rejected_by_reason["rate_limited"] = \
                        self.rejected_by_reason.get("rate_limited", 0) \
                        + (n - k)
            out[tenant] = (k, rej)
        return out

    # -------------------------------------------------------------- stats
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            overloaded = self.clock() < self._overload_until
            return {"admitted": self.admitted,
                    "rejected": self.rejected,
                    "overloaded": int(overloaded),
                    "tenants": len(self._buckets),
                    **{f"signal_{k}": v
                       for k, v in self._last_values.items()}}


# -------------------------------------------------- runtime pressure wiring
def region_pressure_signals(region) -> Dict[str, Callable[[], float]]:
    """Admission signals for a DeviceShardRegion backend.

    | signal             | source                                   |
    |--------------------|------------------------------------------|
    | mailbox_overflow   | attention word mailbox_overflow (total)  |
    | exchange_dropped   | attention word dropped (total)           |
    | ask_pool_occupancy | region promise-slot occupancy            |

    Overflow counters are CUMULATIVE: the signal is their GROWTH since
    the previous poll (device mail being lost right now), so thresholds
    compare against a per-interval delta, and a long-dead spike does not
    shed forever.

    The delta/clamp bookkeeping lives in event/pressure.PressureReader —
    the SAME class the mesh autoscaler polls, so admission shedding and
    autoscaling can never disagree about what "pressure" means. Each
    caller gets its OWN reader (own baselines): the two consumers poll at
    different cadences and must not steal each other's deltas."""
    from ..event.pressure import PressureReader, system_pressure_sources
    return PressureReader(system_pressure_sources(
        region, ask_pool_stats=region.ask_pool_stats)).signals()


def handle_pressure_signals(handle) -> Dict[str, Callable[[], float]]:
    """Admission signals for a BatchedRuntimeHandle backend: pipeline
    backlog (programs enqueued minus drained, vs configured depth) and
    ask-pool occupancy."""

    def backlog() -> float:
        st = handle.pipeline_stats()
        depth = max(1, int(st.get("depth", 1)))
        return (st.get("steps", 0) - st.get("drains", 0)) / depth

    return {"pipeline_backlog": backlog,
            "ask_pool_occupancy":
                lambda: float(handle.ask_pool_stats()["occupancy"])}
