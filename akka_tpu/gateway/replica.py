"""Replicated read path for hot entities (ISSUE 14 / ROADMAP item 5).

A gateway "get" is `add(0)` riding the full admission → ask-wave →
device-step → readback pipeline — the cheapest traffic served the most
expensive way. This module is the classic read-mostly scaling move:
writes KEEP their linearized wave path, but every wave's post-wave
totals are published (one batched publish per wave, not per request)
into a ddata-replicated `PNCounterMap`, and "get"s for entities promoted
hot are answered from the local replica BEFORE the ask wave under a
bounded-staleness contract.

The contract, precisely:

- **Publish**: after each ask wave, the authoritative post-wave total of
  every ok outcome is published with the current device step on the
  shared ATT_STEP axis (`system._host_step` via `step_fn`). Entities the
  wave touched get a fresh stamp whether the request was a get or an
  add — fall-throughs therefore re-arm the replica (self-healing).
- **Serve**: a replica read is served ONLY if the entity is hot
  (hit-count promotion within a window, TTL demotion) AND
  `step_fn() - published_step <= max_step_lag`. Any write that advances
  device steps without a publish for this entity pushes it past the
  bound and the read falls through to the authoritative wave — the
  bound cannot be exceeded by construction, only fallen through.
- **Replication**: totals travel as fixed-point integers (`scale`) in a
  PNCounterMap whose 1-entry updates gossip O(entry) via the op-based
  ORMap delta algebra (crdt.py); remote gateway nodes feed their cache
  through a replicator subscription. Writes linearize through the
  owning region's wave path, so publishes are effectively single-writer
  per entity; concurrent multi-gateway publishes of one entity can
  transiently deviate between publishes and are re-converged by the
  next publish (covered by the staleness fall-through).

Sheds and admission are charged identically to wave-served requests —
the replica branch runs strictly AFTER the admission charge.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = ["ReadReplicaCache", "REPLICA_KEY_ID"]

REPLICA_KEY_ID = "gw-replica-totals"

_STEP_PREFIX = "s:"  # map key of an entity's publish step


class ReadReplicaCache:
    """Hot-entity read replica over a ddata-replicated PNCounterMap.

    `step_fn` reads the shared ATT_STEP axis (the region system's
    `_host_step`). Without `system` (or without a ddata provider) the
    cache runs local-only: same promotion/staleness contract, no
    cross-node feed — the single-gateway fast path and the bench's A/B
    baseline."""

    def __init__(self, step_fn: Callable[[], int], system=None,
                 key_id: str = REPLICA_KEY_ID,
                 hot_hits: int = 4, hot_window_s: float = 1.0,
                 hot_ttl_s: float = 5.0, max_step_lag: int = 64,
                 scale: float = 1e6, registry=None):
        self.step_fn = step_fn
        self.max_step_lag = int(max_step_lag)
        self.hot_hits = int(hot_hits)
        self.hot_window_s = float(hot_window_s)
        self.hot_ttl_s = float(hot_ttl_s)
        self.scale = float(scale)
        self._lock = threading.Lock()
        # entity -> (total, publish step): the local replica view. On the
        # publishing node it is updated synchronously at the wave
        # boundary; on peers it is fed by the replicator subscription.
        self._replica: Dict[str, Tuple[float, int]] = {}
        # promotion state: entity -> [hits_in_window, window_t0, last_hit]
        self._hits: Dict[str, List[float]] = {}
        self._hot: Dict[str, float] = {}  # entity -> last hit wall time
        self._stats = {"gets": 0, "replica_served": 0, "fallthrough_stale": 0,
                       "fallthrough_cold": 0, "promotions": 0, "demotions": 0,
                       "publishes": 0, "published_entities": 0,
                       "max_served_lag": 0, "staleness_violations": 0,
                       "restore_republishes": 0}
        self._h_lag = None
        if registry is not None:
            self._h_lag = registry.histogram(
                "gateway_replica_step_lag",
                "step lag of replica-served reads (ATT_STEP axis)")
        self._registry = registry
        # -- optional ddata feed ------------------------------------------
        self._replicator = None
        self._node_id = None
        self._key = None
        self._subscriber = None
        if system is not None:
            self._wire_ddata(system, key_id)

    def _wire_ddata(self, system, key_id: str) -> None:
        try:
            from ..cluster.cluster import Cluster
            from ..ddata import DistributedData, Key, Subscribe
            from ..ddata.replicator import unique_node_id
            dd = DistributedData.get(system)
            self._replicator = dd.replicator
            self._key = Key(key_id)
            self._node_id = unique_node_id(
                Cluster.get(system).self_unique_address)
        except Exception:  # no cluster/ddata provider: local-only mode
            self._replicator = None
            return
        from ..actor.props import Props
        cache = self

        from ..actor.actor import Actor
        from ..ddata import Changed

        class _ReplicaFeed(Actor):
            def receive(self, msg):
                if isinstance(msg, Changed):
                    cache._ingest_map(msg.data)
                return True

        self._subscriber = system.system_actor_of(
            Props(factory=_ReplicaFeed), f"gwReplicaFeed-{id(self):x}")
        self._replicator.tell(
            Subscribe(self._key, self._subscriber), self._subscriber)

    # ------------------------------------------------------------- feed side
    def _ingest_map(self, data) -> None:
        """Replicated map -> local replica view. Steps are monotonic per
        entity, so a stale notification can never roll a stamp back."""
        try:
            entries = {k: data.get(k) for k in data.entries}
        except Exception:
            return
        with self._lock:
            for k, v in entries.items():
                if k.startswith(_STEP_PREFIX) or v is None:
                    continue
                step = entries.get(_STEP_PREFIX + k)
                if step is None:
                    continue
                cur = self._replica.get(k)
                if cur is None or int(step) >= cur[1]:
                    self._replica[k] = (float(v) / self.scale, int(step))

    def publish_wave(self, totals: Dict[str, float]) -> None:
        """ONE batched publish per ask wave: the authoritative post-wave
        totals of the wave's ok outcomes, stamped with the current device
        step. Local view updates synchronously; the replicated map gets a
        single Update whose op delta carries only the touched entries."""
        if not totals:
            return
        step = int(self.step_fn())
        with self._lock:
            for e, total in totals.items():
                self._replica[e] = (float(total), step)
            self._stats["publishes"] += 1
            self._stats["published_entities"] += len(totals)
        if self._replicator is not None:
            self._publish_ddata(totals, step)

    def _publish_ddata(self, totals: Dict[str, float], step: int) -> None:
        from ..ddata import PNCounterMap, Update, WriteLocal
        node, scale = self._node_id, self.scale

        def modify(m):
            for e, total in totals.items():
                fp = int(round(total * scale))
                cur = int(m.get(e) or 0)
                if fp > cur:
                    m = m.increment(node, e, fp - cur)
                elif fp < cur:
                    m = m.decrement(node, e, cur - fp)
                sk = _STEP_PREFIX + e
                cs = int(m.get(sk) or 0)
                if step > cs:
                    m = m.increment(node, sk, step - cs)
            return m

        self._replicator.tell(
            Update(self._key, PNCounterMap.empty(), WriteLocal(),
                   modify=modify), self._subscriber)

    def republish_restored(self,
                           totals: Optional[Dict[str, float]]) -> None:
        """Durable-restore seam: after a restart or in-process failover
        replays the entity journal, the device rows hold the acked
        frontier — but this cache (and the replicated map feeding peer
        gateways) can still hold pre-crash post-wave totals whose step
        stamps read as FRESH against the restored `_host_step`, because
        the restored step lands near the crash frontier. Entries the
        journal covers are re-published at the NEW step (overwriting the
        stale stamp locally and in the replicated map); entries it does
        not cover are dropped, since they can only describe pre-crash
        unacked state — those reads fall through to the wave."""
        totals = dict(totals) if totals else {}
        with self._lock:
            for e in [e for e in self._replica if e not in totals]:
                del self._replica[e]
            self._stats["restore_republishes"] += 1
        if totals:
            self.publish_wave(totals)

    # ------------------------------------------------------------- read side
    def try_read(self, entity: str) -> Optional[Tuple[float, int]]:
        """Replica answer for a get, or None to fall through to the
        authoritative wave. Returns (total, step_lag) only when the
        entity is hot AND fresh within `max_step_lag` — the bound is
        enforced here, so a served read can never exceed it."""
        now = time.monotonic()
        with self._lock:
            self._stats["gets"] += 1
            hot = self._note_hit_locked(entity, now)
            if not hot:
                return None
            rec = self._replica.get(entity)
            if rec is None:
                self._stats["fallthrough_cold"] += 1
                return None
            total, pub_step = rec
            lag = int(self.step_fn()) - pub_step
            if lag < 0 or lag > self.max_step_lag:
                self._stats["fallthrough_stale"] += 1
                return None
            self._stats["replica_served"] += 1
            if lag > self._stats["max_served_lag"]:
                self._stats["max_served_lag"] = lag
            if lag > self.max_step_lag:  # unreachable by construction
                self._stats["staleness_violations"] += 1
        if self._h_lag is not None:
            self._h_lag.observe(
                float(lag),
                step=self._registry.step if self._registry else None)
        return total, lag

    def _note_hit_locked(self, entity: str, now: float) -> bool:
        """Hit-count promotion with TTL demotion. Returns hotness AFTER
        this hit."""
        last = self._hot.get(entity)
        if last is not None:
            if now - last > self.hot_ttl_s:
                del self._hot[entity]
                self._stats["demotions"] += 1
            else:
                self._hot[entity] = now
                return True
        rec = self._hits.get(entity)
        if rec is None or now - rec[1] > self.hot_window_s:
            rec = self._hits[entity] = [1.0, now, now]
        else:
            rec[0] += 1
            rec[2] = now
        if rec[0] >= self.hot_hits:
            del self._hits[entity]
            self._hot[entity] = now
            self._stats["promotions"] += 1
            return True
        return False

    def is_hot(self, entity: str) -> bool:
        with self._lock:
            last = self._hot.get(entity)
            return last is not None and \
                time.monotonic() - last <= self.hot_ttl_s

    # --------------------------------------------------------------- report
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = dict(self._stats)
            out["hot_entities"] = len(self._hot)
            out["replica_entries"] = len(self._replica)
            out["max_step_lag"] = self.max_step_lag
            out["replicated"] = self._replicator is not None
            out["staleness_bound_held"] = \
                int(out["staleness_violations"] == 0)
        return out
