"""SLO tracking for the serving gateway, on the unified telemetry plane.

The tracker is a thin, hot-path-cheap layer over the existing
MetricsRegistry (event/metrics.py): per-outcome counters (ok / reject /
timeout / error, globally and per tenant), a log-bucket latency histogram
for p50/p99 against configured targets, and an error budget — all
step-stamped on the shared `ATT_STEP` axis via `registry.set_step`, so a
latency regression lines up against the same device step as the pipeline
and sentinel collectors.

`artifact()` is the stable JSON schema the bench, the watchdog row and
the chaos integration test all emit/assert (docs/SERVING_GATEWAY.md):

    {"requests", "ok", "rejects", "timeouts", "errors",
     "p50_ms", "p99_ms", "target_p50_ms", "target_p99_ms",
     "p50_met", "p99_met", "reject_rate",
     "slo_target", "error_budget_total", "error_budget_spent",
     "error_budget_remaining", "step", "per_tenant": {tenant: {...}}}
"""

from __future__ import annotations

import math
import threading
from collections import deque
from typing import Any, Dict, Optional

__all__ = ["SloTracker"]

_OUTCOMES = ("ok", "reject", "timeout", "error")


class SloTracker:
    """record(tenant, outcome, latency_s) on every request; artifact()
    for the SLO report. Registered as the "gateway" collector when a
    registry is supplied (gauges: akka_gateway_requests, _p99_ms, ...).

    The error budget follows the SRE convention: with `slo_target`
    success (default 99%), budget = (1 - slo_target) of requests may go
    bad (timeout/error — REJECTS ARE NOT SLO VIOLATIONS: shed load is the
    mechanism protecting the SLO, and it is reported separately as
    reject_rate)."""

    def __init__(self, registry=None,
                 target_p50_ms: float = 50.0,
                 target_p99_ms: float = 250.0,
                 slo_target: float = 0.99,
                 window: int = 8192):
        self.registry = registry
        self.target_p50_ms = float(target_p50_ms)
        self.target_p99_ms = float(target_p99_ms)
        self.slo_target = float(slo_target)
        self._lock = threading.Lock()
        self._counts = {o: 0 for o in _OUTCOMES}
        self._per_tenant: Dict[str, Dict[str, int]] = {}
        # sliding latency window (ms) + sorted-snapshot cache keyed on the
        # append counter, the pipeline_stats idiom: percentile pulls at
        # exposition time must not re-sort an unchanged window
        self._lat_ms: deque = deque(maxlen=int(window))
        self._lat_seq = 0
        self._lat_sorted = (-1, [])
        # replicated-read split (ISSUE 14): replica-served and
        # authoritative latencies in their own windows so artifact() can
        # report both percentile families; `_lat_ms` stays ALL admitted
        # traffic — existing consumers see identical numbers
        self._lat_rep: deque = deque(maxlen=int(window))
        self._lat_auth: deque = deque(maxlen=int(window))
        self._hist = None
        self._batcher = None
        self._aggregator = None
        self._autoscaler = None
        self._replica_cache = None
        if registry is not None:
            registry.register_collector("gateway", self._collect)
            self._hist = registry.histogram(
                "gateway_ask_latency_ms",
                "gateway request latency (admitted asks), milliseconds")

    def attach_batcher(self, batcher) -> None:
        """Carry the ask-batching summary (AskBatcher.stats: batches,
        asks, mean_batch_size, ...) in artifact() as `ask_batch`, so the
        bench rows, the watchdog row and the example's slo.json all show
        how much coalescing the traffic actually got. The size/window
        histograms live on the MetricsRegistry; this is the stable-schema
        summary next to the latency numbers it explains."""
        self._batcher = batcher

    def attach_aggregator(self, aggregator) -> None:
        """Carry the cross-connection ingest summary
        (IngestAggregator.stats: windows, frames, mean_window_size, ...)
        in artifact() as `ingest_window`, next to `ask_batch` — the two
        coalescing layers an operator reads together: how many frames
        shared one decode/admission round, and how many asks shared one
        device round. Size/wait histograms live on the MetricsRegistry
        (docs/OBSERVABILITY.md); this is the stable-schema summary."""
        self._aggregator = aggregator

    def attach_replica_cache(self, cache) -> None:
        """Carry the replicated-read summary (ReadReplicaCache.stats:
        promotions, replica_served, fall-throughs, staleness_bound_held)
        in artifact() as `replica_reads`, WITH the replicated-vs-
        authoritative percentile split — the number the hot-key
        read-storm bench leg asserts. Same stable-schema-summary
        contract as `ask_batch`."""
        self._replica_cache = cache

    def attach_autoscaler(self, autoscaler) -> None:
        """Carry the elastic-mesh summary (MeshAutoscaler.stats: widened/
        narrowed counts, current width, last trigger signal and pause) in
        artifact() as `autoscale` — an operator reading slo.json sees
        WHETHER the mesh moved under the latency numbers, and what it cost.
        Same stable-schema-summary contract as `ask_batch`."""
        self._autoscaler = autoscaler

    # -------------------------------------------------------------- record
    def record(self, tenant: str, outcome: str,
               latency_s: Optional[float] = None) -> None:
        if outcome not in _OUTCOMES:
            raise ValueError(f"unknown outcome {outcome!r}")
        with self._lock:
            self._counts[outcome] += 1
            per = self._per_tenant.get(tenant)
            if per is None:
                per = self._per_tenant[tenant] = {o: 0 for o in _OUTCOMES}
            per[outcome] += 1
            if latency_s is not None:
                self._lat_ms.append(latency_s * 1e3)
                self._lat_auth.append(latency_s * 1e3)
                self._lat_seq += 1
        if self._hist is not None and latency_s is not None:
            step = self.registry.step if self.registry is not None else None
            self._hist.observe(latency_s * 1e3, step=step)

    def record_many(self, tenant: str, outcomes, latencies_s=None,
                    replica_flags=None) -> None:
        """Wave recording for the batch-decoded ingress path: all of one
        tenant's outcomes from a reply wave under ONE lock acquisition,
        with the latency histogram fed in one vectorized observe.
        `outcomes` is a sequence of outcome names; `latencies_s` (same
        length or None) carries per-request latencies, None entries
        skipped — counter parity with N record() calls is exact.
        `replica_flags` (ISSUE 14, same length or None) marks replica-
        served requests so their latencies land in the split windows;
        omitted ⇒ everything counts authoritative."""
        counts: Dict[str, int] = {}
        for o in outcomes:
            if o not in _OUTCOMES:
                raise ValueError(f"unknown outcome {o!r}")
            counts[o] = counts.get(o, 0) + 1
        if not counts:
            return
        lats = [s for s in (latencies_s or ()) if s is not None]
        rep_lats: list = []
        auth_lats: list = []
        if latencies_s is not None:
            flags = replica_flags or (False,) * len(outcomes)
            for s, f in zip(latencies_s, flags):
                if s is None:
                    continue
                (rep_lats if f else auth_lats).append(s * 1e3)
        with self._lock:
            per = self._per_tenant.get(tenant)
            if per is None:
                per = self._per_tenant[tenant] = {o: 0 for o in _OUTCOMES}
            for o, c in counts.items():
                self._counts[o] += c
                per[o] += c
            if lats:
                self._lat_ms.extend(s * 1e3 for s in lats)
                self._lat_seq += len(lats)
                self._lat_rep.extend(rep_lats)
                self._lat_auth.extend(auth_lats)
        if self._hist is not None and lats:
            step = self.registry.step if self.registry is not None else None
            self._hist.observe_many([s * 1e3 for s in lats], step=step)

    # ---------------------------------------------------------- percentiles
    def percentile(self, q: float) -> float:
        """Nearest-rank percentile (ms) over the sliding window."""
        with self._lock:
            seq, d = self._lat_sorted
            if seq != self._lat_seq:
                d = sorted(self._lat_ms)
                self._lat_sorted = (self._lat_seq, d)
        if not d:
            return 0.0
        return d[max(math.ceil(q * len(d)) - 1, 0)]

    def _split_percentiles(self) -> Dict[str, float]:
        """p50/p99 of the replica-served and authoritative windows (the
        replicated-read split). Sorted on demand — this is exposition-
        time only (artifact/bench), never the hot path."""
        with self._lock:
            rep = sorted(self._lat_rep)
            auth = sorted(self._lat_auth)

        def pick(d, q):
            return d[max(math.ceil(q * len(d)) - 1, 0)] if d else 0.0
        return {"replica_p50_ms": round(pick(rep, 0.50), 3),
                "replica_p99_ms": round(pick(rep, 0.99), 3),
                "auth_p50_ms": round(pick(auth, 0.50), 3),
                "auth_p99_ms": round(pick(auth, 0.99), 3),
                "replica_lat_n": len(rep), "auth_lat_n": len(auth)}

    # -------------------------------------------------------------- report
    def artifact(self) -> Dict[str, Any]:
        with self._lock:
            counts = dict(self._counts)
            per_tenant = {t: dict(c) for t, c in self._per_tenant.items()}
        total = sum(counts.values())
        bad = counts["timeout"] + counts["error"]
        served = counts["ok"] + bad  # admitted traffic (SLO denominator)
        budget_total = (1.0 - self.slo_target) * served
        p50, p99 = self.percentile(0.50), self.percentile(0.99)
        step = self.registry.step if self.registry is not None else 0
        batch = ({"ask_batch": self._batcher.stats()}
                 if self._batcher is not None else {})
        ingest = ({"ingest_window": self._aggregator.stats()}
                  if self._aggregator is not None else {})
        scale = ({"autoscale": self._autoscaler.stats()}
                 if self._autoscaler is not None else {})
        replica = {}
        if self._replica_cache is not None:
            replica = {"replica_reads": {**self._replica_cache.stats(),
                                         **self._split_percentiles()}}
        return {
            **batch,
            **ingest,
            **scale,
            **replica,
            "requests": total,
            "ok": counts["ok"],
            "rejects": counts["reject"],
            "timeouts": counts["timeout"],
            "errors": counts["error"],
            "p50_ms": round(p50, 3),
            "p99_ms": round(p99, 3),
            "target_p50_ms": self.target_p50_ms,
            "target_p99_ms": self.target_p99_ms,
            "p50_met": int(p50 <= self.target_p50_ms),
            "p99_met": int(p99 <= self.target_p99_ms),
            "reject_rate": round(counts["reject"] / total, 4) if total else 0.0,
            "slo_target": self.slo_target,
            "error_budget_total": round(budget_total, 3),
            "error_budget_spent": bad,
            "error_budget_remaining": round(budget_total - bad, 3),
            "step": int(step),
            "per_tenant": per_tenant,
        }

    def _collect(self) -> Dict[str, float]:
        """Numeric slice of artifact() for the registry (per_tenant and
        target echoes stay in the JSON artifact)."""
        art = self.artifact()
        return {k: float(v) for k, v in art.items()
                if isinstance(v, (int, float))}
