"""Journaled reply-cache dedup: exactly-once effects for retried asks.

The serving path is durable (entity journal, commit-before-ack) and
retry-capable (`GatewayClient.request_retry`), but the two composed
wrong: a reply lost AFTER the wave group-commits — connection death
post-commit, or kill -9 between the fsync and the ack hitting the wire
— made the client resend and the entity double-apply. This module is
the server half of the fix (ISSUE 20): a `ReplyCacheTable` in the
`VectorTenantTable` style (gateway/admission.py) remembers the reply of
every resolved request id, so a duplicate id short-circuits with the
cached reply and never re-enters the ask wave.

Layout: cached replies of every RESIDENT key live as numpy columns —
`id[i8]`, `status[u1]`, `value[f8]`, `reason slot[i4]`, resolve
`ord[i8]`, `last_used[f8]` — indexed by an interned (tenant, id) → slot
table, so a whole ingest window's dedup check gathers its columns in
one fancy-index pass after ONE dict resolve. Reference shape: Akka 2.6
reliable delivery's ConsumerController seq-nr dedup, ported onto the
columnar window machinery.

Three bounds keep the table honest:

- **Per-tenant window** (`window`, default 4096 ids): each tenant's
  remembered ids form an insertion-ordered window; recording past it
  FORGETS the oldest id entirely. A retry of a forgotten id re-applies
  — the documented at-least-once degradation, priced per tenant so one
  chatty tenant cannot evict another's dedup frontier.
- **LRU residency spill** (`max_resident` slots): past it, the
  least-recently-used resident row spills its RAW scalars to a dict and
  a later hit rehydrates them bit-identically (the admission table's
  spill contract) — a spilled id still dedups, it just pays a dict
  lookup.
- **Pending TTL**: a key staged into an in-flight wave is `pending`;
  a duplicate arriving while its first attempt is still in flight gets
  a typed `duplicate_inflight` shed (retry_after, never a second
  application — the cross-wave row-ownership race the tentpole closes).
  A pending entry older than `pending_ttl_s` is presumed leaked by a
  crashed serve path and degrades to a miss.

What gets recorded: ok replies (the journaled exactly-once frontier —
they ride the entity journal's group commit via `append_wave(replies=)`
and are rehydrated on restore) and ask timeouts (ambiguous: the apply
may have landed without latching a reply, so the cached timeout keeps
the id at-most-once; after a crash the unjournaled apply rolls back and
the lost cache entry correctly lets the retry re-apply). Sheds and
typed faults are never recorded — nothing applied, the client retries
fresh.

Not internally locked: the GatewayServer serializes begin/record under
its own dedup lock, exactly as the AdmissionController serializes the
tenant table (the table replaces per-key state, it does not add a
second lock layer).
"""

from __future__ import annotations

import time
from collections import deque
from typing import (Any, Callable, Deque, Dict, List, Optional, Sequence,
                    Tuple)

import numpy as np

__all__ = ["ReplyCacheTable", "DUPLICATE_INFLIGHT"]

# typed shed reason for a duplicate whose first attempt is still in an
# open wave — the client backs off retry_after_ms and resends SAME id
DUPLICATE_INFLIGHT = "duplicate_inflight"

Key = Tuple[str, int]


class ReplyCacheTable:
    """Columnar reply cache keyed by (tenant, request id). See module
    docstring for the contract; `begin` is the one-per-window dedup
    check, `record`/`release` the resolve-boundary writebacks, `load`
    the journal-restore rehydrate."""

    def __init__(self, window: int = 4096, max_resident: int = 1 << 17,
                 init_capacity: int = 1024, pending_ttl_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        self.window = max(1, int(window))
        self.max_resident = max(1, int(max_resident))
        self.pending_ttl_s = float(pending_ttl_s)
        self.clock = clock
        cap = max(1, min(int(init_capacity), self.max_resident))
        self._cap = cap
        self._ids = np.zeros(cap, np.int64)
        self._status = np.zeros(cap, np.uint8)
        self._value = np.zeros(cap, np.float64)
        self._reason = np.zeros(cap, np.int32)
        self._ord = np.zeros(cap, np.int64)
        # +inf on free slots keeps them out of the LRU argmin
        self._last_used = np.full(cap, np.inf, np.float64)
        self._slot_of: Dict[Key, int] = {}
        self._key_of: List[Optional[Key]] = [None] * cap
        self._free: List[int] = list(range(cap - 1, -1, -1))
        # spilled rows keep their RAW scalars: rehydration is bit-exact
        self._spilled: Dict[Key, Tuple[int, float, bytes, int]] = {}
        # interned reason byte strings; slot 0 is the empty reason
        self._reasons: List[bytes] = [b""]
        self._reason_slot: Dict[bytes, int] = {b"": 0}
        # per-tenant insertion-ordered id windows (the dedup frontier)
        self._order: Dict[str, Deque[int]] = {}
        # keys staged into an in-flight wave -> stage timestamp
        self._pending: Dict[Key, float] = {}
        self._next_ord = 0
        self.hits = 0
        self.misses = 0
        self.alias_hits = 0
        self.inflight_sheds = 0
        self.spills = 0
        self.rehydrates = 0
        self.window_evictions = 0
        self.pending_expired = 0
        self.records = 0
        self.loads = 0

    # ------------------------------------------------------------ residency
    @property
    def resident(self) -> int:
        return len(self._slot_of)

    @property
    def cached(self) -> int:
        return len(self._slot_of) + len(self._spilled)

    def _grow(self) -> None:
        new_cap = min(self.max_resident, self._cap * 2)
        grown = new_cap - self._cap
        self._ids = np.concatenate(
            [self._ids, np.zeros(grown, np.int64)])
        self._status = np.concatenate(
            [self._status, np.zeros(grown, np.uint8)])
        self._value = np.concatenate(
            [self._value, np.zeros(grown, np.float64)])
        self._reason = np.concatenate(
            [self._reason, np.zeros(grown, np.int32)])
        self._ord = np.concatenate(
            [self._ord, np.zeros(grown, np.int64)])
        self._last_used = np.concatenate(
            [self._last_used, np.full(grown, np.inf, np.float64)])
        self._key_of.extend([None] * grown)
        self._free.extend(range(new_cap - 1, self._cap - 1, -1))
        self._cap = new_cap

    def _evict_lru(self) -> int:
        s = int(np.argmin(self._last_used[:self._cap]))
        key = self._key_of[s]
        self._spilled[key] = (int(self._status[s]), float(self._value[s]),
                              self._reasons[int(self._reason[s])],
                              int(self._ord[s]))
        del self._slot_of[key]
        self._key_of[s] = None
        self._last_used[s] = np.inf
        self.spills += 1
        return s

    def _intern_reason(self, reason: bytes) -> int:
        s = self._reason_slot.get(reason)
        if s is None:
            s = len(self._reasons)
            self._reasons.append(reason)
            self._reason_slot[reason] = s
        return s

    def _intern(self, key: Key, now: float) -> int:
        s = self._slot_of.get(key)
        if s is not None:
            return s
        if not self._free:
            if self._cap < self.max_resident:
                self._grow()
            else:
                self._free.append(self._evict_lru())
        s = self._free.pop()
        self._slot_of[key] = s
        self._key_of[s] = key
        self._last_used[s] = now
        return s

    def _drop(self, key: Key) -> None:
        """Forget a key entirely (window eviction): resident slot back
        to the free list, spilled entry deleted."""
        s = self._slot_of.pop(key, None)
        if s is not None:
            self._key_of[s] = None
            self._last_used[s] = np.inf
            self._free.append(s)
        else:
            self._spilled.pop(key, None)

    # --------------------------------------------------------------- check
    def begin(self, keys: Sequence[Optional[Key]]
              ) -> List[Tuple[Any, ...]]:
        """THE per-window dedup check: one verdict per key, aligned.
        Non-dedupable rows (key None — non-integer JSON ids) get
        ("skip",). Verdicts:

          ("miss",)                  first sighting — the key is now
                                     PENDING and must be resolved with
                                     record() or release()
          ("hit", status, value, reason)   cached reply, replay it
          ("alias", j)               duplicate of this window's row j —
                                     copy row j's resolved reply
          ("inflight",)              first attempt still in an open
                                     wave — typed duplicate_inflight

        Resident hits gather their columns in one fancy-index pass;
        spilled hits rehydrate their raw scalars first (bit-exact)."""
        now = self.clock()
        n = len(keys)
        out: List[Tuple[Any, ...]] = [("skip",)] * n
        seen: Dict[Key, int] = {}
        probe_rows: List[int] = []
        probe_slots: List[int] = []
        for j, key in enumerate(keys):
            if key is None:
                continue
            first = seen.get(key)
            if first is not None:
                out[j] = ("alias", first)
                self.alias_hits += 1
                continue
            ts = self._pending.get(key)
            if ts is not None:
                if now - ts <= self.pending_ttl_s:
                    out[j] = ("inflight",)
                    self.inflight_sheds += 1
                    continue
                # a serve path that crashed mid-wave leaked the key:
                # presume dead and let the retry through
                del self._pending[key]
                self.pending_expired += 1
            s = self._slot_of.get(key)
            if s is not None:
                probe_rows.append(j)
                probe_slots.append(s)
                self._last_used[s] = now
                continue
            spilled = self._spilled.pop(key, None)
            if spilled is not None:
                # rehydrate the raw scalars into a fresh slot so the
                # next hit rides the columnar path
                status, value, reason, ordn = spilled
                s = self._intern(key, now)
                self._ids[s] = key[1]
                self._status[s] = status
                self._value[s] = value
                self._reason[s] = self._intern_reason(reason)
                self._ord[s] = ordn
                self.rehydrates += 1
                self.hits += 1
                out[j] = ("hit", status, value, reason)
                continue
            out[j] = ("miss",)
            seen[key] = j
            self._pending[key] = now
            self.misses += 1
        if probe_rows:
            slots = np.asarray(probe_slots, np.int64)
            statuses = self._status[slots]
            values = self._value[slots]
            reasons = self._reason[slots]
            for k, j in enumerate(probe_rows):
                out[j] = ("hit", int(statuses[k]), float(values[k]),
                          self._reasons[int(reasons[k])])
                self.hits += 1
        return out

    # -------------------------------------------------------------- resolve
    def record(self, key: Key, status: int, value: float,
               reason: bytes = b"") -> None:
        """Resolve-boundary writeback: cache the reply and clear the
        pending mark. Enforces the per-tenant window — recording id
        N+window forgets the tenant's oldest remembered id."""
        now = self.clock()
        self._pending.pop(key, None)
        fresh = key not in self._slot_of and key not in self._spilled
        s = self._intern(key, now)
        self._ids[s] = key[1]
        self._status[s] = status
        self._value[s] = value
        self._reason[s] = self._intern_reason(bytes(reason))
        self._ord[s] = self._next_ord
        self._next_ord += 1
        self.records += 1
        if fresh:
            order = self._order.get(key[0])
            if order is None:
                order = self._order[key[0]] = deque()
            order.append(key[1])
            while len(order) > self.window:
                self._drop((key[0], order.popleft()))
                self.window_evictions += 1

    def release(self, key: Key) -> None:
        """Clear a pending mark WITHOUT caching (the ask failed without
        applying — shed/fault): the retry runs fresh."""
        self._pending.pop(key, None)

    def lookup(self, key: Key) -> Optional[Tuple[int, float, bytes]]:
        """Point probe (tests / tools): (status, value, reason) or None.
        Does not touch pending state or the hit counters."""
        s = self._slot_of.get(key)
        if s is not None:
            return (int(self._status[s]), float(self._value[s]),
                    self._reasons[int(self._reason[s])])
        spilled = self._spilled.get(key)
        if spilled is not None:
            return spilled[0], spilled[1], spilled[2]
        return None

    # -------------------------------------------------------------- restore
    def load(self, entries: Sequence[Tuple[str, int, int, float]]) -> int:
        """Rehydrate the dedup frontier from the entity journal's
        replayed reply records: `(tenant, id, status, value)` tuples in
        journal order. Returns the number loaded. Window bounds apply —
        a journal longer than the window keeps only each tenant's
        newest `window` ids, exactly as the live path would have."""
        n = 0
        for tenant, rid, status, value in entries:
            self.record((str(tenant), int(rid)), int(status), float(value))
            n += 1
        self.loads += n
        self.records -= n  # loads are not live records
        return n

    # ---------------------------------------------------------------- stats
    def stats(self) -> Dict[str, float]:
        checks = self.hits + self.alias_hits + self.misses
        return {
            "hits": float(self.hits),
            "alias_hits": float(self.alias_hits),
            "misses": float(self.misses),
            "inflight_sheds": float(self.inflight_sheds),
            "spills": float(self.spills),
            "rehydrates": float(self.rehydrates),
            "window_evictions": float(self.window_evictions),
            "pending_expired": float(self.pending_expired),
            "records": float(self.records),
            "loads": float(self.loads),
            "resident": float(len(self._slot_of)),
            "spilled": float(len(self._spilled)),
            "pending": float(len(self._pending)),
            "window": float(self.window),
            "hit_ratio": ((self.hits + self.alias_hits) / checks)
            if checks else 0.0,
        }
