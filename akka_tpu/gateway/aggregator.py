"""Cross-connection ingest windowing (ISSUE 13 tentpole).

The batched ingress (PR 11) made one SOCKET's frames decode in one pass;
this module makes windows out of CONCURRENCY: frames from MANY
connections land in one shared queue tagged (conn_id, seq), a window
closes on `max_window` request RECORDS or a microsecond deadline — the
AskBatcher's adaptive-close shape (sharding/ask_batch.py), reused
verbatim via `wait_adaptive_close` — and the whole window runs the
gateway's columnar serve path ONCE (`GatewayServer._serve_frames`): one
merged `np.frombuffer` decode for every binary body, JSON bodies lowered
into the SAME record columns, one vectorized admission charge
(`admit_groups`: one pressure poll), one ask wave, one SLO round. Reply
bodies then demux back to each connection's Future in FIFO order.

Ordering: windows are served sequentially by ONE dispatcher thread and
frames enter the queue in per-connection arrival order (the TCP stage
calls `submit` synchronously per frame), so per-connection FIFO is
structural — and the stream layer's ordered MapAsync drain re-asserts it
at the reply writer regardless of completion order.

Backpressure: each connection holds at most `pipeline_depth` frames in
flight (the MapAsync in-flight cap), so the shared queue is bounded by
depth x connections and the TCP demand chain stays intact — a slow
consumer still throttles its own socket, never the window.

Observability: `gateway_ingest_window_size` (records per window) and
`gateway_ingest_window_wait_us` (per-frame wait for window close), both
step-stamped on the shared ATT_STEP axis; `stats()` is the stable
`ingest_window` summary the SLO artifact carries next to `ask_batch`
(docs/OBSERVABILITY.md, docs/SERVING_GATEWAY.md).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional

from ..serialization import frames
from ..sharding.ask_batch import (IDLE_WAIT_MAX, IDLE_WAIT_MIN,
                                  wait_adaptive_close)

__all__ = ["IngestAggregator"]


class _PendingFrame:
    __slots__ = ("body", "future", "records", "conn_id", "seq", "t_submit")


class IngestAggregator:
    """Shared decode/admission/ask windows across connections.

    `submit(body, conn_id)` returns a Future of the reply body; the
    dispatcher closes windows on `max_window` records or `window_s`
    seconds, whichever first (a lone frame under light load waits at
    most the deadline — latency is bounded, batching is opportunistic).
    `close()` drains: every pending frame is SERVED before the
    dispatcher exits, never stranded."""

    def __init__(self, server, max_window: int = 64,
                 window_s: float = 150e-6, registry=None):
        self.server = server
        self.max_window = max(1, int(max_window))
        self.window_s = float(window_s)
        self._lock = threading.Lock()
        self._work = threading.Event()
        # continuous wave formation (ISSUE 16): when the server's
        # backend runs the continuous scheduler, windows are submitted
        # asynchronously (`submit_frames`) — the dispatcher stages
        # window N and immediately starts decoding/admitting window N+1
        # while N's device rounds are in flight. The semaphore bounds
        # dispatcher windows in flight to the server's pipeline depth.
        self._continuous = bool(getattr(server, "continuous", False)) \
            and hasattr(server, "submit_frames")
        self._inflight = 0
        self._idle_wakeups = 0
        self._depth_sem = threading.BoundedSemaphore(
            max(1, int(getattr(server, "pipeline_depth", 4))))
        self._pending: List[_PendingFrame] = []
        self._pending_records = 0
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._seq = 0
        self._windows = 0
        self._frames = 0
        self._records = 0
        self._multi = 0
        self._max_seen = 0
        self._registry = registry
        self._h_size = self._h_wait = None
        if registry is not None:
            self._h_size = registry.histogram(
                "gateway_ingest_window_size",
                "request records aggregated per cross-connection "
                "ingest window")
            self._h_wait = registry.histogram(
                "gateway_ingest_window_wait_us",
                "microseconds a frame waited for its ingest window "
                "to close")
            registry.register_collector("ingest_window", self.stats)

    # ------------------------------------------------------------- submit
    @staticmethod
    def _peek_records(body: bytes) -> int:
        """Window-close unit: a binary body's record count straight from
        its header (count field, bytes 4..8 big-endian — no decode), 1
        for JSON and for anything malformed (the serve path types those
        per frame)."""
        if len(body) >= 8 and body[0] == frames.MAGIC:
            return max(1, int.from_bytes(body[4:8], "big"))
        return 1

    def submit(self, body: bytes, conn_id: int = 0) -> "Future[bytes]":
        """Queue one frame body for the next window; returns a Future of
        its reply body. Frames are tagged (conn_id, seq) on arrival —
        seq is the shared queue's total order, which is also each
        connection's FIFO order because the TCP stage submits
        synchronously per frame."""
        f = _PendingFrame()
        f.body = body
        f.future = Future()
        f.records = self._peek_records(body)
        f.conn_id = int(conn_id)
        f.t_submit = time.perf_counter()
        with self._lock:
            if self._closed:
                raise RuntimeError("IngestAggregator is closed")
            self._seq += 1
            f.seq = self._seq
            self._pending.append(f)
            self._pending_records += f.records
            if self._thread is None:
                t = threading.Thread(target=self._loop, daemon=True,
                                     name="akka-tpu-ingest-aggregator")
                self._thread = t
                t.start()
        self._work.set()
        return f.future

    # --------------------------------------------------------- dispatcher
    def _full(self) -> bool:
        with self._lock:
            return self._pending_records >= self.max_window

    def _idle(self) -> bool:
        """Window fast-close predicate (ISSUE 16 satellite): exactly ONE
        frame is pending, no window of ours is in flight, and the
        backend's ask pipeline is idle — a lone frame under light load
        closes its window immediately instead of eating the full
        adaptive deadline. Two or more pending frames ARE concurrency
        (and downstream idleness flickers true between waves), so the
        adaptive wait behaves exactly as before under load."""
        with self._lock:
            if self._inflight or len(self._pending) > 1:
                return False
        batcher = getattr(getattr(self.server, "backend", None),
                          "batcher", None)
        return batcher is None or batcher.idle()

    def _loop(self) -> None:
        # exponential idle backoff, same policy as the ask-batch loops
        # (ISSUE 18 satellite): 1 ms after work, doubling to 250 ms idle;
        # submit's Event.set() re-arms tight polling instantly
        idle_wait = IDLE_WAIT_MIN
        while True:
            fired = self._work.wait(idle_wait)
            self._work.clear()
            if fired:
                idle_wait = IDLE_WAIT_MIN
            else:
                idle_wait = min(idle_wait * 2.0, IDLE_WAIT_MAX)
                with self._lock:
                    self._idle_wakeups += 1
            while True:
                with self._lock:
                    if not self._pending:
                        break
                    closing = self._closed
                if not closing:
                    # the AskBatcher's adaptive close: re-check fullness
                    # on every submit wakeup until the deadline, closing
                    # immediately when the whole pipeline is idle
                    wait_adaptive_close(self._work, self.window_s,
                                        self._full, idle=self._idle)
                with self._lock:
                    window: List[_PendingFrame] = []
                    taken = 0
                    # whole frames only: a frame's records never split
                    # across windows (its reply is one encode slice)
                    while self._pending and (
                            not window
                            or taken + self._pending[0].records
                            <= self.max_window):
                        f = self._pending.pop(0)
                        window.append(f)
                        taken += f.records
                    self._pending_records -= taken
                if window:
                    self._run_window(window, taken)
            with self._lock:
                if self._closed:
                    return

    def _run_window(self, window: List[_PendingFrame],
                    n_records: int) -> None:
        t_close = time.perf_counter()
        if self._continuous:
            self._run_window_async(window, n_records, t_close)
            return
        try:
            replies = self.server._serve_frames([f.body for f in window])
        except BaseException as e:  # noqa: BLE001 — fail the window's
            for f in window:        # futures, never kill the dispatcher
                if not f.future.done():
                    f.future.set_exception(e)
            return
        self._account(window, n_records, t_close)
        for f, body in zip(window, replies):
            f.future.set_result(body)

    def _run_window_async(self, window: List[_PendingFrame],
                          n_records: int, t_close: float) -> None:
        """Continuous path (ISSUE 16 tentpole): stage the window's wave
        via `submit_frames` (on THIS dispatcher thread — submit order is
        the staging order, so per-connection FIFO stays structural) and
        return to window formation immediately; frame futures complete
        at the wave's resolve boundary. The depth semaphore blocks
        window N+depth's staging until an older wave resolves, bounding
        promise-pool pressure."""
        self._depth_sem.acquire()
        with self._lock:
            self._inflight += 1

        def _settle_err(e: BaseException) -> None:
            for f in window:
                if not f.future.done():
                    f.future.set_exception(e)

        try:
            sfut = self.server.submit_frames([f.body for f in window])
        except BaseException as e:  # noqa: BLE001 — never strand futures
            with self._lock:
                self._inflight -= 1
            self._depth_sem.release()
            _settle_err(e)
            return

        def _finish(sf) -> None:
            try:
                try:
                    replies = sf.result()
                except BaseException as e:  # noqa: BLE001
                    _settle_err(e)
                    return
                self._account(window, n_records, t_close)
                for f, body in zip(window, replies):
                    f.future.set_result(body)
            finally:
                with self._lock:
                    self._inflight -= 1
                self._depth_sem.release()
                self._work.set()  # idle may have transitioned: fast-close

        sfut.add_done_callback(_finish)

    def _account(self, window: List[_PendingFrame], n_records: int,
                 t_close: float) -> None:
        with self._lock:
            self._windows += 1
            self._frames += len(window)
            self._records += n_records
            self._max_seen = max(self._max_seen, n_records)
            if len(window) > 1:
                self._multi += 1
        if self._h_size is not None:
            step = self._registry.step
            self._h_size.observe(float(n_records), step=step)
            self._h_wait.observe_many(
                [(t_close - f.t_submit) * 1e6 for f in window], step=step)

    # ------------------------------------------------------------ shutdown
    def close(self, timeout: float = 10.0) -> None:
        """Shutdown flush: pending frames are SERVED (the dispatcher
        drains without the adaptive wait) before the thread exits —
        close() is a drain, not a drop. Idempotent; submit() after
        close() raises."""
        with self._lock:
            self._closed = True
            t = self._thread
        self._work.set()
        if t is not None:
            t.join(timeout)
        # continuous windows still in flight resolve on the scheduler
        # thread — wait for them so close() stays a drain, not a drop
        deadline = time.perf_counter() + timeout
        while True:
            with self._lock:
                if not self._inflight:
                    break
            if time.perf_counter() >= deadline:
                break
            time.sleep(1e-3)
        # dispatcher never ran (or died): nothing may stay unresolved
        with self._lock:
            leftover, self._pending = self._pending, []
            self._pending_records = 0
        for f in leftover:
            if not f.future.done():
                f.future.set_exception(
                    RuntimeError("IngestAggregator is closed"))

    # --------------------------------------------------------------- stats
    def stats(self) -> Dict[str, Any]:
        """The `ingest_window` summary: how much cross-connection
        coalescing the traffic actually got (mean_window_size > 1 means
        frames shared decode/admission/ask rounds)."""
        with self._lock:
            w, fr, rec = self._windows, self._frames, self._records
            return {
                "windows": float(w),
                "frames": float(fr),
                "records": float(rec),
                "mean_window_size": (rec / w) if w else 0.0,
                "mean_frames_per_window": (fr / w) if w else 0.0,
                "max_window_size": float(self._max_seen),
                "multi_frame_windows": float(self._multi),
                "pending": float(len(self._pending)),
                "idle_wakeups": float(self._idle_wakeups),
            }
