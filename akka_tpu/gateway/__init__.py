"""Serving gateway: external traffic in, sharded entities on-device,
SLOs out (ISSUE 8 tentpole; docs/SERVING_GATEWAY.md).

Four planes, each its own module:
- ingress:    framed-TCP front door + in-proc transport + RegionBackend
- evloop:     selector event-loop transport (C1M front door: all sockets
              on one thread, optional SO_REUSEPORT accept shards)
- aggregator: cross-connection ingest windows (shared decode/admission/
              ask waves across sockets)
- admission:  per-tenant token buckets + runtime-pressure load shedding
- dedup:      journaled reply-cache dedup (exactly-once retry effects)
- slo:        p50/p99 latency vs targets, error budget, per-tenant counters
"""

from .admission import (AdmissionController, AskPoolExhausted, Reject,
                        TokenBucket, VectorTenantTable,
                        handle_pressure_signals, region_pressure_signals)
from .aggregator import IngestAggregator
from .dedup import ReplyCacheTable
from .evloop import EvLoopIngress
from .ingress import (DEFAULT_MAX_FRAME, GatewayClient, GatewayServer,
                      RegionBackend, counter_behavior, encode_body,
                      encode_frame, FrameReader)
from .slo import SloTracker
from ..serialization import frames

__all__ = ["AdmissionController", "AskPoolExhausted", "Reject",
           "TokenBucket", "VectorTenantTable", "ReplyCacheTable",
           "EvLoopIngress",
           "handle_pressure_signals",
           "region_pressure_signals", "GatewayClient", "GatewayServer",
           "IngestAggregator", "RegionBackend", "counter_behavior",
           "encode_body", "encode_frame", "FrameReader", "SloTracker",
           "frames", "DEFAULT_MAX_FRAME"]
