"""Selector event-loop front door: C1M-shaped gateway ingress (ISSUE 18).

The stream transport (`GatewayServer.start`, `transport="stream"`)
materializes a full stream graph per accepted connection — decoder stage,
MapAsync stage, encoder stage, each an actor with its own mailbox — so
10k sockets mean tens of thousands of Python objects exchanging per-frame
messages. That is the per-connection ceiling ROADMAP item 5 names: the
device path already serves WINDOWS (one `IngestAggregator`, one columnar
serve per window), but reaching the aggregator costs a thread-herd of
stream actors per socket.

This module is the mechanical alternative, shaped like Artery's
event-loop transport (PAPER.md substrate stance): ONE thread (optionally
N `SO_REUSEPORT` accept shards, default 1) owns accept/read/write for ALL
gateway sockets through a `selectors` loop. Per connection the state is a
`_EvConn` struct — a `FrameReader` for reassembly, a deque of pending
bodies, a deque of in-order reply futures, an output buffer — not an
actor in sight. Complete frames go straight into the ONE shared
`IngestAggregator` (`submit(body, conn_id)`, exactly the tag the stream
path uses), so more sockets make ingest windows BIGGER, never threads
more numerous.

Backpressure contracts preserved from the stream twin, per connection:

* `pipeline_depth` in-flight bound — at most `depth` frames of one socket
  submitted-and-unreplied at the aggregator; further parsed frames queue
  in `pending` and the socket's READ interest drops while the bound (or
  the write high-water mark) holds, so the kernel window closes back to
  the producer.
* FIFO replies — futures are drained strictly in submit order even when
  continuous windows resolve out of order (the head future gates the
  queue).
* a slow consumer stalls only its own connection — reply bytes queue in
  that connection's `outbuf` with write-interest toggling; past
  `HIGH_WATER` the connection stops reading (and therefore submitting)
  until the consumer drains below `LOW_WATER`.

The loop thread never blocks on the device: window serves run on the
aggregator's dispatcher exactly as for the stream transport, and resolved
futures re-enter the loop through a self-pipe wakeup.
"""

from __future__ import annotations

import collections
import selectors
import socket
import struct
import threading
import time
from typing import Any, Deque, Dict, List, Optional, Tuple

from ..serialization import frames

__all__ = ["EvLoopIngress", "HIGH_WATER", "LOW_WATER"]

# per-connection userspace reply buffer watermarks: above HIGH the
# connection stops reading (backpressure reaches the producer through the
# kernel window), below LOW it resumes. Userspace buffering is bounded by
# HIGH + one reply burst; the kernel sndbuf adds its own bounded slack.
HIGH_WATER = 1 << 18
LOW_WATER = 1 << 16

_RECV_CHUNK = 1 << 16


class _EvConn:
    """One accepted socket's loop-thread-only state. No locks: every
    field is touched exclusively on the owning shard's loop thread
    (future callbacks cross threads through the shard's completion
    queue, never through this struct)."""

    __slots__ = ("sock", "fd", "conn_id", "reader", "pending", "inflight",
                 "replies", "outbuf", "out_len", "mask", "read_done",
                 "closed", "last_rx")

    def __init__(self, sock: socket.socket, conn_id: int, max_frame: int):
        from .ingress import FrameReader
        self.sock = sock
        self.fd = sock.fileno()
        self.conn_id = conn_id
        self.reader = FrameReader(max_frame=max_frame)
        self.pending: Deque[bytes] = collections.deque()   # parsed, unsubmitted
        self.inflight = 0                # submitted frames awaiting replies
        self.replies: Deque[Any] = collections.deque()     # futures, FIFO
        self.outbuf: Deque[memoryview] = collections.deque()
        self.out_len = 0
        self.mask = 0                    # currently-registered selector mask
        self.read_done = False           # peer half-closed
        self.closed = False
        self.last_rx = time.monotonic()  # idle-reap clock (ISSUE 20)


class _AcceptShard(threading.Thread):
    """One selector loop: a listening socket (its accept shard) plus
    every connection it accepted. With `n_shards > 1` each shard binds
    the same port under SO_REUSEPORT and the kernel spreads accepts."""

    def __init__(self, ingress: "EvLoopIngress", lsock: socket.socket,
                 shard_id: int):
        super().__init__(daemon=True,
                         name=f"akka-tpu-gw-evloop-{shard_id}")
        self.ingress = ingress
        self.lsock = lsock
        self.shard_id = shard_id
        self.sel = selectors.DefaultSelector()
        self.conns: Dict[int, _EvConn] = {}
        # cross-thread completion queue: future callbacks append conns
        # here and poke the self-pipe; only the loop thread pops
        self._completions: Deque[_EvConn] = collections.deque()
        self._rd_wake, self._wr_wake = socket.socketpair()
        self._rd_wake.setblocking(False)
        self._wr_wake.setblocking(False)
        self._halt = False
        self._last_sweep = time.monotonic()

    # ---------------------------------------------------- cross-thread API
    def notify(self, conn: _EvConn) -> None:
        """Called from any thread when one of `conn`'s reply futures
        resolves: enqueue for the loop thread and wake the selector."""
        self._completions.append(conn)
        try:
            self._wr_wake.send(b"\x01")
        except (BlockingIOError, OSError):
            pass  # pipe already signaled (or shard shutting down)

    def stop(self) -> None:
        self._halt = True
        try:
            self._wr_wake.send(b"\x01")
        except (BlockingIOError, OSError):
            pass

    # ------------------------------------------------------------ the loop
    def run(self) -> None:
        ing = self.ingress
        self.sel.register(self.lsock, selectors.EVENT_READ, "accept")
        self.sel.register(self._rd_wake, selectors.EVENT_READ, "wake")
        try:
            while True:
                events = self.sel.select(timeout=1.0)
                ing._wakeups += 1
                for key, mask in events:
                    what = key.data
                    if what == "accept":
                        self._accept_ready()
                    elif what == "wake":
                        try:
                            while self._rd_wake.recv(4096):
                                pass
                        except (BlockingIOError, OSError):
                            pass
                    else:
                        conn = what
                        if mask & selectors.EVENT_WRITE:
                            self._flush(conn)
                        if mask & selectors.EVENT_READ and not conn.closed:
                            self._read_ready(conn)
                self._drain_completions()
                self._reap_idle()
                if self._halt:
                    return
        finally:
            for conn in list(self.conns.values()):
                self._close(conn)
            try:
                self.sel.unregister(self.lsock)
            except (KeyError, ValueError):
                pass
            self.lsock.close()
            self._rd_wake.close()
            self._wr_wake.close()
            self.sel.close()

    # ---------------------------------------------------------- idle reaping
    def _reap_idle(self) -> None:
        """Close connections with no frame for `idle_timeout_s` (ISSUE
        20 satellite; 0 = off, the default). Only fully-quiescent
        connections reap — anything with parsed-but-unsubmitted frames,
        in-flight windows or unflushed reply bytes is WORKING, not idle.
        Swept at most once a second off the selector's 1s tick, so the
        cost is one timestamp compare per connection per second."""
        ing = self.ingress
        timeout = ing.idle_timeout_s
        if timeout <= 0:
            return
        now = time.monotonic()
        if now - self._last_sweep < min(1.0, timeout / 2):
            return
        self._last_sweep = now
        for conn in [c for c in self.conns.values()
                     if not c.closed and not c.pending and not c.replies
                     and not c.outbuf and c.inflight == 0
                     and now - c.last_rx > timeout]:
            self._close(conn)
            ing._idle_reaped += 1

    # -------------------------------------------------------------- accept
    def _accept_ready(self) -> None:
        ing = self.ingress
        while True:
            try:
                sock, _addr = self.lsock.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            conn = _EvConn(sock, ing._next_conn_id(), ing.max_frame)
            self.conns[conn.fd] = conn
            ing._accepted += 1
            n = sum(len(s.conns) for s in ing._shards)
            if n > ing._max_conns_seen:
                ing._max_conns_seen = n
            self._set_mask(conn, selectors.EVENT_READ)

    # ---------------------------------------------------------------- read
    def _read_ready(self, conn: _EvConn) -> None:
        ing = self.ingress
        try:
            data = conn.sock.recv(_RECV_CHUNK)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._close(conn)
            return
        if not data:
            conn.read_done = True
            self._maybe_finish(conn)
            return
        conn.last_rx = time.monotonic()
        ing._bytes_in += len(data)
        try:
            for body in conn.reader.feed_raw(data):
                conn.pending.append(body)
        except ValueError:
            # oversized frame: protocol violation, same fate as the
            # stream decoder's FramingError — drop the connection
            self._close(conn)
            return
        self._pump_submits(conn)
        self._update_interest(conn)

    def _pump_submits(self, conn: _EvConn) -> None:
        """Move parsed bodies into the shared aggregator while the
        per-connection in-flight bound allows."""
        ing = self.ingress
        while conn.pending and conn.inflight < ing.pipeline_depth:
            body = conn.pending.popleft()
            conn.inflight += 1
            ing._frames_in += 1
            fut = ing.aggregator.submit(body, conn.conn_id)
            conn.replies.append(fut)
            fut.add_done_callback(
                lambda _f, c=conn, s=self: s.notify(c))

    # --------------------------------------------------------- completions
    def _drain_completions(self) -> None:
        while self._completions:
            conn = self._completions.popleft()
            if conn.closed:
                continue
            self._pump_replies(conn)

    def _pump_replies(self, conn: _EvConn) -> None:
        """Queue resolved replies in submit order (the head future gates
        the drain: out-of-order window resolution never reorders one
        connection's replies), then top up submissions and flush."""
        ing = self.ingress
        wrote = False
        while conn.replies and conn.replies[0].done():
            fut = conn.replies.popleft()
            conn.inflight -= 1
            try:
                body = fut.result()
            except BaseException:  # noqa: BLE001 — window serve failed:
                self._close(conn)  # the stream twin fails the connection
                return
            buf = frames.frame(body)
            conn.outbuf.append(memoryview(buf))
            conn.out_len += len(buf)
            ing._replies_out += 1
            wrote = True
        self._pump_submits(conn)
        if wrote:
            self._flush(conn)
        else:
            self._update_interest(conn)

    # --------------------------------------------------------------- write
    def _flush(self, conn: _EvConn) -> None:
        ing = self.ingress
        try:
            while conn.outbuf:
                head = conn.outbuf[0]
                n = conn.sock.send(head)
                ing._bytes_out += n
                conn.out_len -= n
                if n == len(head):
                    conn.outbuf.popleft()
                else:
                    conn.outbuf[0] = head[n:]
                    ing._write_blocks += 1
                    break
        except (BlockingIOError, InterruptedError):
            ing._write_blocks += 1
        except OSError:
            self._close(conn)
            return
        self._maybe_finish(conn)

    # ----------------------------------------------------- interest + close
    def _update_interest(self, conn: _EvConn) -> None:
        if conn.closed:
            return
        ing = self.ingress
        mask = 0
        if not conn.read_done:
            # stop reading while the in-flight bound or the reply buffer
            # high-water mark holds — this is the backpressure edge
            paused = (conn.inflight >= ing.pipeline_depth
                      or conn.out_len >= HIGH_WATER
                      or (conn.out_len > LOW_WATER
                          and conn.mask & selectors.EVENT_READ == 0))
            if paused:
                if conn.mask & selectors.EVENT_READ:
                    ing._read_pauses += 1
            else:
                mask |= selectors.EVENT_READ
        if conn.outbuf:
            mask |= selectors.EVENT_WRITE
        self._set_mask(conn, mask)

    def _set_mask(self, conn: _EvConn, mask: int) -> None:
        if mask == conn.mask:
            return
        try:
            if mask == 0:
                self.sel.unregister(conn.sock)
            elif conn.mask == 0:
                self.sel.register(conn.sock, mask, conn)
            else:
                self.sel.modify(conn.sock, mask, conn)
            conn.mask = mask
        except (KeyError, ValueError, OSError):
            self._close(conn)

    def _maybe_finish(self, conn: _EvConn) -> None:
        if conn.read_done and not conn.outbuf and not conn.replies \
                and not conn.pending:
            self._close(conn)
        else:
            self._update_interest(conn)

    def _close(self, conn: _EvConn) -> None:
        if conn.closed:
            return
        conn.closed = True
        if conn.mask:
            try:
                self.sel.unregister(conn.sock)
            except (KeyError, ValueError, OSError):
                pass
            conn.mask = 0
        self.conns.pop(conn.fd, None)
        try:
            conn.sock.close()
        except OSError:
            pass
        self.ingress._closed_conns += 1


class EvLoopIngress:
    """The evloop transport behind `GatewayServer(transport="evloop")`:
    owns the listening socket(s) and every accepted connection on
    `n_shards` selector loops (default 1). All frame handling funnels
    into `server.aggregator` — the SAME windows, admission charges and
    serve path as the stream transport, which stays available as the
    bit-identical A/B twin."""

    def __init__(self, server, host: str = "127.0.0.1", port: int = 0,
                 n_shards: int = 1, backlog: int = 4096,
                 registry=None, idle_timeout_s: float = 0.0):
        if server.aggregator is None:
            raise ValueError("evloop transport requires the shared "
                             "IngestAggregator (GatewayServer creates it "
                             "for transport='evloop')")
        self.server = server
        self.aggregator = server.aggregator
        self.max_frame = server.max_frame
        self.pipeline_depth = max(1, int(server.pipeline_depth))
        self.host = host
        self.port = int(port)
        self.n_shards = max(1, int(n_shards))
        self.backlog = int(backlog)
        # idle-connection reaping (ISSUE 20 satellite): 0 disables
        self.idle_timeout_s = float(idle_timeout_s)
        self._idle_reaped = 0
        self._shards: List[_AcceptShard] = []
        self._conn_lock = threading.Lock()
        self._started = False
        # counters (loop-thread writes; torn reads are fine for stats)
        self._accepted = 0
        self._closed_conns = 0
        self._frames_in = 0
        self._replies_out = 0
        self._bytes_in = 0
        self._bytes_out = 0
        self._read_pauses = 0
        self._write_blocks = 0
        self._wakeups = 0
        self._max_conns_seen = 0
        self._t_start = time.monotonic()
        if registry is not None:
            registry.register_collector("gateway_evloop", self.stats)

    def _next_conn_id(self) -> int:
        # shares the server's conn-id space so aggregator window tags
        # stay unique across transports (A/B runs in one process)
        with self._conn_lock:
            return next(self.server._conn_ids)

    # ----------------------------------------------------------- lifecycle
    def _bind_one(self, port: int, reuseport: bool) -> socket.socket:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        if reuseport:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        s.bind((self.host, port))
        s.listen(self.backlog)
        s.setblocking(False)
        return s

    def start(self) -> Tuple[str, int]:
        if self._started:
            return self.host, self.port
        reuseport = self.n_shards > 1
        first = self._bind_one(self.port, reuseport)
        self.port = first.getsockname()[1]
        socks = [first] + [self._bind_one(self.port, True)
                           for _ in range(self.n_shards - 1)]
        self._shards = [_AcceptShard(self, s, i)
                        for i, s in enumerate(socks)]
        for sh in self._shards:
            sh.start()
        self._started = True
        return self.host, self.port

    def stop(self, timeout: float = 5.0) -> None:
        for sh in self._shards:
            sh.stop()
        for sh in self._shards:
            sh.join(timeout)
        self._shards = []
        self._started = False

    # --------------------------------------------------------------- stats
    def stats(self) -> Dict[str, float]:
        elapsed = max(1e-9, time.monotonic() - self._t_start)
        conns = sum(len(sh.conns) for sh in self._shards)
        return {"connections": float(conns),
                "max_connections": float(self._max_conns_seen),
                "accepted": float(self._accepted),
                "closed": float(self._closed_conns),
                "frames_in": float(self._frames_in),
                "replies_out": float(self._replies_out),
                "bytes_in": float(self._bytes_in),
                "bytes_out": float(self._bytes_out),
                "read_pauses": float(self._read_pauses),
                "write_blocks": float(self._write_blocks),
                "wakeups": float(self._wakeups),
                "wakeups_per_s": self._wakeups / elapsed,
                "idle_reaped": float(self._idle_reaped),
                "idle_timeout_s": float(self.idle_timeout_s),
                "accept_shards": float(self.n_shards)}
