"""Service discovery: resolve service names to addresses.

Reference parity: akka-discovery/src/main/scala/akka/discovery/
ServiceDiscovery.scala (Lookup/Resolved/ResolvedTarget), impls
config/ConfigServiceDiscovery.scala (:51), aggregate/AggregateServiceDiscovery
(:49 — try methods in order until one returns targets), and a DNS method
(dns/DnsServiceDiscovery.scala:69) via the system resolver; the in-proc
registry stands in for DNS in zero-egress multi-'node' tests.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..actor.system import ActorSystem, ExtensionId


@dataclass(frozen=True)
class Lookup:
    """(reference: discovery/Lookup.scala) service name + optional port/protocol"""
    service_name: str
    port_name: Optional[str] = None
    protocol: Optional[str] = None


@dataclass(frozen=True)
class ResolvedTarget:
    host: str
    port: Optional[int] = None


@dataclass(frozen=True)
class Resolved:
    service_name: str
    addresses: Tuple[ResolvedTarget, ...] = ()


class ServiceDiscovery:
    def lookup(self, lookup: Lookup, resolve_timeout: float = 3.0) -> Resolved:
        raise NotImplementedError


class ConfigServiceDiscovery(ServiceDiscovery):
    """Services from config:
    akka.discovery.config.services.<name>.endpoints = ["host:port", ...]
    (reference: config/ConfigServiceDiscovery.scala:51)"""

    def __init__(self, system: ActorSystem):
        self._services: Dict[str, List[ResolvedTarget]] = {}
        services = system.settings.config.get(
            "akka.discovery.config.services", {}) or {}
        for name, spec in services.items():
            endpoints = spec.get("endpoints", []) if isinstance(spec, dict) else []
            targets = []
            for ep in endpoints:
                host, _, port = str(ep).rpartition(":")
                if host:
                    targets.append(ResolvedTarget(host, int(port)))
                else:
                    targets.append(ResolvedTarget(str(ep)))
            self._services[name] = targets

    def lookup(self, lookup: Lookup, resolve_timeout: float = 3.0) -> Resolved:
        return Resolved(lookup.service_name,
                        tuple(self._services.get(lookup.service_name, ())))


class InProcServiceDiscovery(ServiceDiscovery):
    """Process-global registry for multi-'node' tests (DNS stand-in)."""

    _registry: Dict[str, List[ResolvedTarget]] = {}
    _lock = threading.Lock()

    def __init__(self, system: Optional[ActorSystem] = None):
        pass

    @classmethod
    def register(cls, service_name: str, host: str, port: Optional[int] = None) -> None:
        with cls._lock:
            cls._registry.setdefault(service_name, []).append(
                ResolvedTarget(host, port))

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls._registry.clear()

    def lookup(self, lookup: Lookup, resolve_timeout: float = 3.0) -> Resolved:
        with InProcServiceDiscovery._lock:
            return Resolved(lookup.service_name, tuple(
                InProcServiceDiscovery._registry.get(lookup.service_name, ())))


class DnsServiceDiscovery(ServiceDiscovery):
    """Resolve service names through DNS (reference:
    discovery/dns/DnsServiceDiscovery.scala:69 — the reference speaks
    SRV + A records through the async resolver; here A/AAAA via the
    system resolver, with the Lookup's port_name carried onto every
    target when it parses as a port number, matching how the A-record
    mode of the reference leaves ports to configuration)."""

    def __init__(self, system: Optional[ActorSystem] = None):
        pass

    def lookup(self, lookup: Lookup, resolve_timeout: float = 3.0) -> Resolved:
        import socket

        port: Optional[int] = None
        if lookup.port_name and lookup.port_name.isdigit():
            port = int(lookup.port_name)
        # getaddrinfo has no timeout of its own (OS resolver retries can
        # block 5-30s) — honor the advertised resolve_timeout by resolving
        # on a PER-CALL daemon thread and abandoning the wait. A fixed pool
        # would let a few black-holed resolutions starve every later lookup
        # (a running getaddrinfo cannot be cancelled); an abandoned thread
        # costs one stack until the OS resolver gives up, bounded by its
        # own retry window.
        result: Dict[str, object] = {}
        done = threading.Event()

        def work():
            try:
                result["v"] = socket.getaddrinfo(
                    lookup.service_name, port, type=socket.SOCK_STREAM)
            except OSError:
                pass
            done.set()

        threading.Thread(target=work, daemon=True,
                         name="akka-tpu-dns").start()
        if not done.wait(resolve_timeout) or "v" not in result:
            return Resolved(lookup.service_name)
        infos = result["v"]
        seen = []
        for _family, _t, _p, _canon, sockaddr in infos:
            target = ResolvedTarget(sockaddr[0], port)
            if target not in seen:
                seen.append(target)
        return Resolved(lookup.service_name, tuple(seen))


class AggregateServiceDiscovery(ServiceDiscovery):
    """Try each method in order; first non-empty wins
    (reference: aggregate/AggregateServiceDiscovery.scala:49)."""

    def __init__(self, methods: List[ServiceDiscovery]):
        self.methods = methods

    def lookup(self, lookup: Lookup, resolve_timeout: float = 3.0) -> Resolved:
        last = Resolved(lookup.service_name)
        for m in self.methods:
            last = m.lookup(lookup, resolve_timeout)
            if last.addresses:
                return last
        return last


_METHODS: Dict[str, Callable[[ActorSystem], ServiceDiscovery]] = {
    "config": ConfigServiceDiscovery,
    "in-proc": InProcServiceDiscovery,
    "dns": DnsServiceDiscovery,
}


def register_discovery_method(name: str,
                              factory: Callable[[ActorSystem], ServiceDiscovery]) -> None:
    _METHODS[name] = factory


class Discovery(ExtensionId):
    """Extension: `Discovery.get(system).discovery` is the method selected by
    `akka.discovery.method`; `load_method(name)` for explicit selection."""

    def create_extension(self, system: ActorSystem) -> "_DiscoveryExt":
        return _DiscoveryExt(system)

    @staticmethod
    def get(system: ActorSystem) -> "_DiscoveryExt":
        return system.register_extension(Discovery())


class _DiscoveryExt:
    def __init__(self, system: ActorSystem):
        self.system = system
        self._cache: Dict[str, ServiceDiscovery] = {}
        method = system.settings.config.get_string("akka.discovery.method",
                                                   "config")
        if "," in method:
            self.discovery: ServiceDiscovery = AggregateServiceDiscovery(
                [self.load_method(m.strip()) for m in method.split(",")])
        else:
            self.discovery = self.load_method(method)

    def load_method(self, name: str) -> ServiceDiscovery:
        if name not in self._cache:
            self._cache[name] = _METHODS[name](self.system)
        return self._cache[name]
