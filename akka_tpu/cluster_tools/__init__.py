"""Cluster services: singleton, distributed pub-sub, lease, discovery, metrics.

Reference parity: akka-cluster-tools (singleton/ClusterSingletonManager.scala,
pubsub/DistributedPubSubMediator.scala), akka-coordination
(lease/scaladsl/LeaseProvider.scala), akka-discovery
(discovery/ServiceDiscovery.scala), akka-cluster-metrics (EWMA.scala,
ClusterMetricsRouting.scala). SURVEY.md §2.6.
"""

from .singleton import (ClusterSingletonManager, ClusterSingletonProxy,
                        ClusterSingletonSettings)
from .pubsub import (DistributedPubSub, DistributedPubSubMediator, Publish,
                     Put, Remove, Send, SendToAll, Subscribe, SubscribeAck,
                     Unsubscribe, UnsubscribeAck, GetTopics, CurrentTopics)
from .lease import Lease, LeaseProvider, LeaseSettings, InProcLease, TimeoutSettings
from .discovery import (AggregateServiceDiscovery, ConfigServiceDiscovery,
                        DnsServiceDiscovery,
                        Discovery, Lookup, Resolved, ResolvedTarget,
                        ServiceDiscovery)
from .metrics import (EWMA, AdaptiveLoadBalancingRoutingLogic,
                      ClusterMetricsExtension, NodeMetrics,
                      CapacityMetricsSelector, CpuMetricsSelector,
                      MemoryMetricsSelector, MixMetricsSelector)

__all__ = [
    "ClusterSingletonManager", "ClusterSingletonProxy", "ClusterSingletonSettings",
    "DistributedPubSub", "DistributedPubSubMediator", "Publish", "Put", "Remove",
    "Send", "SendToAll", "Subscribe", "SubscribeAck", "Unsubscribe",
    "UnsubscribeAck", "GetTopics", "CurrentTopics",
    "Lease", "LeaseProvider", "LeaseSettings", "InProcLease", "TimeoutSettings",
    "AggregateServiceDiscovery", "ConfigServiceDiscovery", "DnsServiceDiscovery",
    "Discovery", "Lookup",
    "Resolved", "ResolvedTarget", "ServiceDiscovery",
    "EWMA", "AdaptiveLoadBalancingRoutingLogic", "ClusterMetricsExtension",
    "NodeMetrics", "CapacityMetricsSelector", "CpuMetricsSelector",
    "MemoryMetricsSelector", "MixMetricsSelector",
    "ClusterClient", "ClusterClientReceptionist", "ClusterClientSettings",
]
from .client import (ClusterClient, ClusterClientReceptionist,  # noqa: F401
                     ClusterClientSettings)
