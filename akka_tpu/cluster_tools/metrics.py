"""Cluster metrics: node load sampling, EWMA smoothing, adaptive routing.

Reference parity: akka-cluster-metrics/src/main/scala/akka/cluster/metrics/
EWMA.scala (exponentially weighted moving average with half-life alpha),
MetricsCollector.scala (:45-78 — sigar JNI with JMX fallback; here: /proc +
os.getloadavg, with an optional TPU/jax device-memory probe as the
accelerator-native analogue), ClusterMetricsCollector gossip, and
ClusterMetricsRouting.scala (CapacityMetricsSelector → weighted routee
selection).
"""

from __future__ import annotations

import math
import os
import random
import threading
import time
from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..actor.actor import Actor
from ..actor.props import Props
from ..actor.system import ActorSystem, ExtensionId
from ..cluster.cluster import Cluster
from ..cluster.member import MemberStatus
from ..routing.router import Routee, RoutingLogic


@dataclass(frozen=True)
class EWMA:
    """(reference: metrics/EWMA.scala) value smoothed with decay alpha, where
    alpha is derived from a half-life and the sample interval."""
    value: float
    alpha: float

    def __add__(self, x: float) -> "EWMA":
        return EWMA(self.alpha * x + (1 - self.alpha) * self.value, self.alpha)

    @staticmethod
    def alpha_for(half_life: float, collect_interval: float) -> float:
        # reference: EWMA.alpha — 1 - exp(ln(0.5) / halfLife * interval)
        return 1.0 - math.exp(math.log(0.5) / half_life * collect_interval)


@dataclass(frozen=True)
class Metric:
    name: str
    value: float
    average: Optional[EWMA] = None

    def updated(self, sample: float) -> "Metric":
        avg = (self.average + sample) if self.average else None
        return Metric(self.name, sample if avg is None else avg.value, avg)

    @property
    def smooth(self) -> float:
        return self.average.value if self.average else self.value


# standard metric names (reference: StandardMetrics)
CPU_COMBINED = "cpu-combined"            # 0..1 load fraction
SYSTEM_LOAD_AVERAGE = "system-load-average"
HEAP_MEMORY_USED = "heap-memory-used"    # here: process RSS bytes
HEAP_MEMORY_MAX = "heap-memory-max"      # here: total system memory bytes
DEVICE_MEMORY_USED = "device-memory-used"  # TPU HBM in use (bytes)
DEVICE_MEMORY_MAX = "device-memory-max"


@dataclass(frozen=True)
class NodeMetrics:
    address: str
    timestamp: float
    metrics: Dict[str, Metric] = field(default_factory=dict)

    def metric(self, name: str) -> Optional[Metric]:
        return self.metrics.get(name)

    def merged(self, other: "NodeMetrics") -> "NodeMetrics":
        return other if other.timestamp >= self.timestamp else self

    def updated(self, samples: Dict[str, float], ts: float,
                alpha: float) -> "NodeMetrics":
        out = dict(self.metrics)
        for name, v in samples.items():
            cur = out.get(name)
            if cur is None:
                out[name] = Metric(name, v, EWMA(v, alpha))
            else:
                out[name] = cur.updated(v)
        return NodeMetrics(self.address, ts, out)


class MetricsCollector:
    """Host+device sampler (reference: MetricsCollector.scala:45-78; sigar →
    /proc, JMX heap → RSS, plus jax device memory when available)."""

    def __init__(self, probe_device: bool = False):
        self.probe_device = probe_device
        self._n_cpus = os.cpu_count() or 1

    def sample(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        try:
            load1, _, _ = os.getloadavg()
            out[SYSTEM_LOAD_AVERAGE] = load1
            out[CPU_COMBINED] = min(load1 / self._n_cpus, 1.0)
        except OSError:
            pass
        try:
            with open("/proc/meminfo") as f:
                info = {}
                for line in f:
                    parts = line.split()
                    if len(parts) >= 2:
                        info[parts[0].rstrip(":")] = int(parts[1]) * 1024
            total = info.get("MemTotal")
            avail = info.get("MemAvailable")
            if total is not None and avail is not None:
                out[HEAP_MEMORY_MAX] = float(total)
                out[HEAP_MEMORY_USED] = float(total - avail)
        except OSError:
            pass
        if self.probe_device:
            try:
                import jax
                stats = jax.devices()[0].memory_stats()
                if stats:
                    out[DEVICE_MEMORY_USED] = float(stats.get("bytes_in_use", 0))
                    out[DEVICE_MEMORY_MAX] = float(
                        stats.get("bytes_limit", 0) or 0)
            except Exception:
                pass
        return out


# -- gossip ------------------------------------------------------------------

@dataclass(frozen=True)
class MetricsGossip:
    nodes: Dict[str, NodeMetrics]


@dataclass(frozen=True)
class _SampleTick:
    pass


@dataclass(frozen=True)
class _GossipTick:
    pass


class ClusterMetricsCollector(Actor):
    """Per-node actor: samples local metrics, gossips the merged map
    (reference: ClusterMetricsCollector in ClusterMetricsExtension.scala)."""

    def __init__(self, collect_interval: float = 0.5,
                 gossip_interval: float = 0.5, half_life: float = 6.0,
                 probe_device: bool = False):
        super().__init__()
        self.collector = MetricsCollector(probe_device)
        self.alpha = EWMA.alpha_for(half_life, collect_interval)
        self.collect_interval = collect_interval
        self.gossip_interval = gossip_interval
        self.cluster = Cluster.get(self.context.system)
        self.self_addr = str(self.context.system.provider.default_address)
        self.nodes: Dict[str, NodeMetrics] = {}
        self._tasks = []

    def pre_start(self) -> None:
        s = self.context.system.scheduler
        self._tasks = [
            s.schedule_tell_with_fixed_delay(0.0, self.collect_interval,
                                             self.self_ref, _SampleTick()),
            s.schedule_tell_with_fixed_delay(self.gossip_interval,
                                             self.gossip_interval,
                                             self.self_ref, _GossipTick()),
        ]

    def post_stop(self) -> None:
        for t in self._tasks:
            t.cancel()

    def receive(self, message: Any) -> Any:
        if isinstance(message, _SampleTick):
            now = time.time()
            cur = self.nodes.get(
                self.self_addr, NodeMetrics(self.self_addr, now))
            self.nodes[self.self_addr] = cur.updated(
                self.collector.sample(), now, self.alpha)
            ext = ClusterMetricsExtension.get(self.context.system)
            ext._publish(dict(self.nodes))
        elif isinstance(message, _GossipTick):
            peers = [str(m.address) for m in self.cluster.state.members
                     if m.status is MemberStatus.UP
                     and str(m.address) != self.self_addr]
            if peers:
                target = random.choice(peers)
                rel = self.context.self_ref.path.to_string_without_address()
                ref = self.context.system.provider.resolve_actor_ref(
                    f"{target}{rel}")
                ref.tell(MetricsGossip(dict(self.nodes)), self.self_ref)
        elif isinstance(message, MetricsGossip):
            for addr, nm in message.nodes.items():
                cur = self.nodes.get(addr)
                self.nodes[addr] = nm if cur is None else cur.merged(nm)
        else:
            return NotImplemented


class ClusterMetricsExtension(ExtensionId):
    """Extension entry: starts the collector, exposes the latest metrics map
    and change subscriptions."""

    def create_extension(self, system: ActorSystem) -> "_MetricsExt":
        return _MetricsExt(system)

    @staticmethod
    def get(system: ActorSystem) -> "_MetricsExt":
        return system.register_extension(ClusterMetricsExtension())


class _MetricsExt:
    def __init__(self, system: ActorSystem):
        self.system = system
        self._lock = threading.Lock()
        self._latest: Dict[str, NodeMetrics] = {}
        self._subscribers: List[Any] = []
        cfg = system.settings.config
        self.supervisor = system.system_actor_of(
            Props.create(
                ClusterMetricsCollector,
                collect_interval=cfg.get_duration(
                    "akka.cluster.metrics.collect-interval", 0.5),
                gossip_interval=cfg.get_duration(
                    "akka.cluster.metrics.gossip-interval", 0.5),
                probe_device=cfg.get_bool(
                    "akka.cluster.metrics.probe-device", False)),
            "clusterMetrics")

    def _publish(self, nodes: Dict[str, NodeMetrics]) -> None:
        with self._lock:
            self._latest = nodes
            subs = list(self._subscribers)
        for cb in subs:
            try:
                cb(nodes)
            except Exception:
                pass

    @property
    def node_metrics(self) -> Dict[str, NodeMetrics]:
        with self._lock:
            return dict(self._latest)

    def subscribe(self, callback) -> None:
        with self._lock:
            self._subscribers.append(callback)


# -- adaptive load-balancing routing (reference: ClusterMetricsRouting.scala) -

class CapacityMetricsSelector:
    """capacity(node) in [0,1]: higher = more headroom."""

    def capacity(self, nodes: Dict[str, NodeMetrics]) -> Dict[str, float]:
        raise NotImplementedError

    def weights(self, nodes: Dict[str, NodeMetrics]) -> Dict[str, int]:
        cap = self.capacity(nodes)
        if not cap:
            return {}
        lo = min(cap.values())
        divisor = max(lo, 0.01)
        return {a: max(int(round(c / divisor)), 1) for a, c in cap.items()}


class CpuMetricsSelector(CapacityMetricsSelector):
    def capacity(self, nodes):
        out = {}
        for addr, nm in nodes.items():
            m = nm.metric(CPU_COMBINED)
            if m is not None:
                out[addr] = max(0.0, 1.0 - m.smooth)
        return out


class MemoryMetricsSelector(CapacityMetricsSelector):
    """Host memory headroom; prefers device (HBM) headroom when sampled —
    the TPU-native capacity signal."""

    def capacity(self, nodes):
        out = {}
        for addr, nm in nodes.items():
            used, cap = nm.metric(DEVICE_MEMORY_USED), nm.metric(DEVICE_MEMORY_MAX)
            if not (used and cap and cap.smooth > 0):
                used, cap = nm.metric(HEAP_MEMORY_USED), nm.metric(HEAP_MEMORY_MAX)
            if used and cap and cap.smooth > 0:
                out[addr] = max(0.0, (cap.smooth - used.smooth) / cap.smooth)
        return out


class MixMetricsSelector(CapacityMetricsSelector):
    def __init__(self, selectors: Optional[Sequence[CapacityMetricsSelector]] = None):
        self.selectors = list(selectors) if selectors else [
            CpuMetricsSelector(), MemoryMetricsSelector()]

    def capacity(self, nodes):
        acc: Dict[str, List[float]] = {}
        for sel in self.selectors:
            for addr, c in sel.capacity(nodes).items():
                acc.setdefault(addr, []).append(c)
        return {a: sum(cs) / len(cs) for a, cs in acc.items()}


class AdaptiveLoadBalancingRoutingLogic(RoutingLogic):
    """Weighted-random routee selection by node capacity (reference:
    AdaptiveLoadBalancingRoutingLogic). Routee→node mapping uses the routee
    ref's address; local refs map to the system's own address."""

    def __init__(self, system: ActorSystem,
                 selector: Optional[CapacityMetricsSelector] = None):
        self.system = system
        self.selector = selector or MixMetricsSelector()
        self.self_addr = str(system.provider.default_address)

    def _node_of(self, routee: Routee) -> str:
        ref = getattr(routee, "ref", None)
        if ref is None:
            return self.self_addr
        addr = ref.path.address
        return str(addr) if addr.has_global_scope else self.self_addr

    def select(self, message: Any, routees: Sequence[Routee]) -> Routee:
        if not routees:
            raise ValueError("no routees")
        nodes = ClusterMetricsExtension.get(self.system).node_metrics
        weights = self.selector.weights(nodes)
        if not weights:
            return random.choice(list(routees))
        ws = [max(weights.get(self._node_of(r), 1), 1) for r in routees]
        return random.choices(list(routees), weights=ws, k=1)[0]
