"""Distributed publish-subscribe: gossip-replicated topic/path registry.

Reference parity: akka-cluster-tools/src/main/scala/akka/cluster/pubsub/
DistributedPubSubMediator.scala (:553 mediator actor; Send/SendToAll :202-213;
publish :799; versioned per-node buckets gossiped via Status/Delta).

One mediator actor per node at /system/distributedPubSubMediator. The
registry maps  node-address -> Bucket(version, {key -> ValueHolder}) where a
key is either a registered actor path ("/user/x") or a topic ("topic:<name>").
Gossip: periodic Status(versions) to random peers; peers reply Delta with
newer buckets. Topic subscribers are local refs fanned out by each node's own
mediator on PublishLocal.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, FrozenSet, List, Optional, Set, Tuple

from ..actor.actor import Actor
from ..actor.props import Props
from ..actor.ref import ActorRef
from ..actor.system import ActorSystem, ExtensionId
from ..cluster.cluster import Cluster
from ..cluster.events import MemberEvent, MemberRemoved
from ..cluster.member import MemberStatus


# -- user API messages (reference: DistributedPubSubMediator object) ---------

@dataclass(frozen=True)
class Subscribe:
    topic: str
    ref: ActorRef
    group: Optional[str] = None


@dataclass(frozen=True)
class SubscribeAck:
    subscribe: Subscribe


@dataclass(frozen=True)
class Unsubscribe:
    topic: str
    ref: ActorRef
    group: Optional[str] = None


@dataclass(frozen=True)
class UnsubscribeAck:
    unsubscribe: Unsubscribe


@dataclass(frozen=True)
class Put:
    ref: ActorRef  # must be a local ref; registered under its path


@dataclass(frozen=True)
class Remove:
    path: str


@dataclass(frozen=True)
class Publish:
    topic: str
    message: Any
    send_one_message_to_each_group: bool = False


@dataclass(frozen=True)
class Send:
    """Send to ONE registered actor for `path` (routing: random with local
    affinity, reference :202)."""
    path: str
    message: Any
    local_affinity: bool = True


@dataclass(frozen=True)
class SendToAll:
    path: str
    message: Any
    all_but_self: bool = False


@dataclass(frozen=True)
class GetTopics:
    pass


@dataclass(frozen=True)
class CurrentTopics:
    topics: FrozenSet[str]


@dataclass(frozen=True)
class Count:
    pass


@dataclass(frozen=True)
class GetRegistryState:
    """Introspection: reply with {key: [node addresses]} for live entries."""
    pass


# -- internal gossip protocol ------------------------------------------------

@dataclass(frozen=True)
class _ValueHolder:
    version: int
    path: Optional[str]  # None => tombstone (removed registration)


@dataclass(frozen=True)
class _Bucket:
    owner: str  # node address string
    version: int
    content: Dict[str, _ValueHolder] = field(default_factory=dict)


@dataclass(frozen=True)
class _Status:
    versions: Dict[str, int]
    is_reply: bool = False


@dataclass(frozen=True)
class _Delta:
    buckets: Tuple[_Bucket, ...]


@dataclass(frozen=True)
class _GossipTick:
    pass


@dataclass(frozen=True)
class _PublishLocal:
    topic: str
    message: Any
    groups: bool = False


@dataclass(frozen=True)
class _SendLocal:
    path: str
    message: Any


class DistributedPubSubMediator(Actor):
    def __init__(self, gossip_interval: float = 0.2,
                 removed_time_to_live: float = 30.0):
        super().__init__()
        self.gossip_interval = gossip_interval
        self.removed_ttl = removed_time_to_live
        self.cluster = Cluster.get(self.context.system)
        self.self_addr = str(self.context.system.provider.default_address)
        # node addr -> bucket; ours is authoritative, others gossip-replicated
        self.registry: Dict[str, _Bucket] = {
            self.self_addr: _Bucket(self.self_addr, 0)}
        # topic -> (group or None) -> set of local subscriber refs
        self.subscribers: Dict[str, Dict[Optional[str], Set[ActorRef]]] = {}
        self.local_refs: Dict[str, ActorRef] = {}  # path -> local ref
        self._send_rr = 0
        self._task = None
        self._nodes: Set[str] = set()

    # -- lifecycle -----------------------------------------------------------
    def pre_start(self) -> None:
        self._task = self.context.system.scheduler.schedule_tell_with_fixed_delay(
            self.gossip_interval, self.gossip_interval, self.self_ref,
            _GossipTick())
        self.cluster.subscribe(lambda e: self.self_ref.tell(e), MemberEvent,
                               initial_state=False)

    def post_stop(self) -> None:
        if self._task:
            self._task.cancel()

    # -- helpers -------------------------------------------------------------
    def _my_bucket(self) -> _Bucket:
        return self.registry[self.self_addr]

    def _put_key(self, key: str, path: Optional[str]) -> None:
        b = self._my_bucket()
        v = b.version + 1
        content = dict(b.content)
        content[key] = _ValueHolder(v, path)
        self.registry[self.self_addr] = _Bucket(self.self_addr, v, content)

    def _peers(self) -> List[str]:
        ups = [str(m.address) for m in self.cluster.state.members
               if m.status in (MemberStatus.UP, MemberStatus.WEAKLY_UP)]
        return [a for a in ups if a != self.self_addr]

    def _mediator_at(self, addr: str) -> ActorRef:
        rel = self.context.self_ref.path.to_string_without_address()
        return self.context.system.provider.resolve_actor_ref(f"{addr}{rel}")

    def _live_addrs(self) -> Set[str]:
        from ..cluster.member import MemberStatus
        live = {str(m.address) for m in self.cluster.state.members
                if m.status in (MemberStatus.JOINING, MemberStatus.WEAKLY_UP,
                                MemberStatus.UP, MemberStatus.LEAVING)}
        live.add(self.self_addr)
        return live

    def _nodes_with_key(self, key: str) -> List[str]:
        live = self._live_addrs()
        out = []
        for addr, b in self.registry.items():
            if addr not in live:
                continue
            vh = b.content.get(key)
            if vh is not None and vh.path is not None:
                out.append(addr)
        return out

    # -- receive -------------------------------------------------------------
    def receive(self, message: Any) -> Any:  # noqa: C901
        if isinstance(message, Subscribe):
            groups = self.subscribers.setdefault(message.topic, {})
            groups.setdefault(message.group, set()).add(message.ref)
            self._put_key(f"topic:{message.topic}", "topic")
            message.ref.tell(SubscribeAck(message), self.self_ref)
        elif isinstance(message, Unsubscribe):
            groups = self.subscribers.get(message.topic, {})
            groups.get(message.group, set()).discard(message.ref)
            if not any(groups.values()):
                self.subscribers.pop(message.topic, None)
                self._put_key(f"topic:{message.topic}", None)
            message.ref.tell(UnsubscribeAck(message), self.self_ref)
        elif isinstance(message, Put):
            path = message.ref.path.to_string_without_address()
            self.local_refs[path] = message.ref
            self._put_key(path, path)
        elif isinstance(message, Remove):
            self.local_refs.pop(message.path, None)
            self._put_key(message.path, None)
        elif isinstance(message, Publish):
            key = f"topic:{message.topic}"
            local = _PublishLocal(message.topic, message.message,
                                  message.send_one_message_to_each_group)
            for addr in self._nodes_with_key(key):
                if addr == self.self_addr:
                    self._publish_local(local)
                else:
                    self._mediator_at(addr).tell(local, self.sender)
        elif isinstance(message, _PublishLocal):
            self._publish_local(message)
        elif isinstance(message, Send):
            nodes = self._nodes_with_key(message.path)
            if not nodes:
                self._dead_letter(message.path, message.message)
            elif message.local_affinity and self.self_addr in nodes \
                    and message.path in self.local_refs:
                self.local_refs[message.path].tell(message.message, self.sender)
            else:
                self._send_rr += 1
                addr = nodes[self._send_rr % len(nodes)]
                if addr == self.self_addr:
                    self._send_local(message.path, message.message)
                else:
                    self._mediator_at(addr).tell(
                        _SendLocal(message.path, message.message), self.sender)
        elif isinstance(message, _SendLocal):
            self._send_local(message.path, message.message)
        elif isinstance(message, SendToAll):
            for addr in self._nodes_with_key(message.path):
                if addr == self.self_addr:
                    if not message.all_but_self:
                        self._send_local(message.path, message.message)
                else:
                    self._mediator_at(addr).tell(
                        _SendLocal(message.path, message.message), self.sender)
        elif isinstance(message, GetTopics):
            topics = set()
            for addr, b in self.registry.items():
                for key, vh in b.content.items():
                    if key.startswith("topic:") and vh.path is not None:
                        topics.add(key[len("topic:"):])
            self.sender.tell(CurrentTopics(frozenset(topics)), self.self_ref)
        elif isinstance(message, GetRegistryState):
            out: Dict[str, List[str]] = {}
            for addr, b in self.registry.items():
                for key, vh in b.content.items():
                    if vh.path is not None:
                        out.setdefault(key, []).append(addr)
            self.sender.tell(out, self.self_ref)
        elif isinstance(message, Count):
            n = sum(len(refs) for groups in self.subscribers.values()
                    for refs in groups.values()) + len(self.local_refs)
            self.sender.tell(n, self.self_ref)
        elif isinstance(message, _GossipTick):
            self._gossip()
        elif isinstance(message, _Status):
            self._on_status(message)
        elif isinstance(message, _Delta):
            self._on_delta(message)
        elif isinstance(message, MemberRemoved):
            addr = str(message.member.address)
            if addr != self.self_addr:
                self.registry.pop(addr, None)
        elif isinstance(message, MemberEvent):
            pass
        else:
            return NotImplemented

    # -- local delivery ------------------------------------------------------
    def _publish_local(self, msg: _PublishLocal) -> None:
        groups = self.subscribers.get(msg.topic, {})
        if msg.groups:
            # one message per group (random member), plus all ungrouped
            for group, refs in groups.items():
                if not refs:
                    continue
                if group is None:
                    for r in refs:
                        r.tell(msg.message, self.sender)
                else:
                    random.choice(sorted(refs, key=str)).tell(
                        msg.message, self.sender)
        else:
            for refs in groups.values():
                for r in refs:
                    r.tell(msg.message, self.sender)

    def _send_local(self, path: str, message: Any) -> None:
        ref = self.local_refs.get(path)
        if ref is not None:
            ref.tell(message, self.sender)
        else:
            self._dead_letter(path, message)

    def _dead_letter(self, path: str, message: Any) -> None:
        from ..actor.messages import DeadLetter
        self.context.system.event_stream.publish(
            DeadLetter(message, self.self_ref, self.self_ref))

    # -- gossip --------------------------------------------------------------
    def _gossip(self) -> None:
        peers = self._peers()
        if not peers:
            return
        target = random.choice(peers)
        versions = {addr: b.version for addr, b in self.registry.items()}
        self._mediator_at(target).tell(_Status(versions), self.self_ref)

    def _on_status(self, status: _Status) -> None:
        # send back buckets the peer is missing / stale on
        delta = tuple(b for addr, b in self.registry.items()
                      if b.version > status.versions.get(addr, -1))
        if delta:
            self.sender.tell(_Delta(delta), self.self_ref)
        if not status.is_reply:
            mine = {addr: b.version for addr, b in self.registry.items()}
            stale = any(v > mine.get(addr, -1)
                        for addr, v in status.versions.items())
            if stale:
                self.sender.tell(_Status(mine, is_reply=True), self.self_ref)

    def _on_delta(self, delta: _Delta) -> None:
        live = self._live_addrs()
        for b in delta.buckets:
            if b.owner == self.self_addr:
                continue  # we are authoritative for our own bucket
            if b.owner not in live:
                continue  # no resurrection of removed nodes' buckets
            cur = self.registry.get(b.owner)
            if cur is None or b.version > cur.version:
                self.registry[b.owner] = b


class DistributedPubSub(ExtensionId):
    """Extension: starts the mediator at /system/distributedPubSubMediator
    (reference: DistributedPubSub extension)."""

    _lock = threading.Lock()

    def create_extension(self, system: ActorSystem):
        return _PubSubExt(system)

    @staticmethod
    def get(system: ActorSystem) -> "_PubSubExt":
        return system.register_extension(DistributedPubSub())


class _PubSubExt:
    def __init__(self, system: ActorSystem):
        interval = system.settings.config.get_duration(
            "akka.cluster.pub-sub.gossip-interval", 0.2)
        self.mediator = system.system_actor_of(
            Props.create(DistributedPubSubMediator, gossip_interval=interval),
            "distributedPubSubMediator")
