"""Coordination lease: pluggable distributed lock API.

Reference parity: akka-coordination/src/main/scala/akka/coordination/lease/
scaladsl/LeaseProvider.scala (:35 — config-driven impl lookup) and
Lease.scala (acquire/release/checkLease + granted-callback on lost lease),
LeaseSettings.scala (lease-name, owner-name, heartbeat-timeout/interval).

`InProcLease` is the reference implementation for single-process multi-"node"
tests (the analogue of a Kubernetes-lease backend): a process-global table
keyed by lease name, with TTL expiry so a crashed owner's lease can be taken
over after heartbeat-timeout.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

from ..actor.system import ActorSystem, ExtensionId


@dataclass(frozen=True)
class TimeoutSettings:
    """(reference: lease/TimeoutSettings.scala)"""
    heartbeat_interval: float = 0.5
    heartbeat_timeout: float = 5.0
    operation_timeout: float = 2.0


@dataclass(frozen=True)
class LeaseSettings:
    """(reference: lease/LeaseSettings.scala)"""
    lease_name: str
    owner_name: str
    timeout: TimeoutSettings = TimeoutSettings()


class Lease:
    """Base lease API (reference: lease/scaladsl/Lease.scala). Implementations
    must be safe to call from any thread."""

    def __init__(self, settings: LeaseSettings):
        self.settings = settings

    def acquire(self, lease_lost_callback:
                Optional[Callable[[Optional[Exception]], None]] = None) -> bool:
        raise NotImplementedError

    def release(self) -> bool:
        raise NotImplementedError

    def check_lease(self) -> bool:
        """True only if this owner holds the lease (and it has not expired)."""
        raise NotImplementedError


class _LeaseRecord:
    __slots__ = ("owner", "deadline", "lost_cb")

    def __init__(self, owner: str, deadline: float, lost_cb):
        self.owner = owner
        self.deadline = deadline
        self.lost_cb = lost_cb


class InProcLease(Lease):
    """Process-global lease table with TTL; take-over allowed after the
    current owner's TTL expires (expiry triggers its lost-callback)."""

    _table: Dict[str, _LeaseRecord] = {}
    _lock = threading.Lock()

    def __init__(self, settings: LeaseSettings):
        super().__init__(settings)
        self._heartbeat_task: Optional[threading.Timer] = None

    @classmethod
    def reset_all(cls) -> None:
        with cls._lock:
            cls._table.clear()

    def _ttl(self) -> float:
        return self.settings.timeout.heartbeat_timeout

    def acquire(self, lease_lost_callback=None) -> bool:
        name, owner = self.settings.lease_name, self.settings.owner_name
        now = time.monotonic()
        with InProcLease._lock:
            rec = InProcLease._table.get(name)
            if rec is not None and rec.owner != owner and rec.deadline > now:
                return False
            if rec is not None and rec.owner != owner and rec.deadline <= now:
                if rec.lost_cb:
                    try:
                        rec.lost_cb(None)
                    except Exception:
                        pass
            InProcLease._table[name] = _LeaseRecord(
                owner, now + self._ttl(), lease_lost_callback)
        self._start_heartbeat()
        return True

    def _start_heartbeat(self) -> None:
        self._stop_heartbeat()

        def beat():
            name, owner = self.settings.lease_name, self.settings.owner_name
            with InProcLease._lock:
                rec = InProcLease._table.get(name)
                if rec is None or rec.owner != owner:
                    return  # lost; stop beating
                rec.deadline = time.monotonic() + self._ttl()
            self._start_heartbeat()

        t = threading.Timer(self.settings.timeout.heartbeat_interval, beat)
        t.daemon = True
        t.start()
        self._heartbeat_task = t

    def _stop_heartbeat(self) -> None:
        if self._heartbeat_task is not None:
            self._heartbeat_task.cancel()
            self._heartbeat_task = None

    def release(self) -> bool:
        name, owner = self.settings.lease_name, self.settings.owner_name
        self._stop_heartbeat()
        with InProcLease._lock:
            rec = InProcLease._table.get(name)
            if rec is not None and rec.owner == owner:
                del InProcLease._table[name]
            return True

    def check_lease(self) -> bool:
        name, owner = self.settings.lease_name, self.settings.owner_name
        with InProcLease._lock:
            rec = InProcLease._table.get(name)
            return (rec is not None and rec.owner == owner
                    and rec.deadline > time.monotonic())


class FileLease(Lease):
    """Cross-PROCESS lease backed by an atomically-created lock file with a
    TTL (the single-host analogue of a Kubernetes-lease backend; used by the
    real-process SBR lease-majority tests). Layout: one JSON file per lease
    name under `FileLease.directory` holding {owner, deadline} (wall clock).

    Every read-check-write cycle (acquire, heartbeat, release) runs under
    an exclusive flock on a sibling .lock file, so contention — including
    two processes racing to take over an EXPIRED lease — has exactly one
    winner; the lease file itself is rewritten via tmp+rename (atomic)."""

    directory: str = "/tmp/akka-tpu-leases"

    def __init__(self, settings: LeaseSettings):
        super().__init__(settings)
        self._heartbeat_task: Optional[threading.Timer] = None

    def _path(self) -> str:
        import os
        import re
        os.makedirs(FileLease.directory, exist_ok=True)
        safe = re.sub(r"[^\w.-]", "_", self.settings.lease_name)
        return os.path.join(FileLease.directory, safe + ".lease")

    def _ttl(self) -> float:
        return self.settings.timeout.heartbeat_timeout

    class _flocked:
        """Exclusive advisory lock over the lease's critical sections —
        the cross-process mutex that makes read-check-write atomic."""

        def __init__(self, path: str):
            self._path = path + ".lock"
            self._f = None

        def __enter__(self):
            import fcntl
            self._f = open(self._path, "a+")  # noqa: SIM115 — held past scope
            fcntl.flock(self._f, fcntl.LOCK_EX)
            return self

        def __exit__(self, *exc):
            import fcntl
            fcntl.flock(self._f, fcntl.LOCK_UN)
            self._f.close()
            self._f = None
            return False

    def _read(self):
        import json
        try:
            with open(self._path(), "r", encoding="utf-8") as f:
                return json.loads(f.read())
        except (OSError, ValueError):
            return None

    def _write(self) -> None:
        import json
        import os
        tmp = self._path() + f".{os.getpid()}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(json.dumps({"owner": self.settings.owner_name,
                                "deadline": time.time() + self._ttl()}))
        os.replace(tmp, self._path())

    def acquire(self, lease_lost_callback=None) -> bool:
        with self._flocked(self._path()):
            rec = self._read()
            if rec is not None \
                    and rec.get("owner") != self.settings.owner_name \
                    and rec.get("deadline", 0) > time.time():
                return False  # held by a live other owner
            self._write()     # fresh, expired, or our own: (re)claim
        self._start_heartbeat()
        return True

    def _start_heartbeat(self) -> None:
        self._stop_heartbeat()

        def beat():
            with self._flocked(self._path()):
                rec = self._read()
                if rec is None or \
                        rec.get("owner") != self.settings.owner_name:
                    return  # lost; stop beating
                try:
                    self._write()
                except OSError:
                    return
            self._start_heartbeat()

        t = threading.Timer(self.settings.timeout.heartbeat_interval, beat)
        t.daemon = True
        t.start()
        self._heartbeat_task = t

    def _stop_heartbeat(self) -> None:
        if self._heartbeat_task is not None:
            self._heartbeat_task.cancel()
            self._heartbeat_task = None

    def release(self) -> bool:
        import os
        self._stop_heartbeat()
        with self._flocked(self._path()):
            rec = self._read()
            if rec is not None and \
                    rec.get("owner") == self.settings.owner_name:
                try:
                    os.unlink(self._path())
                except OSError:
                    pass
        return True

    def check_lease(self) -> bool:
        rec = self._read()
        return (rec is not None
                and rec.get("owner") == self.settings.owner_name
                and rec.get("deadline", 0) > time.time())


_LEASE_IMPLS: Dict[str, Callable[[LeaseSettings], Lease]] = {
    "in-proc": InProcLease,
    "file": FileLease,
}


def register_lease_impl(name: str, factory: Callable[[LeaseSettings], Lease]) -> None:
    """Config-style extension seam (reference: LeaseProvider loads the
    `lease-class` FQCN from config; here a registry name)."""
    _LEASE_IMPLS[name] = factory


class LeaseProvider(ExtensionId):
    """(reference: lease/scaladsl/LeaseProvider.scala:35) — per-system cache
    of (impl, lease-name, owner) -> Lease instance."""

    def create_extension(self, system: ActorSystem) -> "_LeaseProviderExt":
        return _LeaseProviderExt(system)

    @staticmethod
    def get(system: ActorSystem) -> "_LeaseProviderExt":
        return system.register_extension(LeaseProvider())


class _LeaseProviderExt:
    def __init__(self, system: ActorSystem):
        self.system = system
        self._leases: Dict[tuple, Lease] = {}
        self._lock = threading.Lock()

    def get_lease(self, lease_name: str, config_path: str,
                  owner_name: str) -> Lease:
        key = (lease_name, config_path, owner_name)
        with self._lock:
            if key not in self._leases:
                cfg = self.system.settings.config
                impl = cfg.get_string(f"{config_path}.lease-implementation",
                                      "in-proc")
                timeout = TimeoutSettings(
                    heartbeat_interval=cfg.get_duration(
                        f"{config_path}.heartbeat-interval", 0.5),
                    heartbeat_timeout=cfg.get_duration(
                        f"{config_path}.heartbeat-timeout", 5.0))
                settings = LeaseSettings(lease_name, owner_name, timeout)
                self._leases[key] = _LEASE_IMPLS[impl](settings)
            return self._leases[key]
