"""Cluster singleton: exactly-one actor cluster-wide, hosted on the oldest node.

Reference parity: akka-cluster-tools/src/main/scala/akka/cluster/singleton/
ClusterSingletonManager.scala (:176-225 — oldest-node FSM with hand-over
protocol HandOverToMe/HandOverInProgress/HandOverDone/TakeOverFromMe) and
ClusterSingletonProxy.scala (tracks the oldest member, buffers while the
singleton location is unknown, identifies via periodic probes).

The FSM here keeps the reference's state names and hand-over protocol but runs
on the host control plane (singleton moves are rare; fidelity > speed).
States: Start → Younger | Oldest; Younger → BecomingOldest → Oldest;
Oldest → WasOldest (hand-over on leave) → End.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, List, Optional

from ..actor.actor import Actor
from ..actor.props import Props
from ..cluster.cluster import Cluster
from ..cluster.events import (MemberEvent, MemberExited, MemberLeft,
                              MemberRemoved, MemberUp)
from ..cluster.member import Member, MemberStatus, UniqueAddress


# -- hand-over protocol (reference: ClusterSingletonManager.Internal) --------

@dataclass(frozen=True)
class HandOverToMe:
    pass


@dataclass(frozen=True)
class HandOverInProgress:
    pass


@dataclass(frozen=True)
class HandOverDone:
    pass


@dataclass(frozen=True)
class TakeOverFromMe:
    pass


@dataclass(frozen=True)
class _Cleanup:
    pass


@dataclass(frozen=True)
class ClusterSingletonSettings:
    """(reference: ClusterSingletonManagerSettings) — singleton name, role
    filter, hand-over retry cadence; `use_lease` guards instantiation with
    a coordination lease (ClusterSingletonManagerSettings.LeaseSettings —
    the singleton only starts while its node HOLDS the lease, so even a
    split brain cannot run two instances)."""
    singleton_name: str = "singleton"
    role: Optional[str] = None
    hand_over_retry_interval: float = 0.25
    # proxy settings
    buffer_size: int = 1000
    singleton_identification_interval: float = 0.25
    # lease guard (reference: singleton lease-implementation config)
    use_lease: bool = False
    lease_name: Optional[str] = None


class ClusterSingletonManager(Actor):
    """Runs on every node (with the configured role); hosts the singleton
    child while this node is the oldest. Spawn one per singleton name:

        system.actor_of(Props.create(ClusterSingletonManager, props, settings),
                        name="my-singleton-manager")
    """

    def __init__(self, singleton_props: Props,
                 settings: Optional[ClusterSingletonSettings] = None,
                 termination_message: Any = None):
        super().__init__()
        self.settings = settings or ClusterSingletonSettings()
        self.singleton_props = singleton_props
        self.termination_message = termination_message
        self.cluster = Cluster.get(self.context.system)
        self.state = "Start"
        self.singleton: Optional[Any] = None  # ActorRef of the child
        self._members_by_age: List[Member] = []  # oldest first
        self._hand_over_to: Optional[Any] = None  # ref of previous oldest
        self._retry_task = None

    # -- membership bookkeeping ----------------------------------------------
    def _matches_role(self, m: Member) -> bool:
        return self.settings.role is None or self.settings.role in m.roles

    def _refresh_members(self) -> None:
        ms = [m for m in self.cluster.state.members
              if m.status in (MemberStatus.UP, MemberStatus.LEAVING,
                              MemberStatus.EXITING) and self._matches_role(m)]
        ms.sort(key=lambda m: (m.up_number, m.unique_address))
        self._members_by_age = ms

    def _oldest(self) -> Optional[Member]:
        for m in self._members_by_age:
            if m.status is MemberStatus.UP:
                return m
        return self._members_by_age[0] if self._members_by_age else None

    def _self_node(self) -> Optional[UniqueAddress]:
        sm = self.cluster.self_member
        return sm.unique_address if sm else None

    def _am_oldest(self) -> bool:
        o = self._oldest()
        return o is not None and o.unique_address == self._self_node()

    def _peer_manager(self, node: UniqueAddress):
        rel = self.context.self_ref.path.to_string_without_address()
        return self.context.system.provider.resolve_actor_ref(
            f"{node.address_str}{rel}")

    # -- lifecycle -----------------------------------------------------------
    def pre_start(self) -> None:
        self.cluster.subscribe(self._on_cluster_event, MemberEvent,
                               initial_state=False)
        self._retry_task = self.context.system.scheduler.schedule_tell_with_fixed_delay(
            self.settings.hand_over_retry_interval,
            self.settings.hand_over_retry_interval,
            self.self_ref, _Cleanup())
        self.cluster.register_on_member_up(
            lambda: self.self_ref.tell(_Cleanup()))

    def post_stop(self) -> None:
        self.cluster.unsubscribe(self._on_cluster_event)
        if self._retry_task:
            self._retry_task.cancel()
        self._release_lease()

    def _on_cluster_event(self, event: Any) -> None:
        # runs on the cluster event thread; re-enter via our mailbox
        self.self_ref.tell(event)

    # -- FSM -----------------------------------------------------------------
    def receive(self, message: Any) -> Any:
        if isinstance(message, (MemberEvent, _Cleanup)):
            self._refresh_members()
            self._evaluate(message)
        elif isinstance(message, HandOverToMe):
            self._on_hand_over_to_me()
        elif isinstance(message, HandOverInProgress):
            pass  # previous oldest acknowledged; keep waiting for HandOverDone
        elif isinstance(message, HandOverDone):
            if self.state == "BecomingOldest":
                self._become_oldest()
        elif isinstance(message, TakeOverFromMe):
            # previous oldest offers hand-over proactively
            if self.state in ("Younger", "BecomingOldest") and self._am_oldest():
                self.state = "BecomingOldest"
                self.sender.tell(HandOverToMe(), self.self_ref)
        else:
            return NotImplemented

    def _evaluate(self, event: Any) -> None:
        self_node = self._self_node()
        if self_node is None:
            return
        sm = self.cluster.self_member
        leaving = sm is not None and sm.status in (
            MemberStatus.LEAVING, MemberStatus.EXITING)

        if self.state == "Start":
            if sm is None or sm.status is not MemberStatus.UP:
                return
            if self._am_oldest():
                self._become_oldest()
            else:
                self.state = "Younger"
        elif self.state == "Younger":
            if self._am_oldest() and not leaving:
                # previous oldest gone or leaving: hand-over or direct takeover
                prev = self._previous_oldest_gone(event)
                if prev is None:
                    self._become_oldest()  # previous oldest fully removed
                else:
                    self.state = "BecomingOldest"
                    self._peer_manager(prev).tell(HandOverToMe(), self.self_ref)
        elif self.state == "BecomingOldest":
            prev = self._previous_oldest_gone(event)
            if prev is None:
                self._become_oldest()
            elif isinstance(event, _Cleanup):
                self._peer_manager(prev).tell(HandOverToMe(), self.self_ref)
        elif self.state == "Oldest":
            if self.settings.use_lease and isinstance(event, _Cleanup) \
                    and getattr(self, "_lease", None) is not None \
                    and not self._lease.check_lease():
                # lease LOST while running (TTL expired during a stall —
                # another node may already be instantiating): stop our
                # instance immediately and re-race for the lease
                if self.singleton is not None:
                    self.context.stop(self.singleton)
                    self.singleton = None
                self.state = "BecomingOldest"
                return
            if leaving or not self._am_oldest():
                self.state = "WasOldest"
                new = self._oldest()
                if new is not None and new.unique_address != self_node:
                    self._peer_manager(new.unique_address).tell(
                        TakeOverFromMe(), self.self_ref)
        elif self.state == "WasOldest":
            new = self._oldest()
            if isinstance(event, _Cleanup) and new is not None \
                    and new.unique_address != self_node:
                self._peer_manager(new.unique_address).tell(
                    TakeOverFromMe(), self.self_ref)

    def _previous_oldest_gone(self, event: Any) -> Optional[UniqueAddress]:
        """The node we must hand over from: the oldest *other* known member
        that is Leaving/Exiting, or None if no such node remains."""
        self_node = self._self_node()
        for m in self._members_by_age:
            if m.unique_address != self_node and m.status in (
                    MemberStatus.LEAVING, MemberStatus.EXITING):
                return m.unique_address
        return None

    def _acquire_lease(self) -> bool:
        """Take (or confirm) the singleton lease; False defers instantiation
        to the next retry tick (the reference's AcquiringLease state)."""
        if not self.settings.use_lease:
            return True
        if getattr(self, "_lease", None) is None:
            from .lease import LeaseProvider
            name = self.settings.lease_name or (
                f"{self.context.system.name}-singleton-"
                f"{self.settings.singleton_name}")
            self._lease = LeaseProvider.get(self.context.system).get_lease(
                name, "akka.cluster.singleton.lease",
                str(self._self_node()))
        return self._lease.acquire()

    def _release_lease(self) -> None:
        lease = getattr(self, "_lease", None)
        if lease is not None:
            lease.release()

    def _become_oldest(self) -> None:
        if not self._acquire_lease():
            # stay in BecomingOldest: the _Cleanup retry tick re-evaluates
            # and re-attempts the acquire until the holder releases/expires
            self.state = "BecomingOldest"
            return
        self.state = "Oldest"
        if self.singleton is None:
            self.singleton = self.context.actor_of(
                self.singleton_props, self.settings.singleton_name)

    def _on_hand_over_to_me(self) -> None:
        """New oldest asks us to stop the singleton and confirm."""
        requester = self.sender
        if self.state == "HandingOver":
            # retried request while the old instance is still stopping: must
            # NOT ack done yet (two live singletons otherwise); re-confirm
            # in-progress and ack the latest requester on termination
            self._pending_handover_ack = requester
            requester.tell(HandOverInProgress(), self.self_ref)
            return
        if self.state in ("Oldest", "WasOldest") and self.singleton is not None:
            self.state = "HandingOver"
            requester.tell(HandOverInProgress(), self.self_ref)
            singleton, self.singleton = self.singleton, None
            self.context.watch(singleton)
            self._pending_handover_ack = requester
            if self.termination_message is not None:
                singleton.tell(self.termination_message, self.self_ref)
            else:
                self.context.stop(singleton)
        elif self.singleton is None:
            # nothing to hand over (already stopped or never had it)
            requester.tell(HandOverDone(), self.self_ref)
            if self.state in ("Oldest", "WasOldest", "HandingOver"):
                self.state = "End"

    def around_receive(self, receive, msg) -> None:
        from ..actor.messages import Terminated
        if isinstance(msg, Terminated):
            ack = getattr(self, "_pending_handover_ack", None)
            if self.state == "HandingOver" and ack is not None:
                ack.tell(HandOverDone(), self.self_ref)
                self._pending_handover_ack = None
                self.state = "End"
                # the instance is gone: free the lease so the new oldest's
                # acquire succeeds immediately
                self._release_lease()
            return
        super().around_receive(receive, msg)


@dataclass(frozen=True)
class _TryToIdentify:
    pass


class ClusterSingletonProxy(Actor):
    """Location-transparent ref to the singleton: tracks the oldest member,
    buffers messages until the singleton is CONFIRMED alive via Identify
    probing — blind sends during a hand-over would land in dead letters
    (reference: ClusterSingletonProxy.scala identifyInterval + buffer)."""

    def __init__(self, manager_path: str,
                 settings: Optional[ClusterSingletonSettings] = None):
        super().__init__()
        self.settings = settings or ClusterSingletonSettings()
        # path of the manager actor relative to root, e.g. "/user/my-manager"
        self.manager_path = manager_path if manager_path.startswith("/") \
            else "/" + manager_path
        self.cluster = Cluster.get(self.context.system)
        self.buffer: List[tuple] = []
        self.singleton = None       # confirmed-live ref
        self._identify_id = 0
        self._task = None

    def pre_start(self) -> None:
        self.cluster.subscribe(self._on_cluster_event, MemberEvent,
                               initial_state=False)
        self._task = self.context.system.scheduler.schedule_tell_with_fixed_delay(
            0.0, self.settings.singleton_identification_interval,
            self.self_ref, _TryToIdentify())

    def post_stop(self) -> None:
        self.cluster.unsubscribe(self._on_cluster_event)
        if self._task:
            self._task.cancel()

    def _on_cluster_event(self, event: Any) -> None:
        self.self_ref.tell(event)

    def _matches_role(self, m: Member) -> bool:
        return self.settings.role is None or self.settings.role in m.roles

    def _singleton_path(self) -> Optional[str]:
        ms = [m for m in self.cluster.state.members
              if m.status is MemberStatus.UP and self._matches_role(m)]
        if not ms:
            return None
        oldest = min(ms, key=lambda m: (m.up_number, m.unique_address))
        return (f"{oldest.unique_address.address_str}{self.manager_path}/"
                f"{self.settings.singleton_name}")

    def _identify(self) -> None:
        from ..actor.messages import Identify
        path = self._singleton_path()
        if path is None:
            return
        self._identify_id += 1
        ref = self.context.system.provider.resolve_actor_ref(path)
        ref.tell(Identify((self._identify_id, path)), self.self_ref)

    def receive(self, message: Any) -> Any:
        from ..actor.messages import ActorIdentity, Terminated
        if isinstance(message, MemberEvent):
            # topology changed: the singleton may have moved — re-confirm
            self.singleton = None
            self._identify()
        elif isinstance(message, _TryToIdentify):
            if self.singleton is None:
                self._identify()
        elif isinstance(message, ActorIdentity):
            if message.ref is not None and message.correlation_id[0] == self._identify_id:
                self.singleton = message.ref
                self.context.watch(self.singleton)
                self._flush()
        elif isinstance(message, Terminated):
            if self.singleton is not None and message.actor == self.singleton:
                self.singleton = None
                self._identify()
        else:
            if self.singleton is not None:
                self.singleton.tell(message, self.sender)
            else:
                if len(self.buffer) >= self.settings.buffer_size:
                    self.buffer.pop(0)  # drop oldest (reference logs + drops)
                self.buffer.append((message, self.sender))
                self._identify()

    def _flush(self) -> None:
        buffered, self.buffer = self.buffer, []
        for msg, snd in buffered:
            self.singleton.tell(msg, snd)
