"""ClusterClient: an actor system OUTSIDE the cluster talking to services
inside it through a receptionist.

Reference parity: akka-cluster-tools/src/main/scala/akka/cluster/client/
ClusterClient.scala:287 (the client FSM: establish contact from
initial-contacts, buffer while connecting, forward Send/SendToAll/Publish)
and ClusterReceptionist (the cluster-side endpoint delegating into the
DistributedPubSub mediator; services are exposed with
ClusterClientReceptionist.registerService).

The client's system uses `provider = remote` — it is NOT a cluster member;
only the receptionist endpoints need to be reachable.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

from ..actor.actor import Actor
from ..actor.props import Props
from ..actor.system import ActorSystem, ExtensionId
from . import pubsub as _ps


# -- protocol (reference: ClusterClientMessages) ------------------------------

@dataclass(frozen=True)
class Send:
    """Deliver to ONE actor registered at `path` (mediator Send routing)."""
    path: str
    msg: Any
    local_affinity: bool = False


@dataclass(frozen=True)
class SendToAll:
    path: str
    msg: Any


@dataclass(frozen=True)
class Publish:
    topic: str
    msg: Any


@dataclass(frozen=True)
class GetContacts:
    pass


@dataclass(frozen=True)
class Contacts:
    """Receptionist addresses the client may (re)connect to."""
    contact_points: Tuple[str, ...]


RECEPTIONIST_NAME = "cluster-client-receptionist"


class ClusterReceptionistActor(Actor):
    """Cluster-side endpoint (reference: client/ClusterReceptionist): hands
    out contact points and forwards client traffic into the pub-sub
    mediator, preserving the ORIGINAL client as sender so replies flow
    straight back over remoting."""

    def __init__(self):
        super().__init__()
        self._mediator = None

    def pre_start(self) -> None:
        self._mediator = _ps.DistributedPubSub.get(
            self.context.system).mediator

    def receive(self, message: Any):
        if isinstance(message, GetContacts):
            from ..cluster import Cluster
            from ..cluster.member import MemberStatus
            cluster = Cluster.get(self.context.system)
            state = cluster.state
            # advertise only LIVE endpoints: Up/WeaklyUp and reachable —
            # handing out a Down node's path would make the client burn
            # its re-establish ticks on a dead receptionist
            points = tuple(
                f"{m.address_str}/system/{RECEPTIONIST_NAME}"
                for m in state.members
                if m.status in (MemberStatus.UP, MemberStatus.WEAKLY_UP)
                and m not in state.unreachable)
            self.sender.tell(Contacts(points or (
                f"{cluster.self_unique_address.address_str}"
                f"/system/{RECEPTIONIST_NAME}",)), self.self_ref)
        elif isinstance(message, Send):
            self._mediator.tell(
                _ps.Send(message.path, message.msg,
                         local_affinity=message.local_affinity), self.sender)
        elif isinstance(message, SendToAll):
            self._mediator.tell(_ps.SendToAll(message.path, message.msg),
                                self.sender)
        elif isinstance(message, Publish):
            self._mediator.tell(_ps.Publish(message.topic, message.msg),
                                self.sender)
        else:
            return NotImplemented
        return None


class ClusterClientReceptionist(ExtensionId):
    """Cluster-side extension: starts the receptionist endpoint and exposes
    registerService (reference: ClusterClientReceptionist.registerService —
    a Put into the mediator so Send-by-path resolves)."""

    def create_extension(self, system: ActorSystem):
        return _ReceptionistExt(system)

    @staticmethod
    def get(system: ActorSystem) -> "_ReceptionistExt":
        return system.register_extension(ClusterClientReceptionist())


class _ReceptionistExt:
    def __init__(self, system: ActorSystem):
        self.system = system
        self.underlying = system.system_actor_of(
            Props.create(ClusterReceptionistActor), RECEPTIONIST_NAME)

    def register_service(self, service) -> None:
        _ps.DistributedPubSub.get(self.system).mediator.tell(
            _ps.Put(service), None)

    def register_subscriber(self, topic: str, subscriber) -> None:
        _ps.DistributedPubSub.get(self.system).mediator.tell(
            _ps.Subscribe(topic, subscriber), subscriber)


@dataclass
class ClusterClientSettings:
    """(reference: ClusterClientSettings) — initial receptionist addresses
    as `akka://sys@host:port` strings."""
    initial_contacts: Tuple[str, ...]
    establishing_get_contacts_interval: float = 0.5
    buffer_size: int = 1024


class ClusterClient(Actor):
    """The client actor (reference: ClusterClient.scala:287): send it
    Send/SendToAll/Publish; it buffers until a receptionist is established
    and re-establishes (round-robining contacts) when the connection's
    node dies."""

    class _Reconnect:
        pass

    def __init__(self, settings: ClusterClientSettings):
        super().__init__()
        if not settings.initial_contacts:
            raise ValueError("initial_contacts must not be empty")
        self.settings = settings
        self._receptionist = None          # established endpoint ref
        self._buffer: List[Tuple[Any, Any]] = []
        self._task = None
        self._contacts: Tuple[str, ...] = tuple(settings.initial_contacts)

    def _contact_refs(self):
        out = []
        for addr in self._contacts:
            path = addr if "/system/" in addr else \
                f"{addr}/system/{RECEPTIONIST_NAME}"
            out.append(self.context.system.provider.resolve_actor_ref(path))
        return out

    def pre_start(self) -> None:
        self._task = self.context.system.scheduler.schedule_tell_with_fixed_delay(
            0.0, self.settings.establishing_get_contacts_interval,
            self.self_ref, self._Reconnect())

    def post_stop(self) -> None:
        if self._task is not None:
            self._task.cancel()

    def receive(self, message: Any):
        from ..actor.messages import Terminated

        if isinstance(message, self._Reconnect):
            if self._receptionist is None:
                for ref in self._contact_refs():
                    ref.tell(GetContacts(), self.self_ref)
            else:
                # refresh contacts while ESTABLISHED too: the cluster may
                # roll its membership completely — a client frozen on its
                # first Contacts reply could be left with an all-dead list
                # and never re-establish (reference: periodic
                # HeartbeatTick/contacts refresh)
                self._receptionist.tell(GetContacts(), self.self_ref)
        elif isinstance(message, Contacts):
            if message.contact_points:
                self._contacts = message.contact_points
            if self._receptionist is None:
                self._receptionist = self.sender
                self.context.watch(self._receptionist)
                for msg, snd in self._buffer:
                    self._receptionist.tell(msg, snd)
                self._buffer.clear()
        elif isinstance(message, Terminated):
            if self._receptionist is not None and \
                    message.actor.path == self._receptionist.path:
                self._receptionist = None  # re-establish on next tick
        elif isinstance(message, (Send, SendToAll, Publish)):
            if self._receptionist is not None:
                self._receptionist.tell(message, self.sender)
            else:
                self._buffer.append((message, self.sender))
                if len(self._buffer) > self.settings.buffer_size:
                    # full: evict the OLDEST (the reference drops the first
                    # buffered message, keeping the freshest traffic) and
                    # make the loss VISIBLE via dead letters
                    from ..actor.messages import DeadLetter
                    old_msg, old_snd = self._buffer.pop(0)
                    self.context.system.dead_letters.tell(
                        DeadLetter(old_msg, old_snd, self.self_ref), old_snd)
        else:
            return NotImplemented
        return None
