"""Dispatchers: bind actors to executors; the executeMailbox hot path.

Reference parity: akka-actor/src/main/scala/akka/dispatch/Dispatcher.scala
(`dispatch` = enqueue + registerForExecution :61-65; the CAS-schedule
:120-143) and AbstractDispatcher.scala (attach/detach/inhabitants :95-327).
PinnedDispatcher (dispatch/PinnedDispatcher.scala) dedicates one thread per
actor. CallingThreadDispatcher (testkit) runs receive on the caller's thread
for deterministic tests (akka-testkit/.../CallingThreadDispatcher.scala).

On TPU the real hot path bypasses all of this — see batched.py — but host
actors (IO, control plane, cluster daemons) run here.
"""

from __future__ import annotations

import os
import threading
import weakref
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Optional

from .mailbox import (AtomicInt, Envelope, Mailbox, Mailboxes, MailboxType,
                      UnboundedMailbox)
from . import sysmsg


class MessageDispatcher:
    """Base: lifecycle accounting + the dispatch contract
    (reference: dispatch/AbstractDispatcher.scala:95-327)."""

    def __init__(self, dispatchers: "Any", id: str, throughput: int = 64,
                 throughput_deadline: float = 0.0, shutdown_timeout: float = 1.0):
        self.dispatchers = dispatchers
        self.id = id
        self.throughput = throughput
        self.throughput_deadline = throughput_deadline
        self.shutdown_timeout = shutdown_timeout
        self._inhabitants = AtomicInt(0)
        self._shutdown_lock = threading.Lock()

    # -- attach/detach ------------------------------------------------------
    def attach(self, cell) -> None:
        self.register(cell)
        self.register_for_execution(cell.mailbox, False, True)

    def detach(self, cell) -> None:
        try:
            self.unregister(cell)
        finally:
            self.if_sensible_to_do_something_do_it()

    def register(self, cell) -> None:
        self._inhabitants.get_and_add(1)

    def unregister(self, cell) -> None:
        self._inhabitants.get_and_add(-1)
        mailbox = cell.swap_mailbox(None)
        if mailbox is not None:
            mailbox.become_closed()
            mailbox.clean_up()

    def if_sensible_to_do_something_do_it(self) -> None:
        pass

    @property
    def inhabitants(self) -> int:
        return self._inhabitants.get()

    # -- the dispatch contract ----------------------------------------------
    def create_mailbox(self, cell, mailbox_type: MailboxType) -> Mailbox:
        mb = Mailbox(mailbox_type.create(cell.self_ref, cell.system))
        mb.dispatcher = self
        return mb

    def dispatch(self, cell, envelope: Envelope) -> None:
        mbox = cell.mailbox
        mbox.enqueue(cell.self_ref, envelope)
        self.register_for_execution(mbox, True, False)

    def system_dispatch(self, cell, message: sysmsg.SystemMessage) -> None:
        mbox = cell.mailbox
        mbox.system_enqueue(cell.self_ref, message)
        self.register_for_execution(mbox, False, True)

    def register_for_execution(self, mbox: Optional[Mailbox], has_message_hint: bool,
                               has_system_message_hint: bool) -> bool:
        raise NotImplementedError

    def execute(self, fn) -> None:
        """Run an arbitrary task on this dispatcher's executor."""
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


class Dispatcher(MessageDispatcher):
    """Event-based dispatcher over a shared thread pool
    (reference: dispatch/Dispatcher.scala)."""

    def __init__(self, dispatchers, id: str, throughput: int = 64,
                 throughput_deadline: float = 0.0, shutdown_timeout: float = 1.0,
                 pool_size: int = 0, executor: Optional[ThreadPoolExecutor] = None):
        super().__init__(dispatchers, id, throughput, throughput_deadline, shutdown_timeout)
        workers = pool_size or min(32, (os.cpu_count() or 4))
        self._executor = executor or ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix=f"akka-tpu-{id}")
        self._owns_executor = executor is None

    def register_for_execution(self, mbox, has_message_hint, has_system_message_hint) -> bool:
        if mbox is None:
            return False
        if mbox.can_be_scheduled_for_execution(has_message_hint, has_system_message_hint):
            if mbox.set_as_scheduled():
                try:
                    self._executor.submit(mbox.run)
                    return True
                except RuntimeError:
                    mbox.set_as_idle()
                    return False
        return False

    def execute(self, fn) -> None:
        self._executor.submit(fn)

    def shutdown(self) -> None:
        if self._owns_executor:
            self._executor.shutdown(wait=False, cancel_futures=True)


class PinnedDispatcher(Dispatcher):
    """One dedicated thread per actor (reference: dispatch/PinnedDispatcher.scala)."""

    def __init__(self, dispatchers, id: str, throughput: int = 1,
                 shutdown_timeout: float = 1.0):
        super().__init__(dispatchers, id, throughput=throughput,
                         shutdown_timeout=shutdown_timeout,
                         executor=ThreadPoolExecutor(max_workers=1,
                                                     thread_name_prefix=f"akka-tpu-pinned-{id}"))
        self._owns_executor = True


class CallingThreadDispatcher(MessageDispatcher):
    """Processes the mailbox synchronously on the sending thread — the
    deterministic-test dispatcher (reference: akka-testkit
    CallingThreadDispatcher.scala). Reentrant sends are queued and drained
    iteratively to avoid unbounded recursion."""

    def __init__(self, dispatchers=None, id: str = "calling-thread-dispatcher"):
        super().__init__(dispatchers, id, throughput=1)
        self._draining = threading.local()

    def register_for_execution(self, mbox, has_message_hint, has_system_message_hint) -> bool:
        if mbox is None:
            return False
        if getattr(self._draining, "active", False):
            # already draining higher up the stack; outer loop will pick it up
            self._draining.pending.append(mbox)
            return True
        self._draining.active = True
        self._draining.pending = [mbox]
        try:
            while self._draining.pending:
                m = self._draining.pending.pop(0)
                if m.can_be_scheduled_for_execution(True, True) and m.set_as_scheduled():
                    m.run()
        finally:
            self._draining.active = False
        return True

    def execute(self, fn) -> None:
        fn()


class DispatcherConfigurator:
    """Config section -> dispatcher instance
    (reference: MessageDispatcherConfigurator, AbstractDispatcher.scala:338-381)."""

    def __init__(self, config, dispatchers):
        self.config = config
        self.dispatchers = dispatchers

    def dispatcher(self) -> MessageDispatcher:
        raise NotImplementedError


class _StdDispatcherConfigurator(DispatcherConfigurator):
    def __init__(self, config, dispatchers, id: str):
        super().__init__(config, dispatchers)
        self.id = id
        self._instance: Optional[Dispatcher] = None
        self._lock = threading.Lock()

    def dispatcher(self) -> MessageDispatcher:
        with self._lock:
            if self._instance is None:
                c = self.config
                self._instance = Dispatcher(
                    self.dispatchers, self.id,
                    throughput=c.get_int("throughput", 64),
                    throughput_deadline=c.get_duration("throughput-deadline-time", 0.0),
                    shutdown_timeout=c.get_duration("shutdown-timeout", "1s"),
                    pool_size=c.get_int("thread-pool-executor.fixed-pool-size", 0),
                )
            return self._instance


class _PinnedDispatcherConfigurator(DispatcherConfigurator):
    def __init__(self, config, dispatchers, id: str):
        super().__init__(config, dispatchers)
        self.id = id
        self._instances: list[PinnedDispatcher] = []
        self._lock = threading.Lock()

    def dispatcher(self) -> MessageDispatcher:
        # a new pinned dispatcher per lookup (one per actor)
        d = PinnedDispatcher(self.dispatchers, self.id,
                             shutdown_timeout=self.config.get_duration("shutdown-timeout", "1s"))
        with self._lock:
            self._instances.append(d)
        return d

    def shutdown_all(self) -> None:
        with self._lock:
            for d in self._instances:
                d.shutdown()
            self._instances.clear()


class _CallingThreadDispatcherConfigurator(DispatcherConfigurator):
    def __init__(self, config, dispatchers, id: str):
        super().__init__(config, dispatchers)
        self.id = id
        self._instance = CallingThreadDispatcher(dispatchers, id)

    def dispatcher(self) -> MessageDispatcher:
        return self._instance


class Dispatchers:
    """THE extension point: config-driven dispatcher lookup by id, with a
    `type` string selecting the backend and runtime registration of custom
    configurators (reference: dispatch/Dispatchers.scala:121,184-185,235-259).
    The `tpu-batched` type (registered by akka_tpu.dispatch.batched) is the
    flagship backend per BASELINE.json."""

    DEFAULT_DISPATCHER_ID = "akka.actor.default-dispatcher"
    INTERNAL_DISPATCHER_ID = "akka.actor.internal-dispatcher"

    def __init__(self, settings, system: Any = None):
        self.settings = settings
        self.system = weakref.proxy(system) if system is not None else None
        self._configurators: dict[str, DispatcherConfigurator] = {}
        self._type_factories: dict[str, Any] = {}
        self._lock = threading.Lock()
        self.register_type("Dispatcher", _StdDispatcherConfigurator)
        self.register_type("PinnedDispatcher", _PinnedDispatcherConfigurator)
        self.register_type("CallingThreadDispatcher", _CallingThreadDispatcherConfigurator)

    def register_type(self, type_name: str, factory) -> None:
        """factory(config, dispatchers, id) -> DispatcherConfigurator"""
        self._type_factories[type_name] = factory

    def register_configurator(self, id: str, configurator: DispatcherConfigurator) -> bool:
        with self._lock:
            if id in self._configurators:
                return False
            self._configurators[id] = configurator
            return True

    def has_dispatcher(self, id: str) -> bool:
        return id in self._configurators or self.settings.config.has_path(id)

    def lookup(self, id: str) -> MessageDispatcher:
        return self._lookup_configurator(id).dispatcher()

    def _lookup_configurator(self, id: str) -> DispatcherConfigurator:
        with self._lock:
            c = self._configurators.get(id)
            if c is not None:
                return c
            cfg = self.settings.config.get_config(id)
            type_name = cfg.get_string("type", "Dispatcher")
            factory = self._type_factories.get(type_name)
            if factory is None:
                raise KeyError(f"unknown dispatcher type [{type_name}] for id [{id}]; "
                               f"registered: {sorted(self._type_factories)}")
            c = factory(cfg, self, id)
            self._configurators[id] = c
            return c

    @property
    def default_global_dispatcher(self) -> MessageDispatcher:
        return self.lookup(self.DEFAULT_DISPATCHER_ID)

    @property
    def internal_dispatcher(self) -> MessageDispatcher:
        return self.lookup(self.INTERNAL_DISPATCHER_ID)

    def shutdown(self) -> None:
        with self._lock:
            for c in self._configurators.values():
                inst = getattr(c, "_instance", None)
                if inst is not None:
                    inst.shutdown()
                if hasattr(c, "shutdown_all"):
                    c.shutdown_all()
