"""System messages — the out-of-band control plane with guaranteed delivery.

Reference parity: akka-actor/src/main/scala/akka/dispatch/sysmsg/SystemMessage.scala:220-273
(Create/Recreate/Suspend/Resume/Terminate/Supervise/Watch/Unwatch/Failed/
DeathWatchNotification/NoMessage). System messages bypass the user mailbox and
are processed before user messages on every mailbox run
(dispatch/Mailbox.scala:227-237).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional


class SystemMessage:
    """Base class. Instances are single-use and owned by exactly one queue —
    the reference's 'NEVER SEND THE SAME SYSTEM MESSAGE OBJECT TO TWO ACTORS'
    invariant (actor/dungeon/Dispatch.scala:92-97)."""

    __slots__ = ()


@dataclass
class Create(SystemMessage):
    failure: Optional[BaseException] = None


@dataclass
class Recreate(SystemMessage):
    cause: Optional[BaseException] = None


@dataclass
class Suspend(SystemMessage):
    pass


@dataclass
class Resume(SystemMessage):
    caused_by_failure: Optional[BaseException] = None


@dataclass
class Terminate(SystemMessage):
    pass


@dataclass
class Supervise(SystemMessage):
    child: Any = None  # ActorRef
    asynchronous: bool = True


@dataclass
class Watch(SystemMessage):
    watchee: Any = None  # InternalActorRef
    watcher: Any = None


@dataclass
class Unwatch(SystemMessage):
    watchee: Any = None
    watcher: Any = None


@dataclass
class Failed(SystemMessage):
    child: Any = None
    cause: Optional[BaseException] = None
    uid: int = 0


@dataclass
class DeathWatchNotification(SystemMessage):
    actor: Any = None
    existence_confirmed: bool = True
    address_terminated: bool = False
    cause: Optional[BaseException] = None  # set when death was a failure


@dataclass
class NoMessage(SystemMessage):
    pass
